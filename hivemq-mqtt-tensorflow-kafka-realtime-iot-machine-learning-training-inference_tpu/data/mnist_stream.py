"""MNIST-over-the-broker smoke test data path.

The reference's ingestion smoke test produces raw MNIST bytes onto two
topics — images on `xx`, labels on `yy` — then consumes both with
`KafkaDataset`, `decode_raw`s them back into tensors, zips and trains a
small Dense classifier (reference `confluent-tensorflow-io-kafka.py:5-58`).
The point is isolating ingestion bugs from model bugs (the no-broker
control model is `models.mnist.MNISTBaseline`).

Byte format parity: one message per example; the image message is the 784
raw uint8 pixels (`.tobytes()`/`decode_raw(..., tf.uint8)` round-trip), the
label message is a single uint8.

The real MNIST files can't be downloaded in hermetic environments, so
`synth_mnist` generates MNIST-shaped data with learnable class structure
(a fixed random prototype per digit + pixel noise): the smoke test's
training curve still has to fall, which is what it exists to check.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Optional, Tuple

import numpy as np

from ..stream.broker import Broker
from ..stream.consumer import StreamConsumer
from .dataset import Batch


def synth_mnist(n: int = 2000, seed: int = 0,
                image_shape: Tuple[int, int] = (28, 28)):
    """(images uint8 [n,28,28], labels uint8 [n]) with class structure."""
    rng = np.random.default_rng(seed)
    protos = rng.integers(0, 256, (10,) + image_shape, dtype=np.uint8)
    labels = rng.integers(0, 10, n).astype(np.uint8)
    noise = rng.integers(-40, 41, (n,) + image_shape)
    images = np.clip(protos[labels].astype(np.int16) + noise, 0, 255) \
        .astype(np.uint8)
    return images, labels


def load_mnist_idx(images_path: str, labels_path: str):
    """Read the classic IDX files when they are available locally."""
    with open(images_path, "rb") as fh:
        magic, n, rows, cols = struct.unpack(">IIII", fh.read(16))
        if magic != 2051:
            raise ValueError(f"bad IDX image magic {magic}")
        images = np.frombuffer(fh.read(), np.uint8).reshape(n, rows, cols)
    with open(labels_path, "rb") as fh:
        magic, n2 = struct.unpack(">II", fh.read(8))
        if magic != 2049:
            raise ValueError(f"bad IDX label magic {magic}")
        labels = np.frombuffer(fh.read(), np.uint8)
    if n != n2:
        raise ValueError(f"image/label count mismatch {n} != {n2}")
    return images, labels


def produce_mnist(broker: Broker, images: np.ndarray, labels: np.ndarray,
                  image_topic: str = "xx", label_topic: str = "yy") -> int:
    """Producer parity: raw pixel bytes on `xx`, one-byte labels on `yy`
    (confluent-tensorflow-io-kafka.py:5-18)."""
    broker.create_topic(image_topic)
    broker.create_topic(label_topic)
    for img, lab in zip(images, labels):
        broker.produce(image_topic, img.tobytes())
        broker.produce(label_topic, bytes([int(lab)]))
    return len(images)


class MnistBatches:
    """Zip the image and label topics into fixed-shape supervised batches.

    Mirrors the reference's `tf.data.Dataset.zip((dataset, dataset_label))
    .batch(batch_size)`; message i on `xx` pairs with message i on `yy` by
    offset — the ingestion invariant this smoke test exists to validate.
    """

    def __init__(self, broker: Broker, batch_size: int = 32,
                 image_topic: str = "xx", label_topic: str = "yy",
                 image_shape: Tuple[int, int] = (28, 28),
                 take: Optional[int] = None):
        self.broker = broker
        self.batch_size = batch_size
        self.image_topic = image_topic
        self.label_topic = label_topic
        self.image_shape = image_shape
        self.take = take

    def __iter__(self) -> Iterator[Batch]:
        xs = StreamConsumer(self.broker, [f"{self.image_topic}:0:0"],
                            group="mnist-x")
        ys = StreamConsumer(self.broker, [f"{self.label_topic}:0:0"],
                            group="mnist-y")
        emitted_batches = 0
        flat = int(np.prod(self.image_shape))
        buf_x, buf_y = [], []
        while True:
            mx = xs.poll(1024)
            my = ys.poll(1024)
            if not mx and not my:
                break
            for ix, iy in zip(mx, my):
                if ix.offset != iy.offset:
                    raise ValueError(
                        f"image/label stream misaligned: {ix.offset} vs "
                        f"{iy.offset}")
                img = np.frombuffer(ix.value, np.uint8)
                if img.size != flat:
                    raise ValueError(f"image message has {img.size} bytes, "
                                     f"expected {flat}")
                buf_x.append(img.reshape(self.image_shape))
                buf_y.append(iy.value[0])
                if len(buf_x) == self.batch_size:
                    yield Batch(x=np.stack(buf_x).astype(np.float32),
                                n_valid=self.batch_size,
                                first_index=emitted_batches * self.batch_size,
                                y=np.asarray(buf_y, np.int32))
                    emitted_batches += 1
                    buf_x, buf_y = [], []
                    if self.take and emitted_batches >= self.take:
                        return
        if buf_x:
            n_valid = len(buf_x)
            pad = self.batch_size - n_valid
            x = np.concatenate([np.stack(buf_x).astype(np.float32),
                                np.zeros((pad,) + self.image_shape, np.float32)])
            y = np.concatenate([np.asarray(buf_y, np.int32),
                                np.zeros((pad,), np.int32)])
            yield Batch(x=x, n_valid=n_valid,
                        first_index=emitted_batches * self.batch_size, y=y)

    def epochs(self, n: int):
        for _ in range(n):
            yield iter(self)
