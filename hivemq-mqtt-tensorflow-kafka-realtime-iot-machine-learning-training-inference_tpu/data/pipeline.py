"""Columnar decode ring + the pipeline's process-level knobs.

The tf.data-shaped half of the zero-copy data plane (PAPERS: *tf.data*,
*Optimizing High-Throughput Distributed Data Pipelines*): raw frame
batches (`Broker.fetch_raw` / wire RAW_FETCH) are decoded by the native
`FrameDecoder` straight into a SMALL RING of reusable preallocated
column buffers — float32 numeric, fixed-stride labels and keys — so the
steady state allocates nothing per record and nothing per chunk beyond
the normalized output block.  Decode runs on whatever thread drains the
batch iterator (under `DevicePrefetcher` that is the staging thread),
`jax.device_put` stays on the consumer thread, and the device step
overlaps both — the same overlap discipline `data/prefetch.py`
documents.

Knobs (process-level env toggles, in ``config.non_config`` like
``IOTML_TRACE``; a malformed value fails loudly, the config system's
contract):

  IOTML_PREFETCH_DEPTH       DevicePrefetcher queue depth (default 2 —
                             classic double buffering)
  IOTML_DECODE_RING_BUFFERS  slots in the decode ring (default 4; min 2
                             so decode N+1 never overwrites a chunk the
                             batcher still views)
  IOTML_RAW_BATCH_BYTES      max bytes per raw frame fetch (default
                             1 MiB — one disk/wire read per decode call)
  IOTML_RAW_PRODUCE          write-path plane selector: ``auto`` (the
                             default — RAW_PRODUCE where the broker
                             supports it, classic PRODUCE fallback
                             pinned on UNSUPPORTED_VERSION), ``on``
                             (raw required: an extension-less server is
                             an error, the CI-parity mode) or ``off``
                             (classic everywhere, the debug escape
                             hatch — also disables the broker's durable
                             framing fusion)
  IOTML_PRODUCE_BATCH_BYTES  max frame bytes per RAW_PRODUCE request
                             (default 1 MiB); bigger accumulations are
                             split at frame boundaries
  IOTML_MESH_DATA            data-axis size for the multi-chip streaming
                             trainer (parallel.streaming): 0 (default) =
                             single-chip legacy path; N >= 2 builds an
                             N-device data mesh with partition-parallel
                             feeds (cli.live train / cluster up)
  IOTML_DEVICE_NORMALIZE     1 = fold the affine normalization into the
                             jitted step (host ships raw columns);
                             0 (default) = host-side normalization.
                             Needs a mesh (the fold lives in the
                             sharded step)
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

_DEFAULTS = {
    "IOTML_PREFETCH_DEPTH": (2, 1),
    "IOTML_DECODE_RING_BUFFERS": (4, 2),
    "IOTML_RAW_BATCH_BYTES": (1 << 20, 4096),
    "IOTML_PRODUCE_BATCH_BYTES": (1 << 20, 4096),
    "IOTML_MESH_DATA": (0, 0),
}

_RAW_PRODUCE_MODES = ("auto", "on", "off")


def _env_int(name: str) -> int:
    default, lo = _DEFAULTS[name]
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = int(raw)
    except ValueError as e:
        raise ValueError(f"env {name}={raw!r}: expected an integer "
                         f"(>= {lo})") from e
    if value < lo:
        raise ValueError(f"env {name}={value}: must be >= {lo} "
                         f"({'prefetch depth 0 would be UNBOUNDED, ' if 'PREFETCH' in name else ''}"
                         f"see data/pipeline.py)")
    return value


def prefetch_depth() -> int:
    """DevicePrefetcher queue depth (IOTML_PREFETCH_DEPTH, default 2)."""
    return _env_int("IOTML_PREFETCH_DEPTH")


def decode_ring_buffers() -> int:
    """Decode-ring slot count (IOTML_DECODE_RING_BUFFERS, default 4)."""
    return _env_int("IOTML_DECODE_RING_BUFFERS")


def raw_batch_bytes() -> int:
    """Max bytes per raw frame fetch (IOTML_RAW_BATCH_BYTES, 1 MiB)."""
    return _env_int("IOTML_RAW_BATCH_BYTES")


def produce_batch_bytes() -> int:
    """Max frame bytes per RAW_PRODUCE request
    (IOTML_PRODUCE_BATCH_BYTES, 1 MiB)."""
    return _env_int("IOTML_PRODUCE_BATCH_BYTES")


def mesh_data() -> int:
    """Multi-chip data-axis size (IOTML_MESH_DATA, default 0 = off).
    1 behaves like 0 (a one-device mesh is the legacy path with extra
    machinery); >= 2 engages partition-parallel sharded training."""
    return _env_int("IOTML_MESH_DATA")


def device_normalize() -> bool:
    """Device-side normalization toggle (IOTML_DEVICE_NORMALIZE,
    default off).  A malformed value fails loudly, like every knob."""
    raw = os.environ.get("IOTML_DEVICE_NORMALIZE", "0").strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        return False
    if raw in ("1", "true", "on", "yes"):
        return True
    raise ValueError(f"env IOTML_DEVICE_NORMALIZE={raw!r}: expected a "
                     f"boolean (0|1|true|false|on|off)")


def raw_produce_mode() -> str:
    """Write-path plane selector (IOTML_RAW_PRODUCE): auto|on|off.
    A malformed value fails loudly, like every pipeline knob."""
    raw = os.environ.get("IOTML_RAW_PRODUCE", "auto").strip().lower()
    if raw == "":
        return "auto"
    if raw not in _RAW_PRODUCE_MODES:
        raise ValueError(f"env IOTML_RAW_PRODUCE={raw!r}: expected one "
                         f"of {'|'.join(_RAW_PRODUCE_MODES)}")
    return raw


def set_knobs(prefetch_depth: Optional[int] = None,
              decode_ring_buffers: Optional[int] = None,
              raw_batch_bytes: Optional[int] = None,
              produce_batch_bytes: Optional[int] = None,
              raw_produce: Optional[str] = None,
              mesh_data: Optional[int] = None,
              device_normalize: Optional[bool] = None) -> None:
    """CLI → env bridge: publish the given knobs into this process's
    environment (validated; None = leave as-is) so every pipeline built
    afterwards — and every supervised component thread — reads them.
    Used by ``cli.up`` / ``cli.live`` flags and the cluster CLI."""
    if raw_produce is not None:
        mode = str(raw_produce).strip().lower()
        if mode not in _RAW_PRODUCE_MODES:
            # validate BEFORE publishing (same contract as below)
            raise ValueError(f"IOTML_RAW_PRODUCE={raw_produce!r}: expected "
                             f"one of {'|'.join(_RAW_PRODUCE_MODES)}")
    for name, value in (("IOTML_PREFETCH_DEPTH", prefetch_depth),
                        ("IOTML_DECODE_RING_BUFFERS", decode_ring_buffers),
                        ("IOTML_RAW_BATCH_BYTES", raw_batch_bytes),
                        ("IOTML_PRODUCE_BATCH_BYTES",
                         produce_batch_bytes),
                        ("IOTML_MESH_DATA", mesh_data)):
        if value is None:
            continue
        _default, lo = _DEFAULTS[name]
        value = int(value)
        if value < lo:
            # validate BEFORE publishing: a caught error must not leave
            # an invalid value active process-wide
            raise ValueError(f"{name}={value}: must be >= {lo}")
        os.environ[name] = str(value)
    if raw_produce is not None:
        os.environ["IOTML_RAW_PRODUCE"] = mode
    if device_normalize is not None:
        os.environ["IOTML_DEVICE_NORMALIZE"] = \
            "1" if bool(device_normalize) else "0"


class _Slot:
    """One reusable column-buffer set (a decode target)."""

    __slots__ = ("x", "labels", "keys")

    def __init__(self, rows: int, n_numeric: int, n_strings: int,
                 label_stride: int, key_stride: int, with_keys: bool):
        self.x = np.zeros((rows, n_numeric), np.float32)
        self.labels = np.zeros((rows, max(n_strings, 1)),
                               f"S{label_stride}")
        self.keys = np.zeros((rows,), f"S{key_stride}") if with_keys \
            else None


class DecodeRing:
    """Round-robin ring of preallocated columnar decode buffers.

    The decoder fills slot *i* while the batcher may still hold VIEWS of
    the previous slots (the tail-carry between chunks in
    `SensorBatches.__iter__` keeps at most the last partial chunk
    alive, and the normalized output is always a fresh block) — with
    >= 2 slots a decode can never overwrite bytes a held view still
    reads.  Buffers are allocated once for the pipeline's lifetime:
    steady-state chunk decode costs zero numpy allocations.
    """

    def __init__(self, rows: int, n_numeric: int, n_strings: int,
                 label_stride: int = 16, key_stride: int = 64,
                 with_keys: bool = False,
                 n_buffers: Optional[int] = None):
        n = decode_ring_buffers() if n_buffers is None else int(n_buffers)
        if n < 2:
            raise ValueError(f"decode ring needs >= 2 buffers, got {n}")
        self.rows = int(rows)
        self._slots: List[_Slot] = [
            _Slot(self.rows, n_numeric, n_strings, label_stride,
                  key_stride, with_keys)
            for _ in range(n)]
        self._i = 0

    def __len__(self) -> int:
        return len(self._slots)

    def next_slot(self) -> _Slot:
        """The next decode target (round-robin reuse)."""
        slot = self._slots[self._i]
        self._i = (self._i + 1) % len(self._slots)
        return slot
