"""Host→device prefetch: overlap stream decode with the device step.

SURVEY §7 'hard parts (b)': host Avro decode + network consume must hide
under the device step or throughput dies.  A background thread drains the
batch iterator (decode/normalize/filter — the host-CPU leg) into a small
queue; the CONSUMER thread issues the `jax.device_put` as it dequeues.
`device_put` is asynchronous — it returns immediately and the DMA proceeds
in the background — so the transfer still overlaps the device step without
the worker thread ever touching JAX.  Keeping all JAX dispatch on one
thread matters: concurrent dispatch from the staging thread intermittently
aborted inside the PJRT CPU client on the forced-host 8-device mesh the
test suite uses (SIGABRT in an XLA-internal thread, ~1 in 3 full-suite
runs), and single-threaded dispatch costs nothing on real hardware.  With
a sharding, `device_put` lands shards directly on the mesh (the
per-partition → per-shard assignment path used by
`parallel.data_parallel`).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Optional

import jax

from ..obs import metrics as obs_metrics


class DevicePrefetcher:
    """Iterate device-resident batches with background staging.

    Args:
      batches: host batch iterable.
      to_device: maps a host batch to device arrays; defaults to
        `jax.device_put` of `batch.x`, `batch.y` (when present) AND
        `batch.mask` — the WHOLE batch follows `sharding` (ISSUE 15: a
        sharded x paired with a default-device mask forces a resharding
        copy inside the first jitted op that pairs them), returning
        ((x, y, mask), batch) so callers keep metadata (n_valid,
        first_index).
      depth: queue depth; 2 = classic double buffering.  None reads the
        process knob IOTML_PREFETCH_DEPTH (data/pipeline.py, default 2).
      sharding: optional `jax.sharding.Sharding` for direct sharded puts.
    """

    _END = object()

    def __init__(self, batches: Iterable, to_device: Optional[Callable] = None,
                 depth: Optional[int] = None, sharding=None,
                 loop: str = "train"):
        if depth is None:
            from .pipeline import prefetch_depth

            depth = prefetch_depth()
        #: step_seconds/occupancy loop label (train | score | online) —
        #: which hot loop this prefetcher feeds (ISSUE 13 profiling)
        self.loop = loop
        if depth < 1:
            # queue.Queue(maxsize=0) means UNBOUNDED — a depth of 0 would
            # silently stage the entire stream onto the device with no
            # backpressure instead of disabling prefetch
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.batches = batches
        self.sharding = sharding
        self.to_device = to_device or self._default_to_device
        self.q: queue.Queue = queue.Queue(maxsize=depth)
        self._err = None
        self._closed = False
        self._consumed = False
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self._work, daemon=True, name="iotml-prefetch"))
        self._thread.start()

    def _default_to_device(self, batch):
        x = jax.device_put(batch.x, self.sharding)
        y = jax.device_put(batch.y, self.sharding) \
            if getattr(batch, "y", None) is not None else None
        mask = getattr(batch, "mask", None)
        if mask is not None:
            mask = jax.device_put(mask, self.sharding)
        return (x, y, mask), batch

    def _put(self, item) -> bool:
        """put that gives up when the consumer closed; never blocks forever."""
        while not self._closed:
            try:
                self.q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _work(self):
        # host-side decode only: the consumer thread runs to_device (all
        # JAX calls stay on one thread — see module docstring)
        try:
            for b in self.batches:
                if not self._put(b):
                    return  # consumer closed mid-stream
        except BaseException as e:  # surfaced on the consumer side
            self._err = e
        finally:
            self._put(self._END)

    def close(self):
        """Release the worker (called automatically when iteration stops,
        including early break); safe to call twice."""
        self._closed = True
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self):
        if self._consumed:
            raise RuntimeError(
                "DevicePrefetcher is single-use: the background thread already "
                "drained its source; build a new one per pass")
        self._consumed = True
        depth = self.q.maxsize or 1
        try:
            while True:
                # profiling hooks (ISSUE 13): host_wait is the time the
                # consuming loop starves on the host pipeline, and the
                # occupancy gauge is the queue's fill fraction at each
                # dequeue — together the measured host-vs-device balance
                # (occupancy ~0 + large host_wait = input-bound; ~full
                # queue = device-bound).  Per-batch cost: one clock pair.
                t0 = time.perf_counter()
                item = self.q.get()
                obs_metrics.step_seconds.observe(
                    time.perf_counter() - t0, loop=self.loop,
                    phase="host_wait")
                obs_metrics.prefetch_occupancy.set(
                    min(self.q.qsize() / depth, 1.0), loop=self.loop)
                if item is self._END:
                    if self._err is not None:
                        raise self._err
                    return
                yield self.to_device(item)
        finally:
            self.close()
