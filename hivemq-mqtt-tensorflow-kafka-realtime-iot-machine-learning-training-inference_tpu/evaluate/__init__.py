from .anomaly import (AnomalyReport, auc, average_precision,
                      confusion_at_threshold, evaluate_detector,
                      precision_recall_curve, reconstruction_errors,
                      roc_curve)

__all__ = ["reconstruction_errors", "confusion_at_threshold", "roc_curve",
           "auc", "precision_recall_curve", "average_precision",
           "evaluate_detector", "AnomalyReport"]
