"""Anomaly-detection evaluation: the reference notebook's protocol as a library.

The reference validates model quality notebook-side (SURVEY §4.5): per-row
reconstruction-error MSE, a fixed decision threshold (5.0 in the creditcard
notebook, cells 21-26), confusion matrix, ROC curve + AUC, and a
precision/recall-vs-threshold analysis.  None of that is reusable code in
the reference — it lives in matplotlib cells.  Here it is a typed library:
curves are computed by the standard sort-and-cumsum sweep (every distinct
score is a candidate threshold), AUC/AP by trapezoid / step integration,
and the error computation itself is a jitted TPU kernel so scoring a large
eval stream stays on-chip.

Reference parity targets: creditcard notebook cells 19-26
(Python-Tensorflow-2.0-Keras-Fraud-Detection-Autoencoder.ipynb).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnums=0)
def _recon_err(apply_fn, params, x):
    pred = apply_fn({"params": params}, x)
    return jnp.mean(jnp.square(pred - x), axis=-1)


def reconstruction_errors(model, params, x, batch_size: int = 8192) -> np.ndarray:
    """Per-row reconstruction MSE (the anomaly score).

    Mirrors the notebook's `np.mean(np.power(data - predictions, 2), axis=1)`
    but runs forward + error on-device in fixed-size padded chunks so one
    compiled program serves any eval-set size.
    """
    x = np.asarray(x, np.float32)
    n = x.shape[0]
    out = np.empty((n,), np.float32)
    for start in range(0, n, batch_size):
        chunk = x[start:start + batch_size]
        if chunk.shape[0] < batch_size and n > batch_size:
            pad = np.zeros((batch_size - chunk.shape[0],) + x.shape[1:], np.float32)
            err = _recon_err(model.apply, params, np.concatenate([chunk, pad]))
            out[start:start + chunk.shape[0]] = np.asarray(err)[: chunk.shape[0]]
        else:
            out[start:start + chunk.shape[0]] = np.asarray(
                _recon_err(model.apply, params, chunk))
    return out


def confusion_at_threshold(scores, labels, threshold: float) -> Dict[str, float]:
    """Confusion matrix + derived metrics at a fixed decision threshold.

    `scores > threshold` ⇒ predicted anomaly (the notebook's
    `error_df.Reconstruction_error > threshold` rule, fixed threshold 5).
    labels: 1 = anomaly, 0 = normal.
    """
    scores = np.asarray(scores, np.float64)
    labels = np.asarray(labels).astype(bool)
    pred = scores > threshold
    tp = int(np.sum(pred & labels))
    fp = int(np.sum(pred & ~labels))
    fn = int(np.sum(~pred & labels))
    tn = int(np.sum(~pred & ~labels))
    precision = tp / (tp + fp) if tp + fp else 0.0
    recall = tp / (tp + fn) if tp + fn else 0.0
    f1 = 2 * precision * recall / (precision + recall) if precision + recall else 0.0
    return {"tp": tp, "fp": fp, "fn": fn, "tn": tn,
            "precision": precision, "recall": recall, "f1": f1,
            "accuracy": (tp + tn) / max(len(labels), 1)}


def _sorted_sweep(scores, labels):
    """Descending-score sweep: cumulative TP/FP at every distinct threshold."""
    scores = np.asarray(scores, np.float64)
    labels = np.asarray(labels).astype(np.float64)
    order = np.argsort(-scores, kind="mergesort")
    scores, labels = scores[order], labels[order]
    # indices where the score strictly drops — thresholds between ties are
    # not realizable decision points.
    distinct = np.where(np.diff(scores))[0]
    idx = np.concatenate([distinct, [len(scores) - 1]])
    tps = np.cumsum(labels)[idx]
    fps = (idx + 1) - tps
    return scores[idx], tps, fps


def roc_curve(scores, labels):
    """(fpr, tpr, thresholds), thresholds descending. labels: 1 = anomaly."""
    thr, tps, fps = _sorted_sweep(scores, labels)
    p = tps[-1] if len(tps) else 0.0
    n = fps[-1] if len(fps) else 0.0
    tpr = np.concatenate([[0.0], tps / p if p else np.zeros_like(tps)])
    fpr = np.concatenate([[0.0], fps / n if n else np.zeros_like(fps)])
    thresholds = np.concatenate([[np.inf], thr])
    return fpr, tpr, thresholds


def auc(x, y) -> float:
    """Trapezoidal area under a curve given by (x, y) points."""
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    integrate = getattr(np, "trapezoid", np.trapz)
    return float(integrate(y, x))


def precision_recall_curve(scores, labels):
    """(precision, recall, thresholds), thresholds descending.

    Ends with the conventional (precision=1, recall=0) anchor point.
    """
    thr, tps, fps = _sorted_sweep(scores, labels)
    p = tps[-1] if len(tps) else 0.0
    precision = tps / np.maximum(tps + fps, 1.0)
    recall = tps / p if p else np.zeros_like(tps)
    precision = np.concatenate([precision[::-1], [1.0]])
    recall = np.concatenate([recall[::-1], [0.0]])
    return precision, recall, thr[::-1]


def average_precision(scores, labels) -> float:
    """AP = Σ (R_i − R_{i−1}) · P_i over the descending-threshold sweep."""
    thr, tps, fps = _sorted_sweep(scores, labels)
    p = tps[-1] if len(tps) else 0.0
    if not p:
        return 0.0
    precision = tps / np.maximum(tps + fps, 1.0)
    recall = tps / p
    prev_r = np.concatenate([[0.0], recall[:-1]])
    return float(np.sum((recall - prev_r) * precision))


@dataclasses.dataclass
class AnomalyReport:
    """Everything the reference's eval cells produce, in one object."""

    threshold: float
    confusion: Dict[str, float]
    roc_auc: float
    avg_precision: float
    mean_error_normal: float
    mean_error_anomaly: float
    n: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def summary(self) -> str:
        c = self.confusion
        return (f"n={self.n} thr={self.threshold:g} "
                f"auc={self.roc_auc:.4f} ap={self.avg_precision:.4f} "
                f"P={c['precision']:.3f} R={c['recall']:.3f} F1={c['f1']:.3f} "
                f"err(normal)={self.mean_error_normal:.4g} "
                f"err(anomaly)={self.mean_error_anomaly:.4g}")


def evaluate_detector(model, params, x, labels,
                      threshold: float = 5.0,
                      scores: Optional[np.ndarray] = None) -> AnomalyReport:
    """Full notebook protocol in one call.

    threshold=5.0 is the reference's fixed creditcard threshold (cell 24);
    pass `scores` to skip the forward pass (already-computed errors).
    """
    if scores is None:
        scores = reconstruction_errors(model, params, x)
    labels = np.asarray(labels).astype(bool)
    fpr, tpr, _ = roc_curve(scores, labels)
    normal_err = scores[~labels]
    anom_err = scores[labels]
    return AnomalyReport(
        threshold=threshold,
        confusion=confusion_at_threshold(scores, labels, threshold),
        roc_auc=auc(fpr, tpr),
        avg_precision=average_precision(scores, labels),
        mean_error_normal=float(normal_err.mean()) if len(normal_err) else 0.0,
        mean_error_anomaly=float(anom_err.mean()) if len(anom_err) else 0.0,
        n=len(scores))
