"""Persisted model-quality report — the notebook's output cells as files.

The reference's evaluation lives in notebook cells that render ROC/AUC,
precision-recall, and reconstruction-error threshold plots, with committed
TensorBoard/profiler artifacts proving the runs happened
(reference `python-scripts/autoencoder-anomaly-detection/` cells 21-26 and
its committed `logs/`).  Round 1 computed all the numbers
(`evaluate.anomaly`) but persisted nothing an operator could open.

`write_report` turns an `AnomalyReport` + raw scores into:

- `report.json` — every scalar the notebook prints, plus downsampled
  ROC/PR curve points (machine-readable, diffable between runs);
- `report.svg` — a three-panel figure (ROC with AUC, PR with AP,
  reconstruction-error histogram with the decision threshold), the same
  three visuals the notebook renders.

Both land in a directory that can be pushed through `ArtifactStore`
(local or gs://) right beside the model it describes.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from .anomaly import (AnomalyReport, precision_recall_curve, roc_curve)


def _downsample(xs: np.ndarray, ys: np.ndarray, max_points: int = 256):
    if len(xs) <= max_points:
        return xs, ys
    idx = np.linspace(0, len(xs) - 1, max_points).astype(int)
    return xs[idx], ys[idx]


def write_report(report: AnomalyReport, scores, labels, out_dir: str,
                 store=None, name: str = "eval-report") -> dict:
    """Write report.json + report.svg under out_dir; optionally upload the
    directory through an ArtifactStore as `name`.  Returns the paths."""
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels).astype(bool)
    os.makedirs(out_dir, exist_ok=True)

    fpr, tpr, _ = roc_curve(scores, labels)
    prec, rec, _ = precision_recall_curve(scores, labels)
    fpr_s, tpr_s = _downsample(np.asarray(fpr), np.asarray(tpr))
    rec_s, prec_s = _downsample(np.asarray(rec), np.asarray(prec))

    json_path = os.path.join(out_dir, "report.json")
    payload = dict(report.as_dict())
    payload["curves"] = {
        "roc": {"fpr": fpr_s.tolist(), "tpr": tpr_s.tolist()},
        "pr": {"recall": rec_s.tolist(), "precision": prec_s.tolist()},
    }
    with open(json_path, "w") as f:
        json.dump(payload, f, indent=2)

    svg_path = os.path.join(out_dir, "report.svg")
    _render_svg(report, scores, labels, fpr_s, tpr_s, rec_s, prec_s,
                svg_path)

    uploaded: Optional[str] = None
    if store is not None:
        uploaded = store.upload_tree(out_dir, name)
    return {"json": json_path, "svg": svg_path, "uploaded": uploaded}


def _render_svg(report: AnomalyReport, scores, labels,
                fpr, tpr, rec, prec, path: str) -> None:
    import matplotlib

    matplotlib.use("Agg")  # headless; must precede pyplot import
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 3, figsize=(13, 4))
    ax = axes[0]
    ax.plot(fpr, tpr, lw=1.5)
    ax.plot([0, 1], [0, 1], ls="--", lw=0.8, color="gray")
    ax.set_xlabel("false positive rate")
    ax.set_ylabel("true positive rate")
    ax.set_title(f"ROC (AUC = {report.roc_auc:.4f})")

    ax = axes[1]
    ax.plot(rec, prec, lw=1.5)
    ax.set_xlabel("recall")
    ax.set_ylabel("precision")
    ax.set_ylim(-0.02, 1.02)
    ax.set_title(f"Precision-Recall (AP = {report.avg_precision:.4f})")

    ax = axes[2]
    normal, anom = scores[~labels], scores[labels]
    bins = np.histogram_bin_edges(scores, bins=50)
    if len(normal):
        ax.hist(normal, bins=bins, alpha=0.6, label="normal", log=True)
    if len(anom):
        ax.hist(anom, bins=bins, alpha=0.6, label="anomaly", log=True)
    ax.axvline(report.threshold, color="red", ls="--", lw=1.2,
               label=f"threshold = {report.threshold:g}")
    ax.set_xlabel("reconstruction error")
    ax.set_ylabel("count (log)")
    ax.set_title("Error distribution")
    ax.legend(fontsize=8)

    c = report.confusion
    fig.suptitle(
        f"n={report.n}  P={c['precision']:.3f} R={c['recall']:.3f} "
        f"F1={c['f1']:.3f}", fontsize=10)
    fig.tight_layout()
    fig.savefig(path, format="svg")
    plt.close(fig)
