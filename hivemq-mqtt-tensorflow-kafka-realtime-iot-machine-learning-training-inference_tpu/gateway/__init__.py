"""iotml.gateway — sharded scatter-gather twin serving (ISSUE 20).

The serving plane over the digital twin: the TwinTable partitions
across cluster shards keyed by the changelog partitioning, each shard
shadowed by a warm standby rebuilt continuously from the compacted
changelog (the Kafka Streams standby-replica pattern), a scatter-gather
router on the Connect REST shapes, and a federated multi-process MQTT
ingest front so the reference's full 100,000-car fleet runs live.

Layers (one direction, no cycles):

    fronts.py   federated MQTT ingest (front processes + fleet driver)
    shards.py   GatewayShard / TwinStandby / GatewayCluster (primaries,
                warm standbys, promotion, the leadership map)
    router.py   GatewayClient (smart, scatter-gather, feature-store
                duck-type) + GatewayRouter (fleet-facing REST mounts)
    drill.py    live shard-kill / standby-promotion drill
"""

from .drill import GatewayDrillReport, run_gateway_drill
from .fronts import (FederatedFleet, FrontProcess, MqttFront, front_for,
                     run_federated_fleet)
from .router import (GatewayClient, GatewayError, GatewayRouter,
                     partition_for_key, shard_for_key)
from .shards import (GatewayCluster, GatewayShard, StandbyDriver,
                     TwinStandby)

__all__ = [
    "FederatedFleet",
    "FrontProcess",
    "GatewayClient",
    "GatewayCluster",
    "GatewayDrillReport",
    "GatewayError",
    "GatewayRouter",
    "GatewayShard",
    "MqttFront",
    "StandbyDriver",
    "TwinStandby",
    "front_for",
    "partition_for_key",
    "run_federated_fleet",
    "run_gateway_drill",
    "shard_for_key",
]
