"""CLI: the gateway serving plane, live.

``python -m iotml.gateway drill``
    Kill a serving shard under a query storm, promote its warm standby,
    prove zero wrong answers.  Exit status is the verdict (0 = every
    invariant held) — CI and deploy/smoke.sh gate on it directly, the
    same contract as the twin/chaos/supervise drills.

``python -m iotml.gateway front --stream HOST:PORT``
    Run ONE federated MQTT ingest front in this process: serve MQTT,
    bridge into the wire-protocol stream broker, announce the bound
    port as a JSON line, exit when stdin closes.  Spawned by
    ``FrontProcess``; useful standalone for manual federation.

``python -m iotml.gateway fleet --cars 100000 --fronts 2``
    The reference's full 100,000-car scenario, live: N front processes,
    a consistent car→front assignment, the sharded gateway serving
    every car.  Exit status is the verdict.
"""

from __future__ import annotations

import argparse
import json
import sys

from .drill import run_gateway_drill
from .fronts import run_federated_fleet, run_front


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m iotml.gateway")
    sub = ap.add_subparsers(dest="cmd", required=True)

    d = sub.add_parser("drill",
                       help="shard-kill + standby-promotion drill "
                            "under a query storm")
    d.add_argument("--seed", type=int, default=11)
    d.add_argument("--records", type=int, default=2000)
    d.add_argument("--cars", type=int, default=40)
    d.add_argument("--shards", type=int, default=2)
    d.add_argument("--partitions", type=int, default=4)
    d.add_argument("--json", action="store_true",
                   help="emit the full report as JSON")

    f = sub.add_parser("front",
                       help="one federated MQTT ingest front process")
    f.add_argument("--stream", required=True,
                   help="wire broker bootstrap, host:port")
    f.add_argument("--partitions", type=int, default=10)
    f.add_argument("--mqtt-port", type=int, default=0)
    f.add_argument("--topic", default="SENSOR_DATA_S_AVRO")

    fl = sub.add_parser("fleet",
                        help="federated fleet scenario: N fronts, "
                             "sharded gateway, every car served")
    fl.add_argument("--cars", type=int, default=100_000)
    fl.add_argument("--fronts", type=int, default=2)
    fl.add_argument("--ticks", type=int, default=2)
    fl.add_argument("--shards", type=int, default=2)
    fl.add_argument("--partitions", type=int, default=8)
    fl.add_argument("--seed", type=int, default=20)
    args = ap.parse_args(argv)

    if args.cmd == "front":
        run_front(args.stream, partitions=args.partitions,
                  mqtt_port=args.mqtt_port, topic=args.topic)
        return 0

    if args.cmd == "fleet":
        report = run_federated_fleet(
            cars=args.cars, fronts=args.fronts, ticks=args.ticks,
            shards=args.shards, partitions=args.partitions,
            seed=args.seed)
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1

    report = run_gateway_drill(seed=args.seed, records=args.records,
                               cars=args.cars, n_shards=args.shards,
                               partitions=args.partitions)
    if args.json:
        print(json.dumps(report.to_dict(), indent=2, default=str))
    else:
        print(f"gateway drill  seed={report.seed} cars={report.cars} "
              f"shards={report.n_shards} published={report.published} "
              f"storm_queries={report.storm_queries} "
              f"storm_p99_ms={report.storm_p99_ms} "
              f"promote_s={report.slos['promote_s']} "
              f"staleness_s={report.slos['staleness_s']}")
        for inv in report.invariants:
            print(f"  {inv.verdict()}")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
