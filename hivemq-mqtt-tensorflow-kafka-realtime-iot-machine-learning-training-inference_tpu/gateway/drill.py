"""Live gateway failover drill — kill a serving shard under a query
storm, promote its warm standby, prove nobody got a wrong answer.

The drill drives the real serving plane end to end: a durable broker
(small segments so the changelog rolls and compacts mid-drill), a
seeded fleet publishing framed-Avro sensor records CONTINUOUSLY, a
``GatewayCluster`` of twin shards with warm standbys, and a
``GatewayClient`` query storm mixing point lookups and pipelined
batches.  Mid-storm one primary is KILLED (REST surface drops, pump
stops, nothing flushed) and its standby is promoted:

- ``standby_byte_identical``: quiesced, each shard's warm standby table
  is BYTE-identical to its primary's — across a compaction pass, so
  the standby demonstrably follows the *compacted* changelog;
- ``promote_within_slo``: kill → new primary published within
  ``GatewayCluster.PROMOTE_SLO_S``;
- ``zero_wrong_answers``: every storm query for a committed car
  answered correctly (right car, count never below the pre-storm
  baseline) — across the failover, with zero gateway errors;
- ``bounded_staleness``: records published AFTER the failover are
  served by the promoted primary within ``STALENESS_SLO_S``;
- ``fanout_agrees``: ``GET /twin`` through the mounted router (fan-out
  merge) agrees with per-shard truth on count and page contents;
- ``scorer_join_matches``: ``GatewayClient.matrix`` (the sharded
  feature join ``StreamScorer(feature_store=)`` rides) equals a local
  ``TwinFeatureStore`` over the same changelog, elementwise.

Exit status = verdict (``python -m iotml.gateway drill``).
"""

from __future__ import annotations

import dataclasses
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..chaos.runner import Invariant

IN_TOPIC = "SENSOR_DATA_S_AVRO"
PARTITIONS = 4

#: records produced after a failover must be queryable within this
STALENESS_SLO_S = 5.0


@dataclasses.dataclass
class GatewayDrillReport:
    seed: int
    records: int
    cars: int
    n_shards: int
    published: int
    killed_shard: int
    storm_queries: int
    storm_wrong: int
    storm_errors: int
    storm_p99_ms: float
    promote_catchup_records: int
    slos: Dict[str, float]
    invariants: List[Invariant]

    @property
    def ok(self) -> bool:
        return all(i.ok for i in self.invariants)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        return d


def run_gateway_drill(seed: int = 11, records: int = 2000,
                      cars: int = 40, n_shards: int = 2,
                      partitions: int = PARTITIONS) -> GatewayDrillReport:
    store_dir = tempfile.mkdtemp(prefix="iotml_gw_drill_")
    try:
        return _run(seed, records, cars, n_shards, partitions, store_dir)
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)


def _await(cond, timeout_s: float = 20.0, interval_s: float = 0.02,
           what: str = "condition") -> None:
    deadline = time.monotonic() + timeout_s
    while not cond():
        if time.monotonic() >= deadline:
            raise TimeoutError(f"drill: {what} not reached "
                               f"in {timeout_s}s")
        time.sleep(interval_s)


class _Storm:
    """Closed-loop query storm on its own thread: point lookups by key
    hash plus periodic pipelined ``mget`` sweeps, each answer verified
    against the committed baseline (identity + count monotonicity)."""

    def __init__(self, client, baseline: Dict[str, int]):
        self.client = client
        self.baseline = baseline
        self.cars = sorted(baseline)
        self.queries = 0
        self.wrong = 0
        self.errors = 0
        self.point_lat: List[float] = []
        self.wrong_detail: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _check(self, car: str, doc: Optional[dict]) -> None:
        ok = (doc is not None and doc.get("car") == car
              and doc.get("count", doc.get("aggregates", {})
                          .get("count", 0)) >= self.baseline[car])
        if not ok:
            self.wrong += 1
            if self.wrong_detail is None:
                self.wrong_detail = f"{car!r} -> {doc!r}"

    def _run(self) -> None:
        from .router import GatewayError

        i = 0
        while not self._stop.is_set():
            try:
                if i % 8 == 7:
                    docs = self.client.mget(self.cars)
                    self.queries += len(self.cars)
                    for car, doc in zip(self.cars, docs):
                        self._check(car, doc)
                else:
                    car = self.cars[(i * 7) % len(self.cars)]
                    t0 = time.perf_counter()
                    doc = self.client.get(car)
                    self.point_lat.append(time.perf_counter() - t0)
                    self.queries += 1
                    # full twin doc: identity only (count lives in the
                    # slim mget doc; the full doc carries aggregates)
                    if doc is None or doc.get("car") != car:
                        self.wrong += 1
                        if self.wrong_detail is None:
                            self.wrong_detail = f"{car!r} -> {doc!r}"
            except GatewayError as e:
                # a committed car MUST stay answerable across failover;
                # an exhausted retry deadline is a drill failure
                self.errors += 1
                if self.wrong_detail is None:
                    self.wrong_detail = f"GatewayError: {e}"
            i += 1

    def start(self) -> "_Storm":
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self._run, daemon=True, name="iotml-gw-storm"))
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def p99_ms(self) -> float:
        if not self.point_lat:
            return 0.0
        lat = sorted(self.point_lat)
        return lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1000


def _run(seed: int, records: int, cars: int, n_shards: int,
         partitions: int, store_dir: str) -> GatewayDrillReport:
    import json as _json
    import urllib.request

    import numpy as np

    from ..gen.simulator import FleetGenerator, FleetScenario
    from ..store import StorePolicy
    from ..stream.broker import Broker
    from ..twin.features import TwinFeatureStore
    from ..twin.service import CHANGELOG_TOPIC, TwinService
    from ..utils.rest import RestServer
    from .router import GatewayClient, GatewayRouter
    from .shards import GatewayCluster

    broker = Broker(store_dir=store_dir,
                    store_policy=StorePolicy(fsync="interval",
                                             segment_bytes=8 * 1024,
                                             compact_grace_ms=10**9))
    broker.create_topic(IN_TOPIC, partitions=partitions)
    gen = FleetGenerator(FleetScenario(num_cars=cars, seed=seed,
                                       failure_rate=0.05))
    ticks = max(4, records // cars)
    warm_ticks = ticks // 2

    cluster = GatewayCluster(broker, n_shards=n_shards,
                             source_topic=IN_TOPIC).start()
    client = GatewayClient(cluster)
    try:
        # ---- phase 1: warm the fleet, drain shards and standbys
        published = 0
        for _ in range(warm_ticks):
            published += gen.publish(broker, IN_TOPIC, n_ticks=1,
                                     partitions=partitions)
        _await(lambda: client.aggregate()["records"] >= published,
               what="shards drained after warm-up")
        # compact the changelog mid-drill so the standby equality below
        # proves the shadow follows the COMPACTED log, not a convenient
        # full history
        for p in range(partitions):
            broker.store.log_for(CHANGELOG_TOPIC, p).roll()
        broker.run_compaction(force=True)
        for _ in range(warm_ticks // 2):
            published += gen.publish(broker, IN_TOPIC, n_ticks=1,
                                     partitions=partitions)
        _await(lambda: client.aggregate()["records"] >= published,
               what="shards drained after compaction pass")
        _await(lambda: all(s.lag() == 0
                           for s in cluster.standbys.values()),
               what="standbys caught up")

        standby_identical = all(
            cluster.standbys[s.shard_id].table.snapshot()
            == s.service.table.snapshot()
            for s in cluster.shards)

        # committed baseline every storm answer is checked against
        baseline = {doc["car"]: doc["count"]
                    for doc in client.mget(sorted(client.cars(
                        limit=cars))) if doc is not None}

        # ---- phase 2: query storm + live ingest + shard kill
        storm = _Storm(GatewayClient(cluster), baseline).start()
        pub_stop = threading.Event()
        pub_done = threading.Event()
        pub_counts = {"published": 0}

        def _publish_loop():
            for _ in range(ticks - warm_ticks - warm_ticks // 2):
                if pub_stop.is_set():
                    break
                pub_counts["published"] += gen.publish(
                    broker, IN_TOPIC, n_ticks=1, partitions=partitions)
                time.sleep(0.01)
            pub_done.set()

        from ..supervise.registry import register_thread

        register_thread(threading.Thread(
            target=_publish_loop, daemon=True,
            name="iotml-gw-drill-pub")).start()

        _await(lambda: storm.queries >= 50, what="storm warmed up")
        killed_shard = 0
        cluster.kill_shard(killed_shard)
        time.sleep(0.1)  # let the storm hit the dead shard for real
        promote_s = cluster.promote(killed_shard)
        catchup = cluster.shards[killed_shard].service.rebuilt_records

        _await(pub_done.is_set, what="ingest finished")
        published += pub_counts["published"]
        # ---- bounded staleness: post-failover records become servable
        t0 = time.perf_counter()
        published += gen.publish(broker, IN_TOPIC, n_ticks=1,
                                 partitions=partitions)
        _await(lambda: client.aggregate()["records"] >= published,
               timeout_s=STALENESS_SLO_S + 5,
               what="post-failover records served")
        staleness_s = time.perf_counter() - t0
        _await(lambda: storm.queries >= 200, what="storm sampled enough")
        storm.stop()

        # ---- fan-out agreement through the mounted router
        rest = RestServer(name="iotml-gw-router")
        GatewayRouter(cluster, client=client).mount(rest)
        rest.start()
        try:
            with urllib.request.urlopen(
                    f"{rest.url}/twin?count_only=1", timeout=5) as resp:
                count_doc = _json.loads(resp.read())
            with urllib.request.urlopen(
                    f"{rest.url}/twin?limit={cars}", timeout=5) as resp:
                page_doc = _json.loads(resp.read())
        finally:
            rest.stop()
        all_cars = sorted(c for s in cluster.shards
                          for c in s.service.cars())
        fanout_ok = (count_doc.get("count") == len(all_cars) == cars
                     and page_doc.get("cars") == all_cars
                     and page_doc.get("next_offset") is None)

        # ---- sharded feature join vs a local reference store
        ref = TwinService(broker, source_topic=IN_TOPIC,
                          group="iotml-gw-drill-ref", changelog=False)
        keys = [c.encode() for c in all_cars[:16]]
        local = TwinFeatureStore(ref).matrix(keys, len(keys))
        remote = client.matrix(keys, len(keys))
        join_ok = bool(np.allclose(local, remote, atol=1e-6))
    finally:
        client.close()
        cluster.stop()
        broker.close()

    invariants = [
        Invariant(
            "standby_byte_identical",
            standby_identical,
            "every shard's warm standby table byte-identical to its "
            "primary across a compaction pass" if standby_identical else
            "standby table DIVERGED from its primary"),
        Invariant(
            "promote_within_slo",
            promote_s <= cluster.PROMOTE_SLO_S,
            f"kill -> promoted primary published in {promote_s * 1000:.0f}ms "
            f"(SLO {cluster.PROMOTE_SLO_S:.0f}s); delta replay "
            f"{catchup} records"),
        Invariant(
            "zero_wrong_answers",
            storm.wrong == 0 and storm.errors == 0 and storm.queries > 0,
            f"{storm.queries} storm queries across the failover, all "
            f"answered correctly" if storm.wrong == 0 and storm.errors == 0
            else f"{storm.wrong} wrong, {storm.errors} errors over "
                 f"{storm.queries} queries; first: {storm.wrong_detail}"),
        Invariant(
            "bounded_staleness",
            staleness_s <= STALENESS_SLO_S,
            f"post-failover records served in {staleness_s * 1000:.0f}ms "
            f"(SLO {STALENESS_SLO_S:.0f}s)"),
        Invariant(
            "fanout_agrees",
            fanout_ok,
            f"router GET /twin fan-out merge agrees with per-shard truth "
            f"({cars} cars)" if fanout_ok else
            f"fan-out DISAGREES: count={count_doc}, page={page_doc}"),
        Invariant(
            "scorer_join_matches",
            join_ok,
            f"GatewayClient.matrix == local TwinFeatureStore over "
            f"{len(keys)} keys" if join_ok else
            "sharded feature join diverged from the local store"),
    ]
    return GatewayDrillReport(
        seed=seed, records=records, cars=cars, n_shards=n_shards,
        published=published, killed_shard=killed_shard,
        storm_queries=storm.queries, storm_wrong=storm.wrong,
        storm_errors=storm.errors, storm_p99_ms=round(storm.p99_ms(), 3),
        promote_catchup_records=catchup,
        slos={"promote_s": round(promote_s, 4),
              "promote_slo_s": cluster.PROMOTE_SLO_S,
              "staleness_s": round(staleness_s, 4),
              "staleness_slo_s": STALENESS_SLO_S},
        invariants=invariants)
