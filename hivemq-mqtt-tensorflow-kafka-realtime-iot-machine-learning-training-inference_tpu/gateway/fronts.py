"""Federated MQTT ingest: N front processes, one consistent car→front map.

The reference walls its single MQTT listener at the box's ~18k-fd
ceiling (PARITY "Fleet scale"); its own 100,000-car scenario assumes a
fleet of brokers behind a load balancer.  The rebuild's equivalent
(ISSUE 20): the C++ ingest engine is single-core-idle at reference
rates, so the scale axis is horizontal — several front PROCESSES, each
running its own native MQTT listener, all producing into the SAME keyed
sensor stream over the wire protocol::

    fleet publisher ──crc32(car) % n_fronts──► front 0 (MQTT :p0) ─┐
                                               front 1 (MQTT :p1) ─┤ RAW_PRODUCE
                                               ...                 ▼
                                   SENSOR_DATA_S_AVRO (keyed by car id)
                                               │
                                   twin shards / gateway (iotml.gateway)

The car→front assignment is the same pure-hash discipline the rest of
the plane uses (``front_for``), so a car's records always enter through
one front — per-car ordering survives federation.  Record keys come
from the topic's car segment (``TopicMapping.sensor_data_keyed``), so
every front's records land on the same partition the direct-produce
path would use: the twin shards cannot tell federated ingest from local.
"""

from __future__ import annotations

import json
import subprocess
import sys
import zlib
from typing import List, Optional

import numpy as np

from ..core.schema import KSQL_CAR_SCHEMA, RecordSchema
from ..gen.simulator import FleetGenerator, FleetScenario
from ..mqtt.bridge import TopicMapping
from ..mqtt.wire import MqttClient


def front_for(car_id: str, n_fronts: int) -> int:
    """The consistent car→front assignment — same hash family as the
    broker's keyed partitioner, so any publisher (or operator reading a
    capture) computes the same front with no coordination."""
    return zlib.crc32(car_id.encode()) % int(n_fronts)


class MqttFront:
    """One MQTT ingest front: a TCP listener bridging publishes into
    the keyed sensor stream.

    Native path (preferred): ``NativeIngestBridge`` — the C++ epoll
    engine drains publish batches and ships them as framed RAW_PRODUCE
    into a wire broker, the remote-front shape.  Fallback (no native
    lib): the Python ``MqttServer`` + ``KafkaBridge`` pair, same
    records, per-message produce.

    ``stream`` is a ``host:port`` bootstrap string (federated: the
    front runs in its own process and produces over the wire) or an
    in-process Broker duck-type (tests)."""

    def __init__(self, stream, partitions: int = 10, mqtt_port: int = 0,
                 mapping: Optional[TopicMapping] = None):
        self.mapping = mapping or TopicMapping.sensor_data_keyed()
        if isinstance(stream, str):
            from ..stream.kafka_wire import KafkaWireBroker

            stream = KafkaWireBroker(stream, client_id="iotml-front")
        self.stream = stream
        self.native = False
        self._bridge = None
        self._mqtt_server = None
        try:
            from ..mqtt.native_ingest import NativeIngestBridge

            self._bridge = NativeIngestBridge(
                stream, mapping=self.mapping, partitions=partitions,
                port=mqtt_port)
            self.port = self._bridge.port
            self.native = True
        except (RuntimeError, OSError):
            from ..mqtt.bridge import KafkaBridge
            from ..mqtt.broker import MqttBroker
            from ..mqtt.wire import MqttServer

            core = MqttBroker(name="iotml-front")
            self._py_bridge = KafkaBridge(core, stream,
                                          mappings=[self.mapping],
                                          partitions=partitions)
            self._mqtt_server = MqttServer(core, port=mqtt_port)
            self.port = self._mqtt_server.port

    def start(self) -> "MqttFront":
        if self.native:
            self._bridge.start()
        else:
            self._mqtt_server.start()
        return self

    def forwarded(self) -> int:
        if self.native:
            return self._bridge.forwarded()
        return self._py_bridge.forwarded()

    def stop(self) -> None:
        if self.native:
            self._bridge.stop()
        elif self._mqtt_server is not None:
            self._mqtt_server.shutdown()
            self._mqtt_server.server_close()


def run_front(stream: str, partitions: int = 10, mqtt_port: int = 0,
              topic: str = "SENSOR_DATA_S_AVRO") -> None:
    """Front-process entry (``python -m iotml.gateway front``): serve
    MQTT, bridge into the wire broker, announce the bound port as one
    JSON line on stdout, and run until stdin closes — the parent owns
    the lifetime (closing the pipe is the shutdown signal, robust even
    when the parent dies uncleanly)."""
    front = MqttFront(stream, partitions=partitions, mqtt_port=mqtt_port,
                      mapping=TopicMapping.sensor_data_keyed(topic))
    front.start()
    print(json.dumps({"mqtt_port": front.port, "native": front.native}),
          flush=True)
    try:
        sys.stdin.buffer.read()  # blocks until the parent closes the pipe
    except KeyboardInterrupt:
        pass
    front.stop()
    print(json.dumps({"forwarded": front.forwarded()}), flush=True)


class FrontProcess:
    """Parent-side handle on one spawned front process."""

    def __init__(self, stream_addr: str, partitions: int = 10,
                 topic: str = "SENSOR_DATA_S_AVRO"):
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "iotml.gateway", "front",
             "--stream", stream_addr, "--partitions", str(partitions),
             "--topic", topic],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE)
        line = self.proc.stdout.readline()
        if not line:
            raise RuntimeError("front process died before announcing "
                               "its MQTT port")
        doc = json.loads(line)
        self.mqtt_port = int(doc["mqtt_port"])
        self.native = bool(doc.get("native"))

    def stop(self, timeout_s: float = 10.0) -> Optional[int]:
        """Close the lifetime pipe, collect the front's forwarded count
        from its exit line (None if it died without one)."""
        forwarded = None
        try:
            self.proc.stdin.close()
            for line in self.proc.stdout:
                try:
                    forwarded = json.loads(line).get("forwarded")
                except ValueError:
                    continue
            self.proc.wait(timeout=timeout_s)
        except Exception:
            self.proc.kill()
        return forwarded

    def kill(self) -> None:
        self.proc.kill()


class FederatedFleet:
    """Drive a ``FleetScenario`` through N MQTT fronts.

    The publisher multiplexes each front's cars over ONE pipelined MQTT
    connection (``publish_many``): fleet scale here means 100k DISTINCT
    cars/topics/twins, not 100k sockets — the per-connection ceiling is
    PARITY's separately-measured axis.  Payloads are the same framed
    Avro the direct-produce path emits (vectorized native encode), on
    the reference's ``vehicles/sensor/data/{car}`` topics."""

    def __init__(self, scenario: FleetScenario, front_ports: List[int],
                 host: str = "127.0.0.1",
                 schema: RecordSchema = KSQL_CAR_SCHEMA):
        if not front_ports:
            raise ValueError("federation needs at least one front")
        self.gen = FleetGenerator(scenario)
        self.schema = schema
        self.n_fronts = len(front_ports)
        ids = [scenario.car_id(i) for i in range(scenario.num_cars)]
        self.topics = [f"vehicles/sensor/data/{cid}" for cid in ids]
        self.assign = [front_for(cid, self.n_fronts) for cid in ids]
        self.clients = [MqttClient(host, p, f"iotml-fleet-front{j}",
                                   keepalive=0)
                        for j, p in enumerate(front_ports)]
        self._codec = None
        try:
            from ..stream.native import NativeCodec

            self._codec = NativeCodec(schema)
        except Exception:
            self._codec = None
        self.published = 0

    def _payloads(self, cols: dict) -> List[bytes]:
        if self._codec is not None and self.schema.label_field:
            num = self.gen.sensor_matrix(cols)
            labels = cols["failure_occurred"].astype("S16")[:, None]
            return self._codec.encode_batch(num, labels, schema_id=1)
        from ..ops.avro import AvroCodec
        from ..ops.framing import frame

        codec = AvroCodec(self.schema)
        return [frame(codec.encode(self.gen.row_record(cols, i,
                                                       self.schema)))
                for i in range(len(cols["car"]))]

    def publish_tick(self, batch_cars: Optional[np.ndarray] = None,
                     chunk: int = 4096) -> int:
        """One tick for the whole fleet (or a car-index slice), fanned
        to the assigned fronts in pipelined chunks."""
        cols = self.gen.step_columns(batch_cars=batch_cars)
        payloads = self._payloads(cols)
        per_front: List[List] = [[] for _ in range(self.n_fronts)]
        for i, c in enumerate(cols["car"]):
            ci = int(c)
            per_front[self.assign[ci]].append((self.topics[ci],
                                               payloads[i]))
        n = 0
        for j, items in enumerate(per_front):
            for k in range(0, len(items), chunk):
                n += self.clients[j].publish_many(items[k:k + chunk])
        self.published += n
        return n

    def close(self) -> None:
        for c in self.clients:
            try:
                c.disconnect()
            except OSError:
                pass


def run_federated_fleet(cars: int = 100_000, fronts: int = 2,
                        ticks: int = 2, shards: int = 2,
                        partitions: int = 8, seed: int = 20,
                        probe_per_front: int = 3,
                        timeout_s: float = 900.0) -> dict:
    """The reference's full 100,000-car scenario, live and federated:
    a wire-protocol stream broker, ``fronts`` MQTT front PROCESSES
    producing into it, a sharded gateway serving the resulting twins.

    Verifies the federation contract end to end — every record arrives
    (published == folded), and the gateway answers point lookups for
    cars entering through EVERY front.  Returns a report dict whose
    ``ok`` is the verdict."""
    import time

    from ..stream.broker import Broker
    from ..stream.kafka_wire import KafkaWireServer
    from .router import GatewayClient
    from .shards import GatewayCluster

    t_start = time.perf_counter()
    broker = Broker()
    broker.create_topic("SENSOR_DATA_S_AVRO", partitions=partitions)
    wire = KafkaWireServer(broker).start()
    procs: List[FrontProcess] = []
    fleet = None
    cluster = None
    client = None
    try:
        procs = [FrontProcess(f"127.0.0.1:{wire.port}",
                              partitions=partitions)
                 for _ in range(fronts)]
        scenario = FleetScenario(num_cars=cars, seed=seed)
        fleet = FederatedFleet(scenario, [p.mqtt_port for p in procs])
        cluster = GatewayCluster(broker, n_shards=shards).start()
        client = GatewayClient(cluster)

        for _ in range(ticks):
            fleet.publish_tick()
        deadline = time.monotonic() + timeout_s
        while client.aggregate()["records"] < fleet.published:
            if time.monotonic() >= deadline:
                break
            time.sleep(0.25)
        agg = client.aggregate()

        # lookups for cars that entered through each front, by the
        # shared assignment policy — the federation's consistency proof
        per_front_ok = []
        for j in range(fronts):
            got = 0
            want = 0
            for i in range(cars):
                if want >= probe_per_front:
                    break
                cid = scenario.car_id(i)
                if front_for(cid, fronts) != j:
                    continue
                want += 1
                doc = client.get(cid)
                if doc is not None and doc.get("car") == cid:
                    got += 1
            per_front_ok.append(got == want and want > 0)

        report = {
            "cars": cars, "fronts": fronts, "ticks": ticks,
            "shards": shards, "partitions": partitions,
            "native_fronts": sum(1 for p in procs if p.native),
            "published": fleet.published,
            "folded": agg["records"],
            "fleet_cars_served": agg["cars"],
            "per_front_lookups_ok": per_front_ok,
            "elapsed_s": round(time.perf_counter() - t_start, 2),
            "ok": (agg["records"] == fleet.published
                   and agg["cars"] == cars
                   and all(per_front_ok)),
        }
        return report
    finally:
        if client is not None:
            client.close()
        if cluster is not None:
            cluster.stop()
        if fleet is not None:
            fleet.close()
        for p in procs:
            p.stop()
        wire.shutdown()
        wire.server_close()
        broker.close()
