"""Scatter-gather routing over the sharded twin plane.

Two consumers, one map:

* ``GatewayClient`` — the SMART client (the repo's client-side-routing
  idiom, same shape as ``cluster.ClusterClient``): resolves the routing
  map once, routes point lookups straight to the owning shard over a
  persistent connection, scatters batch lookups / feature joins per
  shard, and re-resolves on connection errors, 421 NOT-OWNER answers,
  or 503 sheds.  It duck-types ``twin.TwinFeatureStore`` (``vector`` /
  ``matrix`` / ``dim``), so ``StreamScorer(feature_store=client)``
  joins per-car history through the gateway with no scorer changes.
* ``GatewayRouter`` — the DUMB-client front: mounts fleet-facing routes
  on an existing REST surface (the connect server, per the reference's
  "query the twin over the Connect API" shape) and does the scatter-
  gather server-side: ``GET /twin/{car}`` proxies to the owning shard,
  ``GET /twin`` and ``/gateway/aggregate`` fan out and merge,
  ``/gateway/map`` hands smart clients the map so they can stop paying
  the extra hop.

Key→owner is the same pure policy everywhere: the broker's keyed
partitioner (``crc32(key) % n_partitions`` — a cross-client invariant
of the produce path) composes with the cluster plane's
``partition % n_shards``.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import zlib
from typing import Dict, List, Optional

import numpy as np

from ..core.normalize import CAR_NORMALIZER, Normalizer
from ..utils.rest import RestError, RestServer


def partition_for_key(key, n_partitions: int) -> int:
    """The broker's keyed-produce partitioner (stream.broker and the
    native RAW_PRODUCE front agree on it byte-for-byte): which source —
    and therefore changelog — partition a car's records land in."""
    if isinstance(key, str):
        key = key.encode()
    return zlib.crc32(key) % int(n_partitions)


def shard_for_key(key, n_partitions: int, n_shards: int) -> int:
    """Key → owning serving shard (composition of the two pure
    policies; every party computes the same answer coordination-free)."""
    return partition_for_key(key, n_partitions) % int(n_shards)


class GatewayError(Exception):
    """A gateway query failed after map refreshes and retries."""


class _ShardConn:
    """One persistent keep-alive connection to a shard's REST surface."""

    def __init__(self, url: str, timeout_s: float):
        host, _, port = url.partition("://")[2].partition(":")
        self.url = url
        self.conn = http.client.HTTPConnection(host, int(port),
                                               timeout=timeout_s)

    def request(self, method: str, path: str,
                body: Optional[dict] = None):
        """(status, parsed json) — raises OSError family on transport
        failure; the caller owns refresh/retry policy."""
        payload = None
        headers = {}
        if body is not None:
            payload = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        try:
            self.conn.request(method, path, body=payload, headers=headers)
            resp = self.conn.getresponse()
            raw = resp.read()
        except Exception:
            # a dead keep-alive socket must not poison the next attempt
            self.close()
            raise
        doc = json.loads(raw) if raw else {}
        return resp.status, doc

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass


class GatewayClient:
    """Smart sharded-twin client; see the module docstring.

    Args:
      source: where the routing map comes from — a ``GatewayCluster``
        (in-process: ``map_doc()`` is read directly) or a URL string
        whose ``/gateway/map`` endpoint serves it (the router's).
      normalizer: feature-vector normalizer — fixes ``dim`` without a
        round trip, so the scorer can build its model input layout
        before the first join.
      retry_deadline_s: how long a query keeps refreshing + retrying
        across a failover window before it errors.  Bounds the drill's
        query-storm latency tail; committed cars must never need more
        than a promotion takes.
    """

    def __init__(self, source, normalizer: Normalizer = CAR_NORMALIZER,
                 timeout_s: float = 5.0, retry_deadline_s: float = 10.0):
        self._source = source
        self.timeout_s = float(timeout_s)
        self.retry_deadline_s = float(retry_deadline_s)
        self.normalizer = normalizer
        self.dim = len(normalizer.scale) + 2
        self._lock = threading.Lock()
        self._conns: Dict[int, _ShardConn] = {}
        self._map: dict = {}
        self.refreshes = 0
        self.refresh()

    # ---------------------------------------------------------------- map
    def _fetch_map(self) -> dict:
        if isinstance(self._source, str):
            import urllib.request

            with urllib.request.urlopen(
                    f"{self._source}/gateway/map",
                    timeout=self.timeout_s) as resp:
                return json.loads(resp.read())
        return self._source.map_doc()

    def refresh(self) -> dict:
        doc = self._fetch_map()
        with self._lock:
            old = {s["shard"]: s["url"]  # lint-ok: R4 dict .get below, not the blocking GatewayClient.get
                   for s in self._map.get("shards", [])}
            self._map = doc
            for s in doc["shards"]:
                if old.get(s["shard"]) != s["url"]:  # lint-ok: R4 dict .get, not the blocking GatewayClient.get
                    stale = self._conns.pop(s["shard"], None)
                    if stale is not None:
                        stale.close()
            self.refreshes += 1
        return doc

    @property
    def n_shards(self) -> int:
        return self._map["n_shards"]

    @property
    def n_partitions(self) -> int:
        return self._map["n_partitions"]

    def shard_of(self, car: str) -> int:
        return shard_for_key(car, self._map["n_partitions"],
                             self._map["n_shards"])

    def _conn_for(self, shard: int) -> _ShardConn:
        with self._lock:
            conn = self._conns.get(shard)  # lint-ok: R4 dict .get, not the blocking GatewayClient.get
            url = next(s["url"] for s in self._map["shards"]
                       if s["shard"] == shard)
            if conn is None or conn.url != url:
                if conn is not None:
                    conn.close()
                conn = self._conns[shard] = _ShardConn(url, self.timeout_s)
        return conn

    # -------------------------------------------------------------- calls
    def _call(self, shard: int, method: str, path: str,
              body: Optional[dict] = None, expect=(200,)):
        """One shard call under the refresh-and-retry discipline: a
        transport error, a 421 (stale map: the shard no longer owns the
        key), or a 503 (shed) re-resolves the map and retries until the
        deadline.  404 and other codes are real answers, returned."""
        deadline = time.monotonic() + self.retry_deadline_s
        delay = 0.02
        while True:
            try:
                status, doc = self._conn_for(shard).request(
                    method, path, body)
            except (OSError, http.client.HTTPException):
                status, doc = None, None
            if status is not None and status not in (421, 503):
                return status, doc
            if time.monotonic() >= deadline:
                raise GatewayError(
                    f"shard {shard} {method} {path}: no live owner "
                    f"within {self.retry_deadline_s}s "
                    f"(last status {status})")
            time.sleep(delay)
            delay = min(delay * 2, 0.25)
            try:
                self.refresh()
            except Exception:
                pass  # map source itself failing over: keep retrying

    # ------------------------------------------------------------ queries
    def get(self, car: str) -> Optional[dict]:
        """Point lookup — routed by key hash to the owning shard.
        None = the fleet has never seen this car (404)."""
        status, doc = self._call(self.shard_of(car), "GET",
                                 f"/shard/twin/{car}")
        if status == 404:
            return None
        if status != 200:
            raise GatewayError(f"GET /twin/{car}: {status} {doc}")
        return doc

    def retire(self, car: str) -> bool:
        status, doc = self._call(self.shard_of(car), "DELETE",
                                 f"/shard/twin/{car}")
        if status == 404:
            return False
        if status != 204:
            raise GatewayError(f"DELETE /twin/{car}: {status} {doc}")
        return True

    def _scatter_keys(self, keys: List[str]) -> Dict[int, List[int]]:
        by_shard: Dict[int, List[int]] = {}
        for i, car in enumerate(keys):
            by_shard.setdefault(self.shard_of(car), []).append(i)
        return by_shard

    def mget(self, cars: List[str]) -> List[Optional[dict]]:
        """Batched point lookups: scatter per owning shard, one
        pipelined round trip each, gather in request order.  None =
        unknown car.  Keys a shard disowns mid-flight (rebalance racing
        the scatter) re-resolve and retry individually."""
        out: List[Optional[dict]] = [None] * len(cars)
        missed: List[int] = []
        for shard, idxs in self._scatter_keys(cars).items():
            status, doc = self._call(shard, "POST", "/shard/mget",
                                     {"keys": [cars[i] for i in idxs]})
            if status != 200:
                raise GatewayError(f"mget on shard {shard}: {status}")
            not_owned = set(doc.get("not_owned", []))
            for j, i in enumerate(idxs):
                if j in not_owned:
                    missed.append(i)
                else:
                    out[i] = doc["docs"][j]
        for i in missed:
            # the ownership policy is pure, so one refreshed map agrees
            # with the shard that disowned the key
            self.refresh()
            status, doc = self._call(self.shard_of(cars[i]), "POST",
                                     "/shard/mget", {"keys": [cars[i]]})
            if status != 200 or doc.get("not_owned"):
                raise GatewayError(
                    f"mget: no shard owns {cars[i]!r} after refresh")
            out[i] = doc["docs"][0]
        return out

    def count(self) -> int:
        return sum(s["count"] for s in self._fan("/shard/info"))

    def cars(self, limit: int = 1000, offset: int = 0,
             prefix: str = "") -> List[str]:
        """Fleet-wide id listing: fan out, merge-sort, slice.  Each
        shard is asked only for the window that could contribute."""
        per_shard = min(limit + offset, 10_000)
        merged: List[str] = []
        for doc in self._fan(f"/shard/cars?limit={per_shard}"
                             f"&prefix={prefix}"):
            merged.extend(doc["cars"])
        merged.sort()
        return merged[offset:offset + limit]

    def aggregate(self) -> dict:
        """Fleet-wide sums, merged from every shard's local fold."""
        cars = records = failures = 0
        for doc in self._fan("/shard/aggregate"):
            cars += doc["cars"]
            records += doc["records"]
            failures += doc["failures"]
        return {"cars": cars, "records": records, "failures": failures,
                "failure_rate": failures / records if records else 0.0}

    def _fan(self, path: str) -> List[dict]:
        out = []
        for shard in range(self.n_shards):
            status, doc = self._call(shard, "GET", path)
            if status != 200:
                raise GatewayError(f"fan-out {path} on shard {shard}: "
                                   f"{status}")
            out.append(doc)
        return out

    # -------------------------------------------- feature-store duck-type
    def vector(self, key) -> np.ndarray:
        """[dim] float32 — one car's historical features via its shard."""
        out = self.matrix([key], 1)
        return out[0]

    def matrix(self, keys, n: int) -> np.ndarray:
        """[n, dim] float32 feature rows for a batch's keys — the
        sharded ``TwinFeatureStore.matrix``: scatter per owning shard,
        gather rows into position.  None keys, padding rows, and
        unknown cars are zero (the cold-start null the scorer already
        understands)."""
        out = np.zeros((n, self.dim), np.float32)
        if keys is None:
            return out
        want: List[str] = []
        pos: List[int] = []
        for i, k in enumerate(list(keys)[:n]):
            if not k:
                continue
            want.append(k.decode() if isinstance(k, bytes) else str(k))
            pos.append(i)
        for shard, idxs in self._scatter_keys(want).items():
            status, doc = self._call(shard, "POST", "/shard/matrix",
                                     {"keys": [want[i] for i in idxs]})
            if status != 200:
                raise GatewayError(f"matrix on shard {shard}: {status}")
            for j, i in enumerate(idxs):
                row = doc["rows"][j]
                if row is not None:
                    out[pos[i]] = row
        return out

    def close(self) -> None:
        with self._lock:
            for conn in self._conns.values():
                conn.close()
            self._conns.clear()


class GatewayRouter:
    """Mount the fleet-facing scatter-gather routes on a REST surface.

    ``mount(rest)`` registers on an existing server — by design the
    connect server, so the reference's "query the twin over the Connect
    API" URL shapes keep working when the twin behind them becomes a
    sharded fleet:

      GET /twin               paginated fleet listing (fan-out merge;
                              limit/offset/count_only as in the single-
                              twin surface)
      GET /twin/{car}         proxied point lookup
      DELETE /twin/{car}      proxied retire
      GET /gateway/map        the routing map (smart clients take over
                              from here and skip this extra hop)
      GET /gateway/aggregate  fleet-wide sums
      POST /gateway/mget      batched lookups for dumb clients
    """

    def __init__(self, cluster, client: Optional[GatewayClient] = None):
        self.cluster = cluster
        self.client = client if client is not None \
            else GatewayClient(cluster)

    def mount(self, rest: RestServer) -> "GatewayRouter":
        car = r"([^/]+)"
        rest.route("GET", r"/gateway/map", self._map)
        rest.route("GET", r"/gateway/aggregate", self._aggregate)
        rest.route("POST", r"/gateway/mget", self._mget)
        rest.route("GET", r"/twin", self._list)
        rest.route("GET", rf"/twin/{car}", self._get)
        rest.route("DELETE", rf"/twin/{car}", self._retire)
        return self

    # ------------------------------------------------------------- routes
    def _map(self, m, body):
        return 200, self.cluster.map_doc()

    def _aggregate(self, m, body):
        return 200, self.client.aggregate()

    def _mget(self, m, body):
        keys = body.get("keys")
        if not isinstance(keys, list):
            raise RestError(400, "mget body needs a 'keys' list")
        return 200, {"docs": self.client.mget([str(k) for k in keys])}

    def _list(self, m, body):
        count = self.client.count()
        out = {"count": count}
        if str(body.get("count_only", "")).lower() in ("1", "true", "yes"):
            return 200, out
        try:
            limit = int(body.get("limit", 1000))
            offset = int(body.get("offset", 0))
        except (TypeError, ValueError):
            raise RestError(400, "limit/offset must be integers")
        if limit < 0 or offset < 0:
            raise RestError(400, "limit/offset must be >= 0")
        page = self.client.cars(limit=limit, offset=offset,
                                prefix=str(body.get("prefix", "")))
        out["cars"] = page
        out["offset"] = offset
        out["limit"] = limit
        nxt = offset + len(page)
        out["next_offset"] = nxt if nxt < count else None
        return 200, out

    def _get(self, m, body):
        doc = self.client.get(m.group(1))
        if doc is None:
            raise RestError(404, f"no twin for car {m.group(1)!r}")
        return 200, doc

    def _retire(self, m, body):
        if not self.client.retire(m.group(1)):
            raise RestError(404, f"no twin for car {m.group(1)!r}")
        return 204, {}
