"""Sharded twin serving plane: primaries + warm standby replicas.

The digital twin is already partition-sharded at the STORAGE layer: car
keys hash to source partitions, the ``CAR_TWIN`` changelog mirrors the
source partitioning 1:1, and ``TwinService(partitions=...)`` materialises
any partition subset with no cross-talk.  This module turns that latent
shardability into a SERVING plane (ISSUE 20)::

    SENSOR_DATA_S_AVRO (P partitions)
      ├─ shard 0: TwinService(partitions=[p: p%N==0]) → REST :port0
      ├─ shard 1: TwinService(partitions=[p: p%N==1]) → REST :port1
      └─ ...                        │ changelog (same partition numbers)
                                    ▼
    CAR_TWIN (compacted) ──────► TwinStandby per shard: a warm shadow
                                 table rebuilt CONTINUOUSLY from the
                                 changelog (Kafka Streams standby-
                                 replica pattern)

Shard ownership is the cluster plane's pure policy
(``PartitionMap.shard_for``: ``partition % n_shards``), so routers,
clients, and shards all compute the same owner with no coordination.
Leadership lives in the same ``PartitionMap``/``Topology`` cells the
broker cluster uses: a shard kill promotes its standby — the warm table
is ADOPTED by a fresh ``TwinService`` (``table=``/``rebuild_from=``
replay only the changelog delta), a new REST surface mounts, and the
map publishes ``(new_url, epoch+1)``.  Promotion moves one shard, not
the world.

Everything here is drilled live (``python -m iotml.gateway drill``):
standby-equals-primary byte equality, promotion inside the SLO under a
query storm, zero wrong answers for committed cars.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..cluster.partition_map import PartitionMap
from ..core.schema import KSQL_CAR_SCHEMA, RecordSchema
from ..obs.metrics import default_registry
from ..stream.broker import OffsetOutOfRangeError
from ..twin.features import TwinFeatureStore
from ..twin.service import CHANGELOG_TOPIC, TwinDriver, TwinService
from ..twin.state import DEFAULT_WINDOW, TwinTable
from ..utils.rest import RestError, RestServer
from .router import partition_for_key

gateway_promotions = default_registry.counter(
    "iotml_gateway_promotions_total",
    "standby-to-primary promotions, by shard")
gateway_standby_lag = default_registry.gauge(
    "iotml_gateway_standby_lag_records",
    "changelog records a shard's warm standby has not yet applied")


class TwinStandby:
    """Warm shadow TwinTable for one shard's changelog partitions.

    Follows ``CAR_TWIN`` continuously (``catch_up()`` on a driver
    thread), tracking per-partition replay positions.  On promotion the
    table and positions hand over to ``TwinService(table=...,
    rebuild_from=...)`` so only the in-flight delta replays — the
    standby IS the rebuild, paid incrementally while the primary was
    healthy."""

    def __init__(self, broker, partitions, window: int = DEFAULT_WINDOW,
                 changelog_topic: str = CHANGELOG_TOPIC):
        self.broker = broker
        self.changelog_topic = changelog_topic
        self.partitions = sorted(int(p) for p in partitions)
        self.table = TwinTable(window=window)
        #: next changelog offset to apply, per partition
        self.positions: Dict[int, int] = {p: 0 for p in self.partitions}
        self.applied = 0

    def catch_up(self, max_records: int = 65536) -> int:
        """Apply new changelog records into the warm table; returns how
        many were applied this pass."""
        applied = 0
        for p in self.partitions:
            off = self.positions[p]
            try:
                end = self.broker.end_offset(self.changelog_topic, p)
            except KeyError:
                continue
            while off < end and applied < max_records:
                try:
                    batch = self.broker.fetch(self.changelog_topic, p, off,
                                              4096)
                except OffsetOutOfRangeError as e:
                    off = e.earliest
                    continue
                if not batch:
                    # compaction holes between segments end a batch
                    # early; past the last record the log is drained
                    break
                for m in batch:
                    if m.key is not None:
                        self.table.apply_changelog(m.key.decode(), m.value)
                        applied += 1
                off = batch[-1].offset + 1
            self.positions[p] = off
        self.applied += applied
        return applied

    def lag(self) -> int:
        """Changelog records not yet applied (promotion catch-up cost)."""
        total = 0
        for p in self.partitions:
            try:
                total += max(0, self.broker.end_offset(self.changelog_topic,
                                                       p)
                             - self.positions[p])
            except KeyError:
                continue
        return total


class StandbyDriver:
    """Background catch-up pump for one TwinStandby (R8-supervised)."""

    def __init__(self, standby: TwinStandby, shard: int,
                 poll_interval_s: float = 0.05):
        self.standby = standby
        self.shard = shard
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "StandbyDriver":
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self._run, daemon=True,
            name=f"iotml-gw-standby-{self.shard}"))
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            n = self.standby.catch_up()
            gateway_standby_lag.set(self.standby.lag(),
                                    shard=str(self.shard))
            if n == 0:
                self._stop.wait(self.poll_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


class GatewayShard:
    """One serving shard: a primary TwinService over its owned
    partitions + the shard-local REST surface the router and smart
    clients scatter to.

    Shard-local routes (all under ``/shard``; the fleet-facing surface
    is the router's):

      GET  /shard/info            → shard id, owned partitions, count
      GET  /shard/twin/{car}      → full twin doc (421 when not owned —
                                    the client's refresh-and-retry cue)
      POST /shard/mget            {"keys": [...]} → slim docs per key
      POST /shard/matrix          {"keys": [...]} → feature rows [k,dim]
      GET  /shard/cars            paginated local ids
      GET  /shard/aggregate       local fleet sums for fan-out merges
      DELETE /shard/twin/{car}    retire through the owning primary
    """

    def __init__(self, broker, shard_id: int, n_shards: int,
                 source_topic: str = "SENSOR_DATA_S_AVRO",
                 schema: RecordSchema = KSQL_CAR_SCHEMA,
                 window: int = DEFAULT_WINDOW,
                 group_prefix: str = "iotml-gw",
                 host: str = "127.0.0.1",
                 table: Optional[TwinTable] = None,
                 rebuild_from: Optional[Dict[int, int]] = None,
                 poll_interval_s: float = 0.02):
        self.shard_id = int(shard_id)
        self.n_shards = int(n_shards)
        self.broker = broker
        n_parts = broker.topic(source_topic).partitions
        self.n_partitions = n_parts
        self.owned = [p for p in range(n_parts)
                      if p % n_shards == self.shard_id]
        self._owned_set = frozenset(self.owned)
        self.service = TwinService(
            broker, source_topic=source_topic, partitions=self.owned,
            group=f"{group_prefix}-{self.shard_id}", schema=schema,
            window=window, table=table, rebuild_from=rebuild_from)
        self.features = TwinFeatureStore(self.service)
        self.driver = TwinDriver(self.service,
                                 poll_interval_s=poll_interval_s)
        self.rest = RestServer(host=host,
                               name=f"iotml-gw-shard{self.shard_id}")
        car = r"([^/]+)"
        self.rest.route("GET", r"/shard/info", self._info)
        self.rest.route("GET", rf"/shard/twin/{car}", self._get)
        self.rest.route("DELETE", rf"/shard/twin/{car}", self._retire)
        self.rest.route("POST", r"/shard/mget", self._mget)
        self.rest.route("POST", r"/shard/matrix", self._matrix)
        self.rest.route("GET", r"/shard/cars", self._cars)
        self.rest.route("GET", r"/shard/aggregate", self._aggregate)
        self.alive = False

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "GatewayShard":
        self.rest.start()
        self.driver.start()
        self.alive = True
        return self

    def stop(self) -> None:
        self.alive = False
        self.driver.stop()
        self.rest.kill()

    def kill(self) -> None:
        """Crash-shaped death for drills: the REST surface drops (every
        established keep-alive connection severed — a zombie answering
        stale state on old sockets is a WRONG answer) and the pump
        stops — nothing is flushed, nothing says goodbye.  The only
        durable trace of this shard's work is the changelog (exactly
        the guarantee the standby rebuilds from)."""
        self.alive = False
        self.rest.kill()
        self.driver.stop()

    @property
    def url(self) -> str:
        return self.rest.url

    # -------------------------------------------------------------- owner
    def _owns(self, car: str) -> bool:
        return partition_for_key(car, self.n_partitions) in self._owned_set

    def _require_owner(self, car: str) -> None:
        if not self._owns(car):
            # 421 Misdirected Request: the caller's map is stale — its
            # cue to refresh /gateway/map and re-route, never an answer
            raise RestError(421, f"shard {self.shard_id} does not own "
                            f"{car!r}")

    # -------------------------------------------------------------- routes
    def _info(self, m, body):
        return 200, {"shard": self.shard_id, "n_shards": self.n_shards,
                     "partitions": self.owned,
                     "count": self.service.count(),
                     "rebuilt_from_changelog": self.service.rebuilt_records}

    def _get(self, m, body):
        car = m.group(1)
        self._require_owner(car)
        doc = self.service.get(car)
        if doc is None:
            raise RestError(404, f"no twin for car {car!r}")
        return 200, doc

    def _retire(self, m, body):
        car = m.group(1)
        self._require_owner(car)
        if not self.service.retire(car):
            raise RestError(404, f"no twin for car {car!r}")
        return 204, {}

    def _slim(self, car: str) -> Optional[dict]:
        twin = self.service.table.get(car)
        if twin is None:
            return None
        return {"car": twin.car, "partition": twin.partition,
                "offset": twin.offset, "ts": twin.ts,
                "count": twin.count, "failures": twin.failures}

    def _mget(self, m, body):
        """Pipelined point lookups: one round trip answers a key batch.
        Slim docs (identity + provenance + lifetime counts) keep the
        reply ~60B/key so the wire cost stays linear in keys, not in
        window depth; ``not_owned`` indexes are the scatter client's
        refresh-and-retry cue for exactly those keys."""
        keys = body.get("keys")
        if not isinstance(keys, list):
            raise RestError(400, "mget body needs a 'keys' list")
        docs: List[Optional[dict]] = []
        not_owned: List[int] = []
        for i, car in enumerate(keys):
            car = str(car)
            if not self._owns(car):
                docs.append(None)
                not_owned.append(i)
                continue
            docs.append(self._slim(car))
        return 200, {"shard": self.shard_id, "docs": docs,
                     "not_owned": not_owned}

    def _matrix(self, m, body):
        """Feature-vector scatter leg: rows for the keys this shard
        owns, in request order — the server half of the sharded
        ``TwinFeatureStore.matrix`` join `StreamScorer(feature_store=)`
        rides."""
        keys = body.get("keys")
        if not isinstance(keys, list):
            raise RestError(400, "matrix body needs a 'keys' list")
        rows: List[Optional[list]] = []
        not_owned: List[int] = []
        for i, car in enumerate(keys):
            car = str(car)
            if not self._owns(car):
                rows.append(None)
                not_owned.append(i)
                continue
            rows.append([float(v) for v in self.features.vector(car.encode())])
        return 200, {"shard": self.shard_id, "dim": self.features.dim,
                     "rows": rows, "not_owned": not_owned}

    def _cars(self, m, body):
        try:
            limit = int(body.get("limit", 1000))
            offset = int(body.get("offset", 0))
        except (TypeError, ValueError):
            raise RestError(400, "limit/offset must be integers")
        prefix = str(body.get("prefix", ""))
        cars = self.service.cars(prefix=prefix)
        return 200, {"shard": self.shard_id, "count": len(cars),
                     "cars": cars[offset:offset + limit]}

    def _aggregate(self, m, body):
        """Local sums for the router's fleet-wide merge."""
        records = 0
        failures = 0
        for twin in self.service.table.twins.values():
            records += twin.count
            failures += twin.failures
        return 200, {"shard": self.shard_id,
                     "cars": self.service.count(),
                     "records": records, "failures": failures}


class GatewayCluster:
    """N serving shards + their standbys + the leadership map.

    The in-process cluster-of-record for drills, benches and the
    platform CLI: shards serve over real HTTP (each on its own
    ephemeral port), the ``PartitionMap`` holds shard URLs in its
    ``Topology`` cells, and ``promote()`` is the standby-replica
    failover the gateway drill kills shards to exercise."""

    #: drill SLO: a killed shard's standby must be promoted and serving
    #: within this budget (catch-up + service adoption + REST mount)
    PROMOTE_SLO_S = 5.0

    def __init__(self, broker, n_shards: int = 2,
                 source_topic: str = "SENSOR_DATA_S_AVRO",
                 schema: RecordSchema = KSQL_CAR_SCHEMA,
                 window: int = DEFAULT_WINDOW,
                 standbys: bool = True,
                 host: str = "127.0.0.1"):
        if n_shards < 1:
            raise ValueError("a gateway needs at least one shard")
        self.broker = broker
        self.source_topic = source_topic
        self.schema = schema
        self.window = window
        self.host = host
        self.n_shards = int(n_shards)
        self.shards: List[GatewayShard] = [
            GatewayShard(broker, i, n_shards, source_topic=source_topic,
                         schema=schema, window=window, host=host)
            for i in range(n_shards)]
        self.n_partitions = self.shards[0].n_partitions
        self.pmap = PartitionMap([s.url for s in self.shards])
        self.pmap.register_topic(CHANGELOG_TOPIC, self.n_partitions)
        self.pmap.register_topic(source_topic, self.n_partitions)
        self.standbys: Dict[int, TwinStandby] = {}
        self.standby_drivers: Dict[int, StandbyDriver] = {}
        if standbys:
            for s in self.shards:
                self.standbys[s.shard_id] = TwinStandby(
                    broker, s.owned, window=window)
                self.standby_drivers[s.shard_id] = StandbyDriver(
                    self.standbys[s.shard_id], s.shard_id)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "GatewayCluster":
        for s in self.shards:
            s.start()
        for d in self.standby_drivers.values():
            d.start()
        return self

    def stop(self) -> None:
        for d in self.standby_drivers.values():
            d.stop()
        for s in self.shards:
            if s.alive:
                s.stop()

    # ------------------------------------------------------------- facade
    def shard_for_key(self, car: str) -> int:
        return self.pmap.shard_for(self.source_topic,
                                   partition_for_key(car, self.n_partitions))

    def map_doc(self) -> dict:
        """The routing map clients resolve (also served as
        ``GET /gateway/map`` by the router): shard → live URL + fencing
        epoch, plus the pure policy inputs (topic partition count and
        shard count) every party derives ownership from."""
        return {
            "topic": self.source_topic,
            "n_partitions": self.n_partitions,
            "n_shards": self.n_shards,
            "generation": self.pmap.generation,
            "shards": [{"shard": i, "url": self.pmap.leader(i),
                        "epoch": self.pmap.epoch(i)}
                       for i in range(self.n_shards)],
        }

    def counts(self) -> List[int]:
        return [s.service.count() for s in self.shards]

    # ------------------------------------------------------------ failover
    def kill_shard(self, shard: int) -> GatewayShard:
        """Crash a primary (drill hook); returns the corpse for
        post-mortem snapshots."""
        corpse = self.shards[shard]
        corpse.kill()
        return corpse

    def promote(self, shard: int) -> float:
        """Standby-replica failover: drain the standby's changelog
        delta, adopt its warm table into a fresh primary, mount a new
        REST surface, publish the new (url, epoch).  Returns seconds
        from call to published — the drill's ``promote_s`` SLO.

        The new primary's delta replay (``rebuild_from``) starts at the
        standby's positions, so promotion cost is proportional to the
        standby's LAG, not to the table size — the whole point of
        paying the rebuild continuously."""
        t0 = time.perf_counter()
        standby = self.standbys.get(shard)
        if standby is None:
            raise ValueError(f"shard {shard} has no standby to promote")
        driver = self.standby_drivers.pop(shard, None)
        if driver is not None:
            driver.stop()
        standby.catch_up()  # drain the tail the driver hadn't reached
        replacement = GatewayShard(
            self.broker, shard, self.n_shards,
            source_topic=self.source_topic, schema=self.schema,
            window=self.window, host=self.host,
            table=standby.table, rebuild_from=dict(standby.positions))
        replacement.start()
        self.shards[shard] = replacement
        # a FRESH standby shadows the promoted primary: the next kill
        # must find the same warm-follower protection in place
        self.standbys[shard] = TwinStandby(self.broker, replacement.owned,
                                           window=self.window)
        self.standby_drivers[shard] = StandbyDriver(
            self.standbys[shard], shard).start()
        self.pmap.publish(shard, replacement.url,
                          self.pmap.epoch(shard) + 1)
        gateway_promotions.inc(shard=str(shard))
        return time.perf_counter() - t0
