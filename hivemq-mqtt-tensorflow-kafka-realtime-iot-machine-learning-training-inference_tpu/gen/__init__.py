from .simulator import FleetScenario, FleetGenerator  # noqa: F401
from .scenarios import (AdversarialFleet, FleetCondition,  # noqa: F401
                        FLEET_CONDITIONS, condition)
