from .simulator import FleetScenario, FleetGenerator  # noqa: F401
