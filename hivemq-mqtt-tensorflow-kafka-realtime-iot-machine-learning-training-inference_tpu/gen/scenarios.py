"""Adversarial fleet conditions — the nastier streams ROADMAP item 5
asks for.

`simulator.FleetGenerator` reproduces the reference's benign scenario
XML: a steady fleet, i.i.d. sensor noise, rare labeled failures.  Real
fleets are nastier in ways that stress specific subsystems, and each
condition here targets one:

- **rush-hour**: 10× publish bursts inside a tick window — the
  backpressure path (`MqttBroker.saturated()`): agents defer into
  their own bounded buffer instead of pushing broker queues into
  drop-oldest.
- **flapping-links**: per-car cellular links drop and recover
  (seeded Markov chain, the chaos mqtt-flap shape at fleet scale);
  a down car stores-and-forwards its readings on recovery.
- **regional-drift**: cars belong to regional cohorts with skewed
  sensor distributions, and selected cohorts SHIFT distribution at a
  seeded tick (step or ramp) — the benign drift that poisons a frozen
  anomaly detector with false positives until `iotml.online` adapts.
- **schema-mix**: a fraction of the fleet publishes writer-schema v2
  (REGION field, `core.schema.KSQL_CAR_SCHEMA_V2`) onto the same live
  topic — the rolling-upgrade mix v1 readers must resolve.
- **drift-storm**: regional drift on every cohort at once, built to
  run UNDER the chaos mqtt-flap schedule (`iotml.chaos` drift-storm
  scenario) — drift and infrastructure failure concurrently.

Everything is seeded and wall-clock-free: the same (scenario,
condition, seed) triple generates the byte-identical stream, which is
what lets bench score each condition with the detection-quality and
saturation harnesses instead of merely narrating it.
"""

from __future__ import annotations

import collections
import dataclasses
import json
from typing import Dict, Optional

import numpy as np

from ..core.schema import (CAR_SCHEMA, CAR_SCHEMA_V2_ID,
                           KSQL_CAR_SCHEMA, KSQL_CAR_SCHEMA_V2)
from ..obs import metrics as obs_metrics
from ..obs import tracing
from ..ops.avro import AvroCodec
from ..ops.framing import frame
from .simulator import FleetGenerator, FleetScenario


@dataclasses.dataclass(frozen=True)
class FleetCondition:
    """One adversarial condition over a base fleet scenario."""

    name: str
    description: str = ""
    #: [start, end) tick window publishing at burst_multiplier× rate
    burst_ticks: Optional[tuple] = None
    burst_multiplier: int = 10
    #: per-car per-tick P(link goes down) / P(down link recovers)
    flap_down: float = 0.0
    flap_up: float = 0.5
    #: regional cohorts (car i belongs to cohort i % regions)
    regions: int = 1
    #: static cohort skew: cohorts sit at slightly different operating
    #: points (scale of the per-region offset vector)
    region_skew: float = 0.0
    #: seeded distribution shift: at drift_tick the cohorts in
    #: drift_regions (None = all) move their operating point by
    #: drift_scale, as a step (ramp_ticks=0) or linear ramp
    drift_tick: Optional[int] = None
    drift_regions: Optional[tuple] = None
    drift_scale: float = 1.0
    drift_ramp_ticks: int = 0
    #: fraction of records encoded under writer schema v2
    schema_v2_fraction: float = 0.0


#: the scenario suite bench + chaos drill by name
FLEET_CONDITIONS: Dict[str, FleetCondition] = {
    "baseline": FleetCondition(
        "baseline", "the reference's benign fleet, unmodified"),
    "rush-hour": FleetCondition(
        "rush-hour",
        "10x publish burst in a tick window; agents must respect the "
        "MQTT backpressure signal instead of overrunning queues",
        burst_ticks=(4, 8), burst_multiplier=10),
    "flapping-links": FleetCondition(
        "flapping-links",
        "per-car cellular links flap (seeded Markov chain); down cars "
        "store-and-forward on recovery",
        flap_down=0.08, flap_up=0.35),
    "regional-drift": FleetCondition(
        "regional-drift",
        "4 regional cohorts at skewed operating points; three cohorts "
        "step-shift their distribution mid-stream (benign drift: "
        "labels stay normal, reconstruction error does not)",
        regions=4, region_skew=0.3, drift_regions=(1, 2, 3),
        drift_scale=1.0),
    "schema-mix": FleetCondition(
        "schema-mix",
        "40% of the fleet publishes writer-schema v2 (REGION field) "
        "onto the live topic; v1 readers resolve instead of DLQ",
        regions=2, schema_v2_fraction=0.4),
    "drift-storm": FleetCondition(
        "drift-storm",
        "every cohort shifts at once — run under the chaos mqtt-flap "
        "schedule for drift + infrastructure failure concurrently",
        regions=4, region_skew=0.2, drift_scale=1.5),
}


def condition(name: str, **overrides) -> FleetCondition:
    """Look up a suite condition, optionally overriding knobs (e.g.
    ``condition("regional-drift", drift_tick=40)``)."""
    if name not in FLEET_CONDITIONS:
        raise KeyError(f"unknown fleet condition {name!r} "
                       f"(have: {sorted(FLEET_CONDITIONS)})")
    base = FLEET_CONDITIONS[name]
    return dataclasses.replace(base, **overrides) if overrides else base


#: (column, per-unit offset) the cohort skew/drift vector moves — the
#: "harsh-terrain cohort" shape.  Two hard constraints, both measured
#: against a converged parity model:
#:
#: 1. A dense autoencoder reconstructs a pure TRANSLATION of its
#:    training distribution almost as well as the original (whole-
#:    fleet mean shifts moved its error < 5%), so detectable drift
#:    must break learned STRUCTURE: the asymmetric tire-pressure
#:    shifts (uneven load/wear across the axles — the four tire
#:    columns are strongly correlated in training data) carry most of
#:    the signal (+50-60% fleet error at scale 1).
#: 2. The vector must stay ORTHOGONAL to the injected failure
#:    signatures, or drifted-normal rows permanently overlap failure
#:    rows and no adaptation can recover detection AUC: vibration
#:    (failure mode 0's spike) and tire_pressure_1_1 (mode 1's
#:    blowout column) are deliberately untouched.
#:
#: Coolant/voltage shifts ride along for full-normalization
#: deployments (the PARITY normalizer zeroes them).  Labels stay
#: "false": this is drift, not failure.
_DRIFT_COLUMNS = (
    ("speed", 8.0),
    ("coolant_temp", 10.0),
    ("intake_air_temp", 6.0),
    ("battery_voltage", -14.0),
    ("throttle_pos", 0.12),
    ("tire_pressure_1_2", -8.0),
    ("tire_pressure_2_1", -10.0),
    ("tire_pressure_2_2", 8.0),
)
_CLIPS = {"speed": (0.0, 50.0), "throttle_pos": (0.0, 1.0)}


class AdversarialFleet:
    """A FleetGenerator driven through a FleetCondition.

    The base generator's RNG stream is untouched (the same seed
    produces the same underlying fleet with or without a condition);
    condition draws — flaps, schema choice — come from a separate
    seeded RNG, so conditions compose deterministically.
    """

    def __init__(self, scenario: Optional[FleetScenario] = None,
                 cond: Optional[FleetCondition] = None,
                 defer_limit: int = 10_000):
        self.scenario = scenario or FleetScenario()
        self.cond = cond or FLEET_CONDITIONS["baseline"]
        self.gen = FleetGenerator(self.scenario)
        self.rng = np.random.default_rng(self.scenario.seed + 0x5EED)
        n = self.scenario.num_cars
        self.region = np.arange(n) % max(1, self.cond.regions)
        self.link_up = np.ones(n, bool)
        #: per-car store-and-forward buffers for down links (bounded)
        self._car_buffers: Dict[int, collections.deque] = {}
        #: fleet-side deferral buffer under MQTT backpressure (bounded:
        #: a fleet cannot hold infinite history either — past the limit
        #: the OLDEST deferred reading drops, counted)
        self.deferred: collections.deque = collections.deque(
            maxlen=max(1, defer_limit))
        self.deferred_total = 0
        self.defer_dropped = 0
        self.flap_buffered_total = 0
        self.published = 0

    # -------------------------------------------------------- generation
    def _tick_reps(self) -> int:
        c = self.cond
        if c.burst_ticks is None:
            return 1
        lo, hi = c.burst_ticks
        return c.burst_multiplier if lo <= self.gen.tick < hi else 1

    def _drift_amount(self) -> float:
        c = self.cond
        if c.drift_tick is None or self.gen.tick < c.drift_tick:
            return 0.0
        if c.drift_ramp_ticks <= 0:
            return c.drift_scale
        frac = (self.gen.tick - c.drift_tick) / c.drift_ramp_ticks
        return c.drift_scale * min(1.0, frac)

    def step_columns(self) -> dict:
        """One fleet tick with cohort skew + active drift applied."""
        # _drift_amount reads gen.tick BEFORE step_columns advances it,
        # so "drift at tick K" means the K-th emitted tick is shifted
        amount = self._drift_amount()
        cols = self.gen.step_columns()
        c = self.cond
        if c.regions <= 1 or (c.region_skew == 0.0 and amount == 0.0):
            return cols
        reg = self.region[cols["car"]]
        # static skew: cohorts spread symmetrically around the fleet
        # mean; drift: the selected cohorts move by `amount` more
        spread = (reg - (c.regions - 1) / 2.0) / max(c.regions - 1, 1)
        shift = spread * c.region_skew
        if amount:
            in_drift = np.ones(len(reg), bool) if c.drift_regions is None \
                else np.isin(reg, c.drift_regions)
            shift = shift + in_drift * amount
        for col, per_unit in _DRIFT_COLUMNS:
            vals = cols[col].astype(np.float64) + shift * per_unit
            if col in _CLIPS:
                vals = np.clip(vals, *_CLIPS[col])
            cols[col] = vals.astype(cols[col].dtype)
        return cols

    def region_name(self, car: int) -> str:
        return f"region-{self.region[car]}"

    # ------------------------------------------------------ stream (avro)
    def publish_stream(self, broker, topic: str, n_ticks: int = 1,
                       partitions: int = 1) -> int:
        """Framed-Avro publish straight onto a stream topic (the
        broker-direct ingest leg), with burst multiplication and the
        schema-version mix.  v2 records carry the car's REGION."""
        broker.create_topic(topic, partitions=partitions)
        codec_v1 = AvroCodec(KSQL_CAR_SCHEMA)
        codec_v2 = AvroCodec(KSQL_CAR_SCHEMA_V2)
        count = 0
        for _ in range(n_ticks):
            for _ in range(self._tick_reps()):
                cols = self.step_columns()
                n = len(cols["car"])
                ts = int(self.gen.t * 1000)
                v2 = self.rng.random(n) < self.cond.schema_v2_fraction
                for i in range(n):
                    car = int(cols["car"][i])
                    rec = self.gen.row_record(cols, i, KSQL_CAR_SCHEMA)
                    if v2[i]:
                        rec["REGION"] = self.region_name(car)
                        payload = frame(codec_v2.encode(rec),
                                        CAR_SCHEMA_V2_ID)
                    else:
                        payload = frame(codec_v1.encode(rec), 1)
                    hdrs = tracing.birth_headers("devsim_publish") \
                        if tracing.ENABLED else None
                    broker.produce(
                        topic, payload,
                        key=self.scenario.car_id(car).encode(),
                        partition=None if partitions > 1 else 0,
                        timestamp_ms=ts, headers=hdrs)
                    count += 1
        self.published += count
        return count

    # -------------------------------------------------------- mqtt (json)
    def _flap_step(self) -> None:
        c = self.cond
        if c.flap_down <= 0:
            return
        n = len(self.link_up)
        go_down = self.rng.random(n) < c.flap_down
        come_up = self.rng.random(n) < c.flap_up
        self.link_up = np.where(self.link_up, ~go_down, come_up)

    def _publish_one(self, mqtt, topic: str, payload: bytes,
                     qos: int) -> bool:
        """One cooperative publish: defer under backpressure instead of
        letting the broker's bounded queues drop-oldest."""
        if mqtt.saturated():
            if len(self.deferred) == self.deferred.maxlen:
                self.defer_dropped += 1
            self.deferred.append((topic, payload, qos))
            self.deferred_total += 1
            obs_metrics.fleet_deferred.inc()
            return False
        mqtt.publish(topic, payload, qos=qos)
        self.published += 1
        return True

    def _drain_deferred(self, mqtt) -> int:
        n = 0
        while self.deferred and not mqtt.saturated():
            topic, payload, qos = self.deferred.popleft()
            mqtt.publish(topic, payload, qos=qos)
            self.published += 1
            n += 1
        return n

    def publish_mqtt(self, mqtt, n_ticks: int = 1, qos: int = 1,
                     topic_prefix: str = "vehicles/sensor/data") -> int:
        """Per-car JSON publishes over MQTT (the device fleet leg) with
        link flapping (store-and-forward) and backpressure deferral.
        Returns publishes DELIVERED to the broker this call; deferred
        and link-buffered readings drain on later ticks."""
        delivered = 0
        for _ in range(n_ticks):
            for _ in range(self._tick_reps()):
                delivered += self._drain_deferred(mqtt)
                self._flap_step()
                cols = self.step_columns()
                n = len(cols["car"])
                for i in range(n):
                    car = int(cols["car"][i])
                    rec = self.gen.row_record(cols, i, CAR_SCHEMA)
                    rec["failure_occurred"] = \
                        str(cols["failure_occurred"][i])
                    if self.cond.regions > 1:
                        rec["region"] = self.region_name(car)
                    topic = f"{topic_prefix}/{self.scenario.car_id(car)}"
                    payload = json.dumps(rec).encode()
                    if not self.link_up[car]:
                        # cellular dead spot: the device stores and
                        # forwards — bounded, oldest dropped (a real
                        # device's ring buffer)
                        buf = self._car_buffers.setdefault(
                            car, collections.deque(maxlen=64))
                        buf.append((topic, payload))
                        self.flap_buffered_total += 1
                        continue
                    buf = self._car_buffers.get(car)
                    while buf:
                        t2, p2 = buf.popleft()
                        if self._publish_one(mqtt, t2, p2, qos):
                            delivered += 1
                    if self._publish_one(mqtt, topic, payload, qos):
                        delivered += 1
        return delivered

    def describe(self) -> dict:
        return {"condition": self.cond.name, "tick": self.gen.tick,
                "published": self.published,
                "deferred_total": self.deferred_total,
                "deferred_pending": len(self.deferred),
                "defer_dropped": self.defer_dropped,
                "flap_buffered": self.flap_buffered_total,
                "links_down": int((~self.link_up).sum())}
