"""Car-fleet load generator — the device-simulator equivalent.

The reference drives its demo with an external Java commander/agent fleet
(`sbaier1/device-simulator:avro`, scenario XML: 100k clients named
`electric-vehicle-[0-9]{5}`, 1 msg/10 s, 3000 msgs/car — reference
`infrastructure/test-generator/scenario.xml`), whose payloads come from
`com.hivemq.CarDataPayloadGenerator` with injected failure modes.  That
simulator is also the reference's only "multi-node test cluster" (SURVEY §4).

This module is the TPU-framework-native rebuild: a vectorized numpy fleet
simulator with per-car latent state, physically-plausible sensor
correlations (vibration tracks speed — the reference's own docstring notes
`speed * 150 or speed * 100`, cardata-v3.py:129), failure modes that perturb
the relevant sensors and set the label, and a scenario config mirroring the
XML knobs (fleet size, per-car rate, message count, ramp-up).  It emits
producer-schema or KSQL-schema records, raw columns (fast path for
benchmarks), or framed-Avro broker messages.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np

from ..core.schema import CAR_SCHEMA, KSQL_CAR_SCHEMA, RecordSchema
from ..obs import tracing
from ..ops.avro import AvroCodec
from ..ops.framing import frame


@dataclasses.dataclass
class FleetScenario:
    """Scenario knobs, mirroring the reference XML (scenario.xml:11-52)."""

    num_cars: int = 25  # reference evaluation scenario size
    msgs_per_car: int = 40
    interval_s: float = 5.0
    ramp_up_s: float = 5.0
    failure_rate: float = 0.01  # fraction of cars that develop a failure
    #: (min_tick, max_tick): failing cars develop their failure at a
    #: uniform-random tick in this range instead of from birth — the
    #: realistic predictive-maintenance shape (a healthy car drifts into
    #: a fault), and what per-car baseline/drift detection needs.  None
    #: keeps the from-birth behavior.
    failure_onset_ticks: Optional[tuple] = None
    seed: int = 7

    @classmethod
    def full(cls):
        """The 100k-car scenario (scenario.xml:13-14,48-49)."""
        return cls(num_cars=100_000, msgs_per_car=3000, interval_s=10.0,
                   ramp_up_s=20.0)

    def car_id(self, i: int) -> str:
        return f"electric-vehicle-{i:05d}"


class FleetGenerator:
    """Stateful vectorized simulator over a fleet scenario."""

    def __init__(self, scenario: FleetScenario = FleetScenario()):
        self.scenario = scenario
        n = scenario.num_cars
        rng = np.random.default_rng(scenario.seed)
        self.rng = rng
        # Per-car latent state.
        self.speed = rng.uniform(0.0, 30.0, n)
        self.battery = rng.uniform(40.0, 100.0, n)
        self.firmware = rng.choice([1000, 2000], n).astype(np.int32)
        self.tire_base = rng.uniform(28.0, 33.0, (n, 4))
        # Failure state: -1 = healthy, else index of failing mode.
        self.failing = np.full(n, -1, np.int32)
        fail_cars = rng.random(n) < scenario.failure_rate
        self.failing[fail_cars] = rng.integers(0, 3, fail_cars.sum())
        # onset tick per failing car (0 = from birth)
        self.onset = np.zeros(n, np.int64)
        if scenario.failure_onset_ticks is not None:
            lo, hi = scenario.failure_onset_ticks
            self.onset[fail_cars] = rng.integers(lo, hi + 1,
                                                 fail_cars.sum())
        self.tick = 0
        self.t = 0.0

    # ----------------------------------------------------------- columns
    def step_columns(self, batch_cars: Optional[np.ndarray] = None) -> dict:
        """Advance one tick for the selected cars; return raw sensor columns
        (producer-schema units) + 'car' ids + 'failure_occurred' labels."""
        s = self.scenario
        idx = np.arange(s.num_cars) if batch_cars is None else batch_cars
        n = len(idx)
        rng = self.rng

        # speed: mean-reverting random walk in [0, 50] m/s
        self.speed[idx] = np.clip(
            self.speed[idx] + rng.normal(0, 2.0, n) - 0.02 * (self.speed[idx] - 20.0),
            0.0, 50.0)
        speed = self.speed[idx]
        throttle = np.clip(speed / 50.0 + rng.normal(0, 0.05, n), 0.0, 1.0)
        vibration = speed * rng.uniform(100.0, 150.0, n)  # reference's own model
        self.battery[idx] = np.clip(self.battery[idx] - rng.uniform(0, 0.05, n), 0.0, 100.0)
        current = 5.0 + speed * 0.5 + rng.normal(0, 1.0, n)
        coolant = 20.0 + speed * 0.6 + rng.normal(0, 2.0, n)
        airflow = speed * 3.0 + rng.normal(0, 5.0, n)
        voltage = 200.0 + self.battery[idx] * 0.5 + rng.normal(0, 2.0, n)
        tires = self.tire_base[idx] + rng.normal(0, 0.5, (n, 4))
        accel = np.abs(rng.normal(0.5, 0.8, (n, 4)))

        # failure modes perturb the physics and set the label — only once
        # a car's onset tick has passed (default: from birth)
        failing = np.where(self.onset[idx] <= self.tick,
                           self.failing[idx], -1)
        lab = failing >= 0
        m0 = failing == 0  # engine failure: vibration spike
        vibration[m0] *= rng.uniform(2.0, 4.0, m0.sum())
        m1 = failing == 1  # tire blowout: one tire loses pressure
        tires[m1, 0] = rng.uniform(10.0, 18.0, m1.sum())
        m2 = failing == 2  # battery fault: voltage sag + current spike
        voltage[m2] -= rng.uniform(30.0, 60.0, m2.sum())
        current[m2] *= rng.uniform(1.5, 3.0, m2.sum())

        cols = {
            "coolant_temp": coolant.astype(np.float32),
            "intake_air_temp": rng.uniform(15.0, 40.0, n).astype(np.float32),
            "intake_air_flow_speed": np.clip(airflow, 0, None).astype(np.float32),
            "battery_percentage": self.battery[idx].astype(np.float32),
            "battery_voltage": voltage.astype(np.float32),
            "current_draw": np.clip(current, 0, None).astype(np.float32),
            "speed": speed.astype(np.float32),
            "engine_vibration_amplitude": vibration.astype(np.float32),
            "throttle_pos": throttle.astype(np.float32),
            "tire_pressure_1_1": tires[:, 0].astype(np.int32),
            "tire_pressure_1_2": tires[:, 1].astype(np.int32),
            "tire_pressure_2_1": tires[:, 2].astype(np.int32),
            "tire_pressure_2_2": tires[:, 3].astype(np.int32),
            "accelerometer_1_1_value": accel[:, 0].astype(np.float32),
            "accelerometer_1_2_value": accel[:, 1].astype(np.float32),
            "accelerometer_2_1_value": accel[:, 2].astype(np.float32),
            "accelerometer_2_2_value": accel[:, 3].astype(np.float32),
            "control_unit_firmware": self.firmware[idx],
            "car": idx,
            "failure_occurred": np.where(lab, "true", "false"),
        }
        self.t += s.interval_s
        self.tick += 1
        return cols

    def sensor_matrix(self, cols: dict) -> np.ndarray:
        """[n, 18] float64 matrix in schema order (pre-normalization)."""
        return np.stack([cols[f.name].astype(np.float64)
                         for f in CAR_SCHEMA.fields], axis=1)

    # ----------------------------------------------------------- records
    def row_record(self, cols: dict, i: int, schema: RecordSchema) -> dict:
        """Row i of a step's columns as a dict record in `schema`'s naming."""
        rec = {}
        for f_ref, f_out in zip(CAR_SCHEMA.fields, schema.sensor_fields):
            v = cols[f_ref.name][i]
            rec[f_out.name] = int(v) if f_out.avro_type in ("int", "long") \
                else float(v)
        if schema.label_field:
            rec[schema.label_field] = str(cols["failure_occurred"][i])
        return rec

    def records(self, n_ticks: int = 1,
                schema: RecordSchema = KSQL_CAR_SCHEMA) -> Iterator[dict]:
        """Yield per-row dict records in the requested schema variant."""
        for _ in range(n_ticks):
            cols = self.step_columns()
            for i in range(len(cols["car"])):
                yield self.row_record(cols, i, schema)

    def publish(self, broker, topic: str, n_ticks: int = 1,
                schema: RecordSchema = KSQL_CAR_SCHEMA,
                encoding: str = "avro",
                framed: bool = True, partitions: int = 1) -> int:
        """Encode and append records to a broker topic. Returns count.

        encoding="avro": Confluent-framed Avro in `schema` (ML input stage).
        encoding="json": raw JSON with producer field names + label — what
          lands on `sensor-data` before the KSQL-equivalent convert stage.
        """
        import json as _json

        broker.create_topic(topic, partitions=partitions)
        codec = AvroCodec(schema)
        native = None
        if encoding == "avro":
            try:
                from ..stream.native import NativeCodec

                native = NativeCodec(schema)
            except Exception:
                native = None
        count = 0
        for tick in range(n_ticks):
            cols = self.step_columns()
            n = len(cols["car"])
            ts = int(self.t * 1000)
            keys = [self.scenario.car_id(int(c)).encode() for c in cols["car"]]
            if native is not None and schema.label_field:
                # vectorized path: columnar floats + labels → framed Avro
                num = self.sensor_matrix(cols)
                labels = cols["failure_occurred"].astype("S16")[:, None]
                msgs = native.encode_batch(num, labels,
                                           schema_id=1 if framed else -1)
                for i, payload in enumerate(msgs):
                    hdrs = tracing.birth_headers("devsim_publish") \
                        if tracing.ENABLED else None
                    broker.produce(topic, payload, key=keys[i],
                                   partition=None if partitions > 1 else 0,
                                   timestamp_ms=ts, headers=hdrs)
                count += n
                continue
            for i in range(n):
                if encoding == "json":
                    rec = self.row_record(cols, i, CAR_SCHEMA)
                    rec["failure_occurred"] = str(cols["failure_occurred"][i])
                    payload = _json.dumps(rec).encode()
                else:
                    payload = codec.encode(self.row_record(cols, i, schema))
                    if framed:
                        payload = frame(payload)
                # trace birth for the broker-direct (no-MQTT) ingest leg;
                # fully guarded: the disabled path makes no tracing calls
                hdrs = tracing.birth_headers("devsim_publish") \
                    if tracing.ENABLED else None
                broker.produce(topic, payload, key=keys[i],
                               partition=None if partitions > 1 else 0,
                               timestamp_ms=ts, headers=hdrs)
                count += 1
        return count


def write_csv_fixture(path: str, n_rows: int = 10_000,
                      scenario: Optional[FleetScenario] = None,
                      start_time: int = 1_567_606_196) -> int:
    """Write a `car-sensor-data.csv`-shaped offline fixture.

    Header and column order match the reference's 10k-row test file
    (`testdata/car-sensor-data.csv:1`): `time,car,<18 sensor columns>`, car
    ids like `car1`, epoch-seconds timestamps.  Returns the row count.
    """
    from ..core.schema import CSV_COLUMNS

    scenario = scenario or FleetScenario(num_cars=100)
    gen = FleetGenerator(scenario)
    header = list(CSV_COLUMNS)
    n = 0
    with open(path, "w") as fh:
        fh.write(",".join(header) + "\n")
        t = start_time
        while n < n_rows:
            cols = gen.step_columns()
            for i in range(len(cols["car"])):
                if n >= n_rows:
                    break
                rec = gen.row_record(cols, i, CAR_SCHEMA)
                row = [str(t), f"car{int(cols['car'][i]) + 1}"] + [
                    str(rec[f.name]) for f in CAR_SCHEMA.fields]
                fh.write(",".join(row) + "\n")
                n += 1
            t += max(int(scenario.interval_s), 1)
    return n
