"""iotml.mlops — model lifecycle: async checkpoints, versioned
registry, zero-downtime rollout, rollback-on-regression.

The reference's model lifecycle is "save to GCS, redeploy the pod"
(PAPER L5/L6) — a restart-shaped gap in an otherwise self-healing
system.  This package closes it:

- ``ModelRegistry``: monotonic versions, manifest-as-commit-marker
  publication (``iotml.store`` atomic-write discipline), offsets +
  metrics + lineage per version, torn-publish recovery, channel
  pointers with promote/rollback history;
- ``AsyncCheckpointer``: device→host snapshot on the train thread,
  serialize+fsync on a supervised writer thread behind a bounded
  drop-oldest queue — checkpointing never stalls training
  (``bench_checkpoint`` pins the claim); group offsets commit only
  AFTER the checkpoint is durable, so model state and stream position
  always resume consistently;
- ``RegistryWatcher``: scorers hot-swap to a newly promoted version
  between super-batches with zero dropped/double-scored records,
  single scorer or the PR 6 partition-parallel fleet alike;
- ``ABRollout`` + ``RolloutGate``: two versions score the same stream
  into compared prediction topics; the r04 detection-quality protocol
  auto-promotes or auto-rolls-back.

Proof lives in ``iotml.mlops.drill`` (live drills) and the seeded
chaos scenarios ``trainer-crash-mid-checkpoint`` /
``rollout-regression-rollback``.  Lint rule R11 keeps registry writes
inside this package.
"""

from .checkpoint import AsyncCheckpointer, restore_trainer
from .registry import Manifest, ModelRegistry
from .rollout import ABRollout, RegistryWatcher, RolloutGate, scorer_quality

__all__ = ["AsyncCheckpointer", "restore_trainer", "Manifest",
           "ModelRegistry", "ABRollout", "RegistryWatcher", "RolloutGate",
           "scorer_quality"]
