"""``python -m iotml.mlops`` — model-lifecycle CLI.

    python -m iotml.mlops drill [--drill rollout|rollback | --all]
                                [--seed S] [--records N] [--json]
                                [--slo-swap S] [--slo-rollback S]
    python -m iotml.mlops registry --root DIR [--json]
    python -m iotml.mlops list

``drill`` runs a LIVE drill — real threads, a supervised scorer, a
registry watcher hot-swapping under load — and exits with the
invariant verdict (0 = zero records lost/double-scored across every
swap, SLOs met).  CI and deploy/smoke.sh run exactly this.
``registry`` inspects a registry root: committed versions, channel
pointers, promote/rollback history.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.mlops",
        description="model lifecycle: versioned registry, live "
                    "rollout/rollback drills")
    sub = ap.add_subparsers(dest="cmd")
    dp = sub.add_parser("drill", help="run a live drill; exit status is "
                                      "the invariant verdict")
    dp.add_argument("--drill", default="rollout",
                    help="drill name (see `list`)")
    dp.add_argument("--all", action="store_true",
                    help="run every drill in sequence")
    dp.add_argument("--seed", type=int, default=7)
    dp.add_argument("--records", type=int, default=0,
                    help="records to pump (0 = the drill's default)")
    dp.add_argument("--slo-swap", type=float, default=5.0,
                    help="rollout: max seconds promote -> scorer swap")
    dp.add_argument("--slo-rollback", type=float, default=60.0,
                    help="rollback: max seconds deploy -> rollback "
                         "verdict")
    dp.add_argument("--json", action="store_true")
    rp = sub.add_parser("registry", help="inspect a model registry root")
    rp.add_argument("--root", required=True)
    rp.add_argument("--json", action="store_true")
    sub.add_parser("list", help="list available drills")
    args = ap.parse_args(argv)

    from .drill import DRILLS

    if args.cmd == "list":
        for name, fn in sorted(DRILLS.items()):
            doc = (fn.__doc__ or "").strip().splitlines()[0]
            print(f"{name:<10} {doc}")
        return 0
    if args.cmd == "registry":
        from .registry import ModelRegistry

        reg = ModelRegistry(args.root)
        desc = reg.describe()
        if args.json:
            print(json.dumps({"registry": desc,
                              "history": reg.history()},
                             indent=2, sort_keys=True))
            return 0
        print(f"registry {desc['root']}")
        for v in desc["versions"]:
            m = reg.manifest(v)
            tags = [c for c in ("serving", "candidate")
                    if desc.get(c) == v]
            off = ", ".join(f"{t}[{p}]={o}" for t, p, o in m.offsets)
            print(f"  v{v:<4} parent={m.parent} step={m.step} "
                  f"offsets({off}) metrics={m.metrics}"
                  + (f"  <- {','.join(tags)}" if tags else ""))
        for e in reg.history()[-8:]:
            print(f"  history: {e}")
        return 0
    if args.cmd != "drill":
        ap.print_help()
        return 2

    names = sorted(DRILLS) if args.all else [args.drill]
    unknown = [n for n in names if n not in DRILLS]
    if unknown:
        print(f"unknown drill(s) {unknown}; have: {sorted(DRILLS)}",
              file=sys.stderr)
        return 2
    ok = True
    for name in names:
        kw = {"seed": args.seed}
        if args.records:
            kw["records"] = args.records
        if name == "rollout":
            kw["slo_swap_s"] = args.slo_swap
        elif name == "rollback":
            kw["slo_rollback_s"] = args.slo_rollback
        report = DRILLS[name](**kw)
        if args.json:
            print(json.dumps(report.to_dict(), indent=2, sort_keys=True,
                             default=str))
        else:
            print("\n".join(report.lines()))
        ok = ok and report.ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
