"""Async checkpointing — snapshot on the train thread, bytes off it.

The seed `train.checkpoint.CheckpointManager` serializes and fsyncs on
the train thread: every save stalls the hot loop for the full disk
round trip.  The Orbax async design (PAPERS: *Orbax: Distributed
Checkpointing with JAX*) splits the save at the device boundary:

- ``snapshot()`` (train thread): one ``jax.device_get`` copies params/
  opt-state to host memory and the pytree joins a BOUNDED queue with
  the stream cursors captured at the same instant.  Cost: the device→
  host copy only (``iotml_checkpoint_seconds{phase="snapshot"}``).
- the **writer** (background thread, or ``write_once()`` driven
  deterministically): serializes the snapshot (phase ``serialize``)
  and commits it to the ``ModelRegistry`` (phase ``fsync`` — the
  atomic-write + dir-fsync publication), stamping the manifest with
  the captured offsets so model state and stream position land as one
  atomic unit.

The queue is **drop-oldest**: when the disk falls behind, pending
snapshots are evicted (``iotml_checkpoint_dropped_total``) and the
newest wins — a slow disk degrades checkpoint FREQUENCY, never
training throughput.  A crash mid-write leaves a torn stage the
registry never serves (see ``registry.publish``); the writer's loop is
supervisable (``unit_loop``) so the PR 4 supervisor restarts a crashed
writer under backoff.
"""

from __future__ import annotations

import collections
import io
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..chaos import faults as chaos
from ..obs import metrics as obs_metrics
from .registry import ModelRegistry


# ------------------------------------------------------- state codecs
def state_to_npz_bytes(params, opt_state, step: int) -> bytes:
    """Flatten the (params, opt_state) pytrees to one .npz blob.

    Leaves are stored positionally (``p_<i>`` / ``o_<i>``): restore
    unflattens onto a template state with the same structure (a freshly
    initialized Trainer), which is exactly the resume contract — the
    model architecture is code, only the numbers are data."""
    import jax

    p_leaves = jax.tree_util.tree_leaves(params)
    o_leaves = jax.tree_util.tree_leaves(opt_state)
    arrays = {f"p_{i}": np.asarray(a) for i, a in enumerate(p_leaves)}
    arrays.update({f"o_{i}": np.asarray(a) for i, a in enumerate(o_leaves)})
    arrays["step"] = np.asarray(int(step))
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def state_from_npz_bytes(data: bytes, template_state):
    """Rebuild a TrainState from ``state_to_npz_bytes`` output onto a
    template with identical tree structure (shape-checked leaf by
    leaf)."""
    import jax

    with np.load(io.BytesIO(data)) as z:
        arrays = {k: z[k] for k in z.files}
    p_def = jax.tree_util.tree_structure(template_state.params)
    o_def = jax.tree_util.tree_structure(template_state.opt_state)
    p_tmpl = jax.tree_util.tree_leaves(template_state.params)
    o_tmpl = jax.tree_util.tree_leaves(template_state.opt_state)
    p_leaves = [arrays[f"p_{i}"] for i in range(len(p_tmpl))]
    o_leaves = [arrays[f"o_{i}"] for i in range(len(o_tmpl))]
    for got, want in zip(p_leaves + o_leaves, p_tmpl + o_tmpl):
        if tuple(got.shape) != tuple(np.shape(want)):
            raise ValueError(
                f"checkpoint leaf shape {tuple(got.shape)} does not "
                f"match template {tuple(np.shape(want))} — wrong model "
                f"architecture for this checkpoint")
    return template_state.replace(
        step=arrays["step"],
        params=jax.tree_util.tree_unflatten(p_def, p_leaves),
        opt_state=jax.tree_util.tree_unflatten(o_def, o_leaves))


def params_to_h5_bytes(params) -> bytes:
    """Serving weights as the reference's h5 byte layout (what scorers
    hot-swap; see models/h5_export)."""
    import os
    import tempfile

    import jax

    from ..models.h5_export import autoencoder_params_to_h5

    with tempfile.TemporaryDirectory(prefix="iotml_ckpt_") as tmp:
        path = os.path.join(tmp, "model.h5")
        autoencoder_params_to_h5(jax.tree.map(np.asarray, params), path)
        with open(path, "rb") as fh:
            return fh.read()


def params_from_h5_bytes(data: bytes):
    import os
    import tempfile

    from ..models.h5_import import autoencoder_params_from_h5

    with tempfile.TemporaryDirectory(prefix="iotml_swap_") as tmp:
        path = os.path.join(tmp, "model.h5")
        with open(path, "wb") as fh:
            fh.write(data)
        return autoencoder_params_from_h5(path)


class _Snapshot:
    __slots__ = ("params", "opt_state", "step", "offsets", "metrics",
                 "end_offsets", "t_captured")

    def __init__(self, params, opt_state, step, offsets, metrics,
                 end_offsets):
        self.params = params
        self.opt_state = opt_state
        self.step = step
        self.offsets = offsets
        self.metrics = metrics
        self.end_offsets = end_offsets
        self.t_captured = time.monotonic()


class AsyncCheckpointer:
    """Bounded-queue async checkpoint pipeline into a ModelRegistry.

    Args:
      registry: the destination ``ModelRegistry`` (this object is its
        single writer).
      queue_depth: max pending snapshots; beyond it the OLDEST pending
        snapshot is dropped (counted), newest-wins.
      save_opt_state: also serialize optimizer moments (``state.npz``)
        so a resumed trainer continues the same Adam trajectory; off,
        checkpoints are weights+offsets only (smaller, scorer-grade).
    """

    def __init__(self, registry: ModelRegistry, queue_depth: int = 2,
                 save_opt_state: bool = True, auto_promote: bool = True,
                 min_interval_s: float = 0.0, keep_versions: int = 0):
        self.registry = registry
        self.queue_depth = max(1, int(queue_depth))
        self.save_opt_state = save_opt_state
        #: registry retention: after each commit, prune committed
        #: versions beyond the newest ``keep_versions`` (channel targets
        #: are never pruned).  0 keeps everything — but a continuously-
        #: checkpointing trainer then grows the registry without bound,
        #: so the CLIs wire a finite default (MlopsConfig.keep_versions)
        self.keep_versions = int(keep_versions)
        #: checkpoint cadence (Orbax's save_interval, in seconds): a
        #: snapshot arriving sooner than this after the last ACCEPTED
        #: one is coalesced away (counted) — sub-second training rounds
        #: must not serialize a version per round; staleness is bounded
        #: by the interval, correctness by commit-trails-durability
        #: (a coalesced snapshot just means the next one commits
        #: further ahead).  0 accepts every snapshot (tests, drills).
        self.min_interval_s = float(min_interval_s)
        self.coalesced = 0
        self._last_accept = float("-inf")
        #: point the ``serving`` channel at each committed version — the
        #: continuous-delivery default (watchers hot-swap immediately).
        #: An A/B-gated deployment turns this OFF and lets the gate own
        #: promotion through the ``candidate`` channel instead.
        self.auto_promote = auto_promote
        #: post-durability hook (set by ContinuousTrainer): called with
        #: the committed Manifest AFTER publication, ON THE WRITER
        #: thread — this is where the group commit trails checkpoint
        #: durability, so committed offsets never outrun a restorable
        #: model state
        self.commit_fn: Optional[Callable] = None
        #: an external supervisor owns the writer loop (unit_loop());
        #: start() must not race it with a second drainer
        self._external = False
        self._queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        #: serializes whole drains: flush()/stop() on a caller thread
        #: may run while a supervised unit_loop (or the owned writer
        #: thread) is mid-write_once — ModelRegistry.publish is single-
        #: writer (listdir-based next_version, pid-named stage dir), so
        #: two concurrent drains could mint the same version id and
        #: tear each other's stage
        self._drain_lock = threading.Lock()
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._idle = threading.Event()
        self._idle.set()
        self.written = 0
        self.dropped = 0
        self.last_error: Optional[str] = None
        self.last_version: Optional[int] = None

    # ------------------------------------------------- train-thread side
    def snapshot(self, state, cursors: Sequence[Tuple[str, int, int]],
                 metrics: Optional[Dict[str, float]] = None,
                 end_offsets: Optional[Dict[Tuple[str, int], int]] = None,
                 force: bool = False) -> None:
        """Capture (device→host) and enqueue; returns immediately.

        ``cursors`` are the consumer positions AT THIS INSTANT — they
        ride the snapshot into the manifest, so the committed version
        names exactly the data this state was trained through.
        ``end_offsets`` (optional ``(topic, part) → end``) lets the
        writer export the offsets-lag gauge without touching the
        broker from the writer thread.  ``force`` bypasses the cadence
        throttle (shutdown wants the newest state archived)."""
        import jax

        if not self.would_accept(force):
            self.coalesced += 1
            return
        self._last_accept = time.monotonic()
        with obs_metrics.checkpoint_seconds.time(phase="snapshot"):
            params, opt_state = jax.device_get(
                (state.params, state.opt_state))
            snap = _Snapshot(params,
                             opt_state if self.save_opt_state else None,
                             int(state.step),
                             [tuple(c) for c in cursors],
                             dict(metrics or {}),
                             dict(end_offsets or {}))
        with self._lock:
            while len(self._queue) >= self.queue_depth:
                self._queue.popleft()
                self.dropped += 1
                obs_metrics.checkpoint_dropped.inc()
            self._queue.append(snap)
            self._idle.clear()
        self._kick.set()

    def would_accept(self, force: bool = False) -> bool:
        """Cheap cadence pre-check: would ``snapshot()`` accept right
        now?  Callers use it to skip the capture itself — consumer
        positions plus one broker ``end_offset`` round trip per
        partition are wasted work on a snapshot the throttle would
        coalesce anyway (see ``ContinuousTrainer._snapshot``)."""
        return force or self.min_interval_s <= 0 or \
            time.monotonic() - self._last_accept >= self.min_interval_s

    # ------------------------------------------------------ writer side
    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def write_once(self) -> Optional[int]:
        """Drain ONE pending snapshot into the registry; returns the
        committed version (None when idle).  This is the deterministic
        drive point — the writer thread, the chaos runner and tests all
        come through here, so a fault injected at ``ckpt.write`` tears
        the same publication step everywhere.  Whole drains are
        serialized (``_drain_lock``): a shutdown flush may overlap the
        supervised writer's loop, and the registry is single-writer."""
        with self._drain_lock:
            return self._write_one()

    def _write_one(self) -> Optional[int]:
        with self._lock:
            if not self._queue:
                self._idle.set()
                return None
            snap = self._queue.popleft()
        try:
            with obs_metrics.checkpoint_seconds.time(phase="serialize"):
                artifacts = {"model.h5": params_to_h5_bytes(snap.params)}
                if snap.opt_state is not None:
                    artifacts["state.npz"] = state_to_npz_bytes(
                        snap.params, snap.opt_state, snap.step)
            # the faultpoint sits between serialize and the atomic
            # publication: an injected crash here IS "killed
            # mid-checkpoint" — host state gone, registry untouched
            chaos.point("ckpt.write")
            with obs_metrics.checkpoint_seconds.time(phase="fsync"):
                manifest = self.registry.publish(
                    artifacts, offsets=snap.offsets, metrics=snap.metrics,
                    step=snap.step)
        except Exception as e:
            self.last_error = f"{type(e).__name__}: {e}"
            raise
        self.written += 1
        self.last_version = manifest.version
        try:
            if self.auto_promote:
                self.registry.promote(manifest.version)
            if self.commit_fn is not None:
                self.commit_fn(manifest)
        except Exception as e:  # noqa: BLE001 - both edges self-heal:
            # the next publish re-promotes, the next checkpoint
            # re-commits forward; surface and let the supervisor decide
            self.last_error = f"{type(e).__name__}: {e}"
            raise
        if snap.end_offsets:
            lag = sum(max(0, snap.end_offsets.get((t, p), o) - o)
                      for t, p, o in snap.offsets)
            obs_metrics.model_offsets_lag.set(
                lag, component=self.registry.component)
        if self.keep_versions > 0:
            self.registry.prune(self.keep_versions)
        with self._lock:
            if not self._queue:
                self._idle.set()
        return manifest.version

    def flush(self, timeout_s: float = 30.0) -> bool:
        """Block until every enqueued snapshot is committed (the
        synchronous edge for shutdown/tests)."""
        if self._thread is None or not self._thread.is_alive():
            while self.write_once() is not None:
                pass
            return True
        return self._idle.wait(timeout_s)

    # -------------------------------------------------------- lifecycle
    def unit_loop(self) -> Callable:
        """The writer body as a ``SupervisedUnit`` loop: heartbeats per
        round, crash (injected or real) surfaces to the supervisor,
        which restarts a fresh incarnation under backoff — pending
        snapshots survive in the queue."""

        self._external = True

        def loop(unit):
            while not unit.should_stop():
                unit.heartbeat()
                if self.write_once() is None:
                    self._kick.wait(0.05)
                    self._kick.clear()

        return loop

    def start(self) -> "AsyncCheckpointer":
        """Spawn an UNsupervised writer thread (callers that already
        run a supervisor should register ``unit_loop()`` instead)."""
        from ..supervise.registry import register_thread

        if self._external or (self._thread is not None
                              and self._thread.is_alive()):
            return self
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                try:
                    wrote = self.write_once()
                except Exception:  # noqa: BLE001 - a failed write must
                    # not kill the writer; the snapshot is gone (newest
                    # wins anyway), the error is surfaced on last_error
                    wrote = None
                if wrote is None:
                    self._kick.wait(0.05)
                    self._kick.clear()

        self._thread = register_thread(threading.Thread(
            target=run, daemon=True, name="iotml-ckpt-writer"))
        self._thread.start()
        return self

    def stop(self, flush: bool = True, timeout_s: float = 30.0) -> None:
        if flush:
            self.flush(timeout_s)
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None


# --------------------------------------------------------- restore side
def restore_trainer(trainer, registry: ModelRegistry,
                    version: Optional[int] = None):
    """Warm-start a ``train.loop.Trainer`` from a committed version.

    Returns the manifest (its ``offsets`` are the resume cursors), or
    None when the registry is empty.  Params always load; optimizer
    moments load when the version carries ``state.npz`` AND the tree
    matches, else the optimizer restarts fresh (documented degradation,
    not an error — weights + offsets are the atomic unit).

    The default version is the NEWEST committed one — the training
    lineage's tip — never the ``serving`` channel: serving is
    deployment state (a rollback points it at an OLD version while the
    group's committed offsets keep following the newest manifest), so
    resuming from it would pair old weights with new cursors and leave
    records trained into no model.  The gate protects serving; the
    trainer resumes where training actually stopped."""
    if version is None:
        version = registry.latest()
    if version is None:
        return None
    m = registry.manifest(version)
    params = params_from_h5_bytes(registry.load_bytes(version, "model.h5"))
    in_dim = _params_input_dim(params)
    trainer._ensure_state(np.zeros((1, in_dim), np.float32))
    if "state.npz" in m.artifacts:
        try:
            trainer.state = state_from_npz_bytes(
                registry.load_bytes(version, "state.npz"), trainer.state)
            return m
        except (ValueError, KeyError):
            pass  # architecture drift: fall through to weights-only
    # weights-only warm start: graft the loaded leaves onto the template
    # params' own tree structure (dict vs FrozenDict must not fork the
    # pytree the optimizer state was built against)
    import jax

    t_def = jax.tree_util.tree_structure(trainer.state.params)
    t_leaves = jax.tree_util.tree_leaves(trainer.state.params)
    l_leaves = jax.tree_util.tree_leaves(params)
    if len(t_leaves) != len(l_leaves) or any(
            tuple(np.shape(a)) != tuple(np.shape(b))
            for a, b in zip(l_leaves, t_leaves)):
        raise ValueError(
            f"version {version} weights do not match the trainer's "
            f"model architecture")
    trainer.state = trainer.state.replace(
        params=jax.tree_util.tree_unflatten(t_def, l_leaves),
        step=np.asarray(m.step, np.int32))
    return m


def _params_input_dim(params) -> int:
    """First layer's fan-in — the sample-x width state init needs."""
    first = params.get("encoder0") or params[sorted(params.keys())[0]]
    return int(np.shape(first["kernel"])[0])
