"""Live mlops drills — zero-downtime rollout and rollback, under fire.

The chaos scenarios (``trainer-crash-mid-checkpoint``,
``rollout-regression-rollback``) prove the model-lifecycle invariants in
a deterministic single-threaded replay; these drills prove the LIVE
multi-threaded system delivers them while the fleet keeps publishing:

- ``drill_rollout``: a supervised scorer serves a stream under
  sustained load while the registry promotes a sequence of new model
  versions.  The registry watcher must hot-swap each one within an SLO,
  and the proof of "zero downtime" is **record identity**: every
  ``(partition, offset)`` in the input log is scored exactly once —
  zero dropped, zero double-scored — across every swap.
- ``drill_rollback``: a deliberately degraded candidate is DEPLOYED to
  serving (the production scorer really runs it); the A/B gate must
  detect the live quality regression, roll serving back to the
  baseline within an SLO, and the production scorer must end up back
  on the baseline version having lost nothing.

Run via ``python -m iotml.mlops drill`` (verdict = exit status; CI runs
exactly this).
"""

from __future__ import annotations

import tempfile
import time
from typing import Dict, List, Tuple

import numpy as np

from ..chaos.runner import (Invariant, _check_commits_monotonic,
                            _record_commits)
from ..supervise.drill import CARS_PER_TICK, DrillReport, _wait
from ..supervise.supervisor import Supervisor
from .checkpoint import AsyncCheckpointer, params_from_h5_bytes, \
    params_to_h5_bytes
from .registry import ModelRegistry
from .rollout import ABRollout, RegistryWatcher, RolloutGate

IN_TOPIC = "SENSOR_DATA_S_AVRO"
PRED_TOPIC = "model-predictions"
GROUP = "mlops-drill-scorer"


# ------------------------------------------------------------- helpers
def _identity_consumer(broker, parts: int, group: str,
                       identities: List[Tuple[int, int]]):
    """A StreamConsumer whose every polled record is ledgered by
    (partition, offset) — the ground truth the zero-loss/zero-dup
    verdict is computed from."""
    from ..stream.consumer import StreamConsumer

    consumer = StreamConsumer(
        broker, [f"{IN_TOPIC}:{p}:0" for p in range(parts)],
        group=group, eof=True)
    orig_poll = consumer.poll

    def poll(max_messages: int = 1024):
        batch = orig_poll(max_messages)
        identities.extend((m.partition, m.offset) for m in batch)
        return batch

    consumer.poll = poll
    return consumer


def _make_scorer(broker, consumer, params, threshold=None):
    from ..data.dataset import SensorBatches
    from ..models.autoencoder import CAR_AUTOENCODER
    from ..serve.scorer import StreamScorer
    from ..stream.producer import OutputSequence

    kw = {} if threshold is None else dict(threshold=threshold)
    batches = SensorBatches(consumer, batch_size=100,
                            keep_labels=threshold is not None)
    out = OutputSequence(broker, PRED_TOPIC, partition=0)
    return StreamScorer(CAR_AUTOENCODER, params, batches, out, **kw)


def _scorer_loop(scorer, consumer, state):
    def loop(unit):
        consumer.rewind_to_committed()
        while not unit.should_stop():
            try:
                n = scorer.score_available()
            except ConnectionError:
                consumer.rewind_to_committed()
                state["rewinds"] += 1
                time.sleep(0.02)
                continue
            unit.heartbeat()
            if not n:
                time.sleep(0.005)

    return loop


def _publish_tick(gen, broker, codec, schema, frame) -> int:
    cols = gen.step_columns()
    n = len(cols["car"])
    for i in range(n):
        rec = gen.row_record(cols, i, schema)
        broker.produce(IN_TOPIC, frame(codec.encode(rec)),
                       key=gen.scenario.car_id(i).encode(),
                       partition=i % 2)  # lint-ok: R5 drill harness is
        # the devsim stand-in feeding the engine-owned leg directly
    return n


def _identity_verdicts(broker, identities, parts: int) -> List[Invariant]:
    expected = set()
    for p in range(parts):
        expected.update((p, o) for o in
                        range(broker.end_offset(IN_TOPIC, p)))
    seen = list(identities)
    dupes = len(seen) - len(set(seen))
    missing = expected - set(seen)
    extra = set(seen) - expected
    return [
        Invariant(
            "zero_records_lost",
            not missing and not extra,
            f"every one of the {len(expected)} (partition, offset) "
            f"identities in the log was polled and scored"
            if not missing and not extra else
            f"{len(missing)} records NEVER SCORED "
            f"(e.g. {sorted(missing)[:3]}); {len(extra)} phantom"),
        Invariant(
            "zero_double_scored",
            dupes == 0,
            f"{len(seen)} polled identities, all unique"
            + ("" if not dupes else f"; {dupes} DOUBLE-SCORED")),
    ]


# ------------------------------------------------------------- rollout
def drill_rollout(seed: int = 7, records: int = 1500,
                  n_versions: int = 3,
                  slo_swap_s: float = 5.0) -> DrillReport:
    """Zero-downtime hot-swap under sustained load.

    A supervised scorer + registry watcher serve the stream while the
    fleet publishes continuously and the registry promotes
    ``n_versions`` successive models mid-flight.  Every promotion must
    be picked up within ``slo_swap_s`` with the scorer never pausing:
    afterwards every (partition, offset) in the log was scored exactly
    once and the predictions topic is contiguous."""
    import jax

    from ..core.schema import KSQL_CAR_SCHEMA
    from ..gen.simulator import FleetGenerator, FleetScenario
    from ..models.autoencoder import CAR_AUTOENCODER
    from ..ops.avro import AvroCodec
    from ..ops.framing import frame
    from ..stream.broker import Broker
    from ..train.loop import Trainer

    if records < (n_versions + 2) * CARS_PER_TICK:
        raise ValueError(f"rollout needs >= {(n_versions + 2) * 25} "
                         f"records, got {records}")
    parts = 2
    broker = Broker()
    broker.create_topic(IN_TOPIC, partitions=parts)
    broker.create_topic(PRED_TOPIC, partitions=1)
    commit_log: List[tuple] = []
    _record_commits(broker, commit_log, "stream")
    tmp = tempfile.TemporaryDirectory(prefix="iotml_drill_registry_")
    registry = ModelRegistry(tmp.name)

    def fresh_params(k: int):
        tr = Trainer(CAR_AUTOENCODER, rng=jax.random.PRNGKey(seed + k))
        tr._ensure_state(np.zeros((4, 18), np.float32))
        return jax.device_get(tr.state.params)

    v1 = registry.publish({"model.h5": params_to_h5_bytes(fresh_params(0))},
                          metrics={"k": 0.0}).version
    registry.promote(v1)

    identities: List[Tuple[int, int]] = []
    consumer = _identity_consumer(broker, parts, GROUP, identities)
    scorer = _make_scorer(
        broker, consumer,
        params_from_h5_bytes(registry.load_bytes(v1, "model.h5")))
    # edge-triggered swap observation at the authoritative point (the
    # set_params call itself): sampling scorer.model_version from the
    # drive loop can MISS an intermediate version on a slow box, and
    # the serving channel is level-triggered by design — two promotions
    # inside one watcher poll coalesce into one swap
    swap_times: Dict[int, float] = {}
    _orig_set_params = scorer.set_params

    def _recording_set_params(params, version=None):
        _orig_set_params(params, version=version)
        if version is not None and version not in swap_times:
            swap_times[version] = time.monotonic()

    scorer.set_params = _recording_set_params
    watcher = RegistryWatcher(registry, scorers=[scorer],
                              poll_interval_s=0.02)
    state: dict = {"rewinds": 0}

    sup = Supervisor(poll_interval_s=0.05, name="mlops-drill-supervisor")
    sup.add_loop("scorer", _scorer_loop(scorer, consumer, state),
                 heartbeat_timeout_s=30.0)
    sup.add_loop("registry-watcher", watcher.unit_loop(),
                 heartbeat_timeout_s=30.0)
    sup.start()

    gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK, seed=seed))
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    published = 0
    ticks = max(1, -(-records // CARS_PER_TICK))
    promote_every = max(1, ticks // (n_versions + 1))
    #: version -> (t_promoted, scored_at_promote); the swap edge itself
    #: lands in swap_times via the set_params wrapper above
    swap_log: Dict[int, list] = {}
    next_k = 1
    try:
        for tick in range(ticks):
            if next_k <= n_versions and tick == next_k * promote_every:
                # promote mid-load: publish new weights, flip serving
                v = registry.publish(
                    {"model.h5": params_to_h5_bytes(fresh_params(next_k))},
                    metrics={"k": float(next_k)}).version
                registry.promote(v)
                swap_log[v] = [time.monotonic(), scorer.scored]
                next_k += 1
            published += _publish_tick(gen, broker, codec,
                                       KSQL_CAR_SCHEMA, frame)
            time.sleep(0.002)  # live pacing: swap windows overlap load
        # quiesce: the last promoted version lands, everything scored
        # and committed
        last_v = max(swap_log) if swap_log else v1
        _wait(lambda: scorer.model_version == last_v, slo_swap_s + 10)
        _wait(lambda: consumer.at_end()
              and all(broker.committed(GROUP, IN_TOPIC, p)
                      == broker.end_offset(IN_TOPIC, p)
                      for p in range(parts)), 30.0)
    finally:
        sup.stop()
        watcher.stop()
        tmp.cleanup()

    lat = {v: swap_times[v] - e[0]
           for v, e in swap_log.items() if v in swap_times}
    coalesced = sorted(v for v in swap_log if v not in swap_times)
    worst = max(lat.values(), default=None)
    pred_end = broker.end_offset(PRED_TOPIC, 0)
    invariants = [
        Invariant(
            # convergence, not every-intermediate-pointer-value: the
            # serving channel is level-triggered, so promotions racing
            # one watcher poll legitimately coalesce — what must hold
            # is that the scorer ends on the LAST promoted version,
            # actually hot-swapped mid-load, and never moved backwards
            "hot_swap_converged",
            len(swap_log) == n_versions
            and scorer.model_version == max(swap_log)
            and len(lat) >= 1
            and sorted(swap_times) == sorted(
                swap_times, key=swap_times.get),
            f"{n_versions} promotions -> {len(lat)} swaps applied in "
            f"version order ({len(coalesced)} coalesced: {coalesced}); "
            f"serving version ended at v{scorer.model_version}"),
        Invariant(
            "swap_within_slo",
            worst is not None and worst <= slo_swap_s,
            f"worst promote->swap latency {worst:.3f}s "
            f"(slo {slo_swap_s}s) across {len(lat)} swaps"
            if worst is not None else "a swap was never observed"),
        *_identity_verdicts(broker, identities, parts),
        Invariant(
            "predictions_contiguous",
            pred_end == scorer.scored == published,
            f"predictions end {pred_end} == scored {scorer.scored} == "
            f"published {published} (no swap dropped or re-emitted a "
            f"row)" if pred_end == scorer.scored == published else
            f"predictions end {pred_end}, scored {scorer.scored}, "
            f"published {published} DIVERGE"),
        _check_commits_monotonic(commit_log),
        Invariant(
            "final_commit_at_end",
            all(broker.committed(GROUP, IN_TOPIC, p)
                == broker.end_offset(IN_TOPIC, p) for p in range(parts)),
            "committed == log end on every partition"),
        Invariant("no_degraded_units", not sup.degraded(),
                  f"degraded units: {sup.degraded() or 'none'}"),
    ]
    return DrillReport(
        drill="rollout", seed=seed, records=records,
        published=published, scored=scorer.scored,
        restarts={u.name: u.restarts for u in sup.units()},
        slos={"worst_swap_latency_s": worst},
        invariants=invariants, injected={})


# ------------------------------------------------------------ rollback
def drill_rollback(seed: int = 7, records: int = 1500,
                   slo_rollback_s: float = 60.0) -> DrillReport:
    """Rollback-on-regression, live: the bad model really serves.

    A baseline is trained on the stream's history and promoted; a
    deliberately degraded candidate is then DEPLOYED (serving flips to
    it — the production scorer hot-swaps onto the bad weights) while an
    A/B evaluation scores both versions against the live labeled
    stream.  The gate must detect the regression and re-point serving
    at the baseline within ``slo_rollback_s``; the production scorer
    must end up back on the baseline with zero records lost across the
    whole deploy→regress→rollback arc."""
    import jax

    from ..core.schema import KSQL_CAR_SCHEMA
    from ..gen.simulator import FleetGenerator, FleetScenario
    from ..ops.avro import AvroCodec
    from ..ops.framing import frame
    from ..stream.broker import Broker
    from ..train.live import ContinuousTrainer

    if records < 20 * CARS_PER_TICK:
        raise ValueError(f"rollback needs >= {20 * 25} records "
                         f"(training history + evaluation window), "
                         f"got {records}")
    parts = 2
    broker = Broker()
    broker.create_topic(IN_TOPIC, partitions=parts)
    broker.create_topic(PRED_TOPIC, partitions=1)
    commit_log: List[tuple] = []
    _record_commits(broker, commit_log, "stream")
    tmp = tempfile.TemporaryDirectory(prefix="iotml_drill_registry_")
    registry = ModelRegistry(tmp.name)

    gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK, seed=seed,
                                       failure_rate=0.05))
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    ticks = max(1, -(-records // CARS_PER_TICK))
    history_ticks = max(1, ticks // 2)
    published = 0
    for _ in range(history_ticks):
        published += _publish_tick(gen, broker, codec, KSQL_CAR_SCHEMA,
                                   frame)

    # baseline: quick-trained on the history, published through the
    # async checkpointer (auto-promoted to serving)
    trainer = ContinuousTrainer(
        broker, IN_TOPIC, None, checkpointer=AsyncCheckpointer(registry),
        group="mlops-drill-train", batch_size=50,
        take_batches=max(2, min(8, published // 60)), epochs_per_round=3)
    trainer.train_round()
    trainer.checkpointer.write_once()
    baseline = registry.latest()

    # production scorer + watcher, supervised, serving the baseline
    identities: List[Tuple[int, int]] = []
    consumer = _identity_consumer(broker, parts, GROUP, identities)
    scorer = _make_scorer(
        broker, consumer,
        params_from_h5_bytes(registry.load_bytes(baseline, "model.h5")),
        threshold=5.0)
    scorer.model_version = baseline
    watcher = RegistryWatcher(registry, scorers=[scorer],
                              poll_interval_s=0.02)
    state: dict = {"rewinds": 0}
    sup = Supervisor(poll_interval_s=0.05, name="mlops-drill-supervisor")
    sup.add_loop("scorer", _scorer_loop(scorer, consumer, state),
                 heartbeat_timeout_s=60.0)
    sup.add_loop("registry-watcher", watcher.unit_loop(),
                 heartbeat_timeout_s=30.0)
    sup.start()

    # candidate: the baseline's weights wrecked with seeded noise
    good = params_from_h5_bytes(registry.load_bytes(baseline, "model.h5"))
    noise = np.random.RandomState(seed)
    bad = jax.tree_util.tree_map(
        lambda a: np.asarray(a)
        + noise.normal(0, 1.0, np.shape(a)).astype(np.float32), good)
    candidate = registry.publish(
        {"model.h5": params_to_h5_bytes(bad)},
        metrics={"degraded": 1.0}).version

    gate = RolloutGate(min_records=max(50, min(300, published // 2)),
                       epsilon=0.02)
    ab = ABRollout(broker, IN_TOPIC, registry, baseline, candidate,
                   gate=gate, threshold=5.0, deploy_candidate=True,
                   from_start=True, group_prefix="mlops-drill-ab")
    t_deploy = time.monotonic()
    t_decided = None
    t_restored = None
    saw_candidate_live = False
    try:
        for _ in range(ticks - history_ticks):
            published += _publish_tick(gen, broker, codec,
                                       KSQL_CAR_SCHEMA, frame)
            ab.step(max_rows=5_000)
            if scorer.model_version == candidate:
                saw_candidate_live = True
            if ab.decision is not None and t_decided is None:
                t_decided = time.monotonic()
            if t_decided is not None and t_restored is None \
                    and scorer.model_version == baseline:
                t_restored = time.monotonic()
            time.sleep(0.002)
        # drain the gate to a verdict if the publish loop outran it
        deadline = time.monotonic() + slo_rollback_s
        while ab.decision is None and time.monotonic() < deadline:
            if ab.step(max_rows=5_000) == 0:
                time.sleep(0.01)
            if scorer.model_version == candidate:
                saw_candidate_live = True
        if ab.decision is not None and t_decided is None:
            t_decided = time.monotonic()
        _wait(lambda: scorer.model_version == baseline, 15.0)
        if t_restored is None and scorer.model_version == baseline:
            t_restored = time.monotonic()
        _wait(lambda: consumer.at_end()
              and all(broker.committed(GROUP, IN_TOPIC, p)
                      == broker.end_offset(IN_TOPIC, p)
                      for p in range(parts)), 30.0)
        serving_final = registry.channel("serving")
    finally:
        sup.stop()
        watcher.stop()
        tmp.cleanup()

    t_rollback = (t_decided - t_deploy) if t_decided is not None else None
    t_heal = (t_restored - t_deploy) if t_restored is not None else None
    qb, qc = ab.quality("baseline"), ab.quality("candidate")
    invariants = [
        Invariant(
            "candidate_deployed_live",
            saw_candidate_live,
            "the production scorer really served the degraded "
            "candidate (deploy-during-eval, not shadow)"
            if saw_candidate_live else
            "the candidate never reached the production scorer"),
        Invariant(
            "regression_rolled_back",
            ab.decision == "rollback",
            f"gate verdict {ab.decision!r} (baseline auc={qb['auc']}, "
            f"candidate auc={qc['auc']})"),
        Invariant(
            "rollback_within_slo",
            t_rollback is not None and t_rollback <= slo_rollback_s,
            f"deploy -> rollback verdict in {t_rollback:.3f}s "
            f"(slo {slo_rollback_s}s)" if t_rollback is not None
            else "the gate never decided"),
        Invariant(
            "production_healed",
            t_heal is not None and scorer.model_version == baseline
            and serving_final == baseline,
            f"serving re-pointed and the production scorer swapped "
            f"back to v{baseline} {t_heal:.3f}s after deploy"
            if t_heal is not None else
            "production scorer never returned to the baseline"),
        *_identity_verdicts(broker, identities, parts),
        _check_commits_monotonic(commit_log),
        Invariant(
            "final_commit_at_end",
            all(broker.committed(GROUP, IN_TOPIC, p)
                == broker.end_offset(IN_TOPIC, p) for p in range(parts)),
            "committed == log end on every partition"),
        Invariant("no_degraded_units", not sup.degraded(),
                  f"degraded units: {sup.degraded() or 'none'}"),
    ]
    return DrillReport(
        drill="rollback", seed=seed, records=records,
        published=published, scored=scorer.scored,
        restarts={u.name: u.restarts for u in sup.units()},
        slos={"time_to_rollback_s": t_rollback,
              "time_to_production_healed_s": t_heal},
        invariants=invariants, injected={})


DRILLS = {
    "rollout": drill_rollout,
    "rollback": drill_rollback,
}
