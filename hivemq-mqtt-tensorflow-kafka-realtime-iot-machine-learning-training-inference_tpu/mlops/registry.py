"""Versioned model registry — the GCS bucket, grown a commit protocol.

The reference hands models from train to predict as one mutable GCS
object name (cardata-v3.py:227-232, :255-261): no versions, no lineage,
no record of WHAT data a blob was trained through, and a redeploy as
the only rollback.  This registry is that handoff made operable:

- **monotonic versions**: every publish gets the next integer id; a
  version directory is immutable once committed;
- **manifest as commit marker**: artifacts are staged into a hidden
  directory, renamed into place, and only then does ``manifest.json``
  land (via the store's ``atomic_write`` tmp+rename+fsync discipline).
  A crash anywhere mid-publish leaves a manifest-less directory that
  readers never see and ``recover()`` sweeps — the torn-tail tolerance
  of ``iotml.store``, applied to model state;
- **offsets in the manifest**: each version records the exact
  ``(topic, partition, next_offset)`` cursors it was trained through
  (offsets-as-checkpoint, ARCHITECTURE §7) plus metrics and parent
  lineage, so model state and stream position move as ONE atomic unit;
- **channels**: tiny atomic pointer files (``serving``, ``candidate``)
  name the version each role should run; ``promote``/``rollback`` are
  pointer flips recorded in an append-only history, and every serving
  flip is ALSO published through a ``supervise.Topology`` cell
  (version id as the epoch) so in-process watchers detect a new model
  the same way clients detect a new leader.

Lint rule R11 keeps every write under a registry directory inside this
module — the same one-writer discipline R9 gives the store.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..chaos import faults as chaos
from ..obs import metrics as obs_metrics
from ..store import atomic_write, fsync_dir
from ..supervise.topology import Topology

#: channels a pointer file may name — a typo'd channel write would
#: otherwise mint a pointer no reader ever resolves
CHANNELS = ("serving", "candidate")

_VERSION_FMT = "v{:010d}"


def _version_dirname(version: int) -> str:
    return _VERSION_FMT.format(version)


def _parse_version(name: str) -> Optional[int]:
    if not name.startswith("v") or not name[1:].isdigit():
        return None
    return int(name[1:])


class Manifest:
    """One committed version's metadata (parsed manifest.json)."""

    __slots__ = ("version", "parent", "created_ts", "offsets", "metrics",
                 "artifacts", "step")

    def __init__(self, version: int, parent: Optional[int],
                 created_ts: float, offsets: List[Tuple[str, int, int]],
                 metrics: Dict[str, float], artifacts: Dict[str, dict],
                 step: int = 0):
        self.version = version
        self.parent = parent
        self.created_ts = created_ts
        self.offsets = offsets
        self.metrics = metrics
        self.artifacts = artifacts
        self.step = step

    def to_dict(self) -> dict:
        return {"version": self.version, "parent": self.parent,
                "created_ts": self.created_ts, "step": self.step,
                "offsets": [list(o) for o in self.offsets],
                "metrics": self.metrics, "artifacts": self.artifacts}

    @classmethod
    def from_dict(cls, doc: dict) -> "Manifest":
        return cls(version=int(doc["version"]),
                   parent=(None if doc.get("parent") is None
                           else int(doc["parent"])),
                   created_ts=float(doc.get("created_ts", 0.0)),
                   offsets=[(str(t), int(p), int(o))
                            for t, p, o in doc.get("offsets", [])],
                   metrics=dict(doc.get("metrics", {})),
                   artifacts=dict(doc.get("artifacts", {})),
                   step=int(doc.get("step", 0)))


class ModelRegistry:
    """Filesystem-backed registry: versions/, channels/, history.jsonl.

    Single-process writers are the expected shape (ONE trainer owns
    publication, like ONE SegmentWriter owns a store dir); readers are
    arbitrary.  All mutation goes through this class (lint R11)."""

    def __init__(self, root: str, component: str = "trainer"):
        self.root = os.path.abspath(root)
        self.component = component
        self._versions_dir = os.path.join(self.root, "versions")
        self._channels_dir = os.path.join(self.root, "channels")
        os.makedirs(self._versions_dir, exist_ok=True)
        os.makedirs(self._channels_dir, exist_ok=True)
        #: in-process change feed: serving flips publish
        #: (version-name, epoch=version) exactly like leader promotions,
        #: so a watcher polls one generation counter, not the disk
        serving = self.channel("serving")
        self.cell = Topology(leader=_version_dirname(serving or 0),
                             epoch=serving or 0)

    # ------------------------------------------------------------ paths
    def version_dir(self, version: int) -> str:
        return os.path.join(self._versions_dir, _version_dirname(version))

    def artifact_path(self, version: int, name: str) -> str:
        return os.path.join(self.version_dir(version), name)

    # ---------------------------------------------------------- reading
    def versions(self) -> List[int]:
        """Committed (manifest-intact) versions, ascending.  A version
        directory without a parseable manifest is a torn publish —
        invisible here, swept by ``recover()``."""
        out = []
        try:
            names = os.listdir(self._versions_dir)
        except FileNotFoundError:
            return []
        for name in names:
            v = _parse_version(name)
            if v is None:
                continue
            if self._read_manifest(v) is not None:
                out.append(v)
        return sorted(out)

    def latest(self) -> Optional[int]:
        vs = self.versions()
        return vs[-1] if vs else None

    def _read_manifest(self, version: int) -> Optional[Manifest]:
        path = os.path.join(self.version_dir(version), "manifest.json")
        try:
            with open(path, "rb") as fh:
                doc = json.loads(fh.read().decode())
            m = Manifest.from_dict(doc)
        except (FileNotFoundError, NotADirectoryError, ValueError,
                KeyError, TypeError):
            return None
        if m.version != version:
            return None
        return m

    def manifest(self, version: int) -> Manifest:
        m = self._read_manifest(version)
        if m is None:
            raise KeyError(f"no committed version {version} in {self.root}")
        return m

    def load_bytes(self, version: int, name: str) -> bytes:
        """Read one artifact, verified against the manifest checksum —
        a bit-rotted or truncated blob fails loudly, never loads."""
        m = self.manifest(version)
        if name not in m.artifacts:
            raise KeyError(f"version {version} has no artifact {name!r} "
                           f"(have: {sorted(m.artifacts)})")
        with open(self.artifact_path(version, name), "rb") as fh:
            data = fh.read()
        want = m.artifacts[name].get("sha256")
        if want and hashlib.sha256(data).hexdigest() != want:
            raise ValueError(
                f"artifact {name!r} of version {version} fails its "
                f"manifest checksum (torn or corrupted blob)")
        return data

    # --------------------------------------------------------- channels
    def channel(self, channel: str) -> Optional[int]:
        """Resolve a channel pointer to a committed version.

        A pointer naming a torn/missing version (crash between a sweep
        and a re-point, or manual surgery) falls back to the newest
        intact version instead of serving nothing."""
        self._check_channel(channel)
        try:
            with open(os.path.join(self._channels_dir, channel)) as fh:
                v = _parse_version(fh.read().strip())
        except FileNotFoundError:
            return None
        if v is not None and self._read_manifest(v) is not None:
            return v
        return self.latest()

    @staticmethod
    def _check_channel(channel: str) -> None:
        if channel not in CHANNELS:
            raise ValueError(f"unknown channel {channel!r} "
                             f"(have: {CHANNELS})")

    def set_channel(self, channel: str, version: int,
                    event: str = "set") -> None:
        self._check_channel(channel)
        if self._read_manifest(version) is None:
            raise KeyError(f"cannot point {channel!r} at uncommitted "
                           f"version {version}")
        atomic_write(os.path.join(self._channels_dir, channel),
                     _version_dirname(version).encode())
        self._history({"event": event, "channel": channel,
                       "version": version, "t": time.time()})
        if channel == "serving":
            # epochs only move forward in a Topology; a rollback is a
            # NEW term serving an OLD version, exactly like a failover
            # is a new epoch serving the old log — so the epoch is
            # max(version, current+1), and the leader string names the
            # version being served
            epoch = max(version, self.cell.epoch + 1)
            self.cell.publish(_version_dirname(version), epoch)
            obs_metrics.model_version.set(version,
                                          component=self.component)

    def promote(self, version: int) -> None:
        """candidate → serving (the rollout gate's accept edge)."""
        self.set_channel("serving", version, event="promote")

    def rollback(self, version: int) -> None:
        """serving → an older committed version (the reject edge)."""
        self.set_channel("serving", version, event="rollback")

    def history(self) -> List[dict]:
        """Parsed history events; a torn last line (crash mid-append)
        is skipped, not fatal."""
        out = []
        try:
            with open(os.path.join(self.root, "history.jsonl")) as fh:
                for line in fh:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail
        except FileNotFoundError:
            pass
        return out

    def _history(self, event: dict) -> None:
        with open(os.path.join(self.root, "history.jsonl"), "a") as fh:
            fh.write(json.dumps(event, sort_keys=True) + "\n")

    # --------------------------------------------------------- writing
    def next_version(self) -> int:
        latest = self.latest()
        return 1 if latest is None else latest + 1

    def publish(self, artifacts: Dict[str, bytes], *,
                offsets: Sequence[Tuple[str, int, int]] = (),
                metrics: Optional[Dict[str, float]] = None,
                step: int = 0,
                parent: Optional[int] = None) -> Manifest:
        """Commit a new version.  Crash-safe by construction:

        1. artifacts land (fsynced) in a hidden ``.stage-*`` dir;
        2. the stage dir is renamed to ``versions/vN`` — visible but
           NOT committed (no manifest yet; readers skip it);
        3. ``manifest.json`` is atomic-written LAST: its appearance IS
           the commit, after which the version is immutable.

        A kill at any point leaves either a stage dir or a manifest-less
        version dir; both are invisible to readers and swept by
        ``recover()``, which also means version ids of failed publishes
        are reused — ids number COMMITS, not attempts."""
        version = self.next_version()
        if parent is None:
            parent = self.channel("serving") or self.latest()
        stage = os.path.join(self.root,
                             f".stage-{_version_dirname(version)}-{os.getpid()}")
        os.makedirs(stage, exist_ok=True)
        art_meta = {}
        for name, data in artifacts.items():
            if name == "manifest.json" or os.sep in name:
                raise ValueError(f"illegal artifact name {name!r}")
            atomic_write(os.path.join(stage, name), data)
            art_meta[name] = {"sha256": hashlib.sha256(data).hexdigest(),
                              "bytes": len(data)}
        final = self.version_dir(version)
        if os.path.isdir(final):
            # a previous torn publish of this reused id (manifest-less
            # by definition, or versions() would have numbered past it)
            shutil.rmtree(final)
        os.replace(stage, final)
        # the faultpoint between artifact visibility and the manifest:
        # an injected crash HERE leaves a manifest-less version dir —
        # the torn-publish artifact readers must never serve and
        # recover() must sweep (chaos trainer-crash-mid-checkpoint)
        chaos.point("registry.commit")
        manifest = Manifest(version=version, parent=parent,
                            created_ts=time.time(),
                            offsets=[tuple(o) for o in offsets],
                            metrics=dict(metrics or {}),
                            artifacts=art_meta, step=step)
        atomic_write(os.path.join(final, "manifest.json"),
                     json.dumps(manifest.to_dict(), indent=2,
                                sort_keys=True).encode())
        # two direntry flushes, both load-bearing: the manifest's
        # rename lives in vN/ (without it a power cut can undo the
        # commit AFTER the group commit trailed it — committed offsets
        # past the newest durable manifest), the stage->vN rename in
        # versions/
        fsync_dir(final)
        fsync_dir(self._versions_dir)
        self._history({"event": "publish", "version": version,
                       "parent": parent, "t": manifest.created_ts})
        obs_metrics.registry_publishes.inc()
        obs_metrics.model_version.set(version, component=self.component)
        return manifest

    # --------------------------------------------------------- recovery
    def recover(self) -> int:
        """Sweep torn publishes: stage dirs and manifest-less version
        dirs (a writer died mid-commit).  Returns dirs removed.  Safe
        to run on every mount — committed versions are never touched."""
        removed = 0
        for name in os.listdir(self.root):
            if name.startswith(".stage-"):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
                removed += 1
        for name in os.listdir(self._versions_dir):
            v = _parse_version(name)
            if v is None:
                continue
            if self._read_manifest(v) is None:
                shutil.rmtree(os.path.join(self._versions_dir, name),
                              ignore_errors=True)
                removed += 1
        if removed:
            obs_metrics.registry_torn_recovered.inc(removed)
        # a pointer may now name a swept version; channel() already
        # falls back on read, but re-anchor the in-process cell too
        serving = self.channel("serving")
        if serving is not None and \
                _version_dirname(serving) != self.cell.leader:
            self.cell.publish(_version_dirname(serving),
                              max(serving, self.cell.epoch + 1))
        return removed

    def prune(self, keep: int) -> int:
        """Bound the registry: remove committed versions older than the
        newest ``keep``, never a channel target (a rolled-back serving
        version stays restorable for as long as it serves).  Returns
        versions removed.  Version ids stay monotonic — ``latest()``
        survives every prune, so ``next_version`` never reuses an id.
        Bounding the version count also bounds ``versions()``'s
        manifest sweep, keeping publish cost flat over a trainer's
        lifetime."""
        if keep <= 0:
            return 0
        vs = self.versions()
        pinned = {self.channel(c) for c in CHANNELS}
        removed = 0
        for v in vs[:-keep] if len(vs) > keep else []:
            if v in pinned:
                continue
            shutil.rmtree(self.version_dir(v), ignore_errors=True)
            removed += 1
        if removed:
            fsync_dir(self._versions_dir)
            self._history({"event": "prune", "removed": removed,
                           "t": time.time()})
            obs_metrics.registry_pruned.inc(removed)
        return removed

    # ------------------------------------------------------ introspection
    def describe(self) -> dict:
        vs = self.versions()
        return {
            "root": self.root,
            "versions": vs,
            "serving": self.channel("serving"),
            "candidate": self.channel("candidate"),
            "latest": vs[-1] if vs else None,
        }
