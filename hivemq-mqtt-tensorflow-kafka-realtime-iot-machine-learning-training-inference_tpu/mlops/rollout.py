"""Zero-downtime rollout: registry watchers and the A/B quality gate.

The reference rolls a model out by restarting its predict pods
(`run.sh:16-91`) — every rollout is downtime, and a bad model stays
bad until a human redeploys the old blob.  Here rollout is a data-path
event:

- ``RegistryWatcher`` watches a registry channel (the in-process
  ``Topology`` cell when available, the atomic pointer file always)
  and hot-swaps every attached scorer's params **between
  super-batches** — the input cursor and the OutputSequence index
  stream are untouched, so a swap can neither drop nor double-score a
  record (drilled under load by ``iotml.mlops.drill``).
- ``ABRollout`` runs TWO versions against the same stream — each with
  its own consumer group and its own predictions topic — and scores
  both live against the stream's labels (the r04 detection-quality
  protocol: threshold confusion + histogram AUC).  ``RolloutGate``
  compares them once enough labeled records accrued and either
  **promotes** the candidate to serving or **rolls back** to the
  baseline (`iotml_rollouts_total{outcome=...}`); with
  ``deploy_candidate=True`` the candidate serves DURING evaluation
  (the rollback-on-regression shape the drill proves within an SLO).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs import metrics as obs_metrics
from .checkpoint import params_from_h5_bytes
from .registry import ModelRegistry


class RegistryWatcher:
    """Poll a registry channel; hot-swap attached scorers on change."""

    def __init__(self, registry: ModelRegistry, scorers=(),
                 channel: str = "serving", component: str = "scorer",
                 poll_interval_s: float = 0.25):
        self.registry = registry
        self.channel = channel
        self.component = component
        self.poll_interval_s = poll_interval_s
        self.scorers: List = list(scorers)
        self.current_version: Optional[int] = None
        self.swaps = 0
        self.last_swap_s: Optional[float] = None
        self._params_cache = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- wiring
    def attach(self, scorer) -> None:
        """Add a scorer; it immediately receives the current model (a
        late-joining fleet member must not score on random init)."""
        with self._lock:
            self.scorers.append(scorer)
            if self._params_cache is not None:
                self._apply(scorer, self._params_cache,
                            self.current_version)

    def _apply(self, scorer, params, version) -> None:
        try:
            scorer.set_params(params, version=version)
        except TypeError:  # plain set_params(params) duck-types too
            scorer.set_params(params)

    # ---------------------------------------------------------- polling
    def poll_once(self) -> bool:
        """One channel read; swap + fan-out when the version moved.
        Cheap by design — callers run this between batches/rounds."""
        v = self.registry.channel(self.channel)
        if v is None or v == self.current_version:
            return False
        t0 = time.perf_counter()
        params = params_from_h5_bytes(
            self.registry.load_bytes(v, "model.h5"))
        with self._lock:
            self.current_version = v
            self._params_cache = params
            for s in self.scorers:
                self._apply(s, params, v)
            self.swaps += 1
        self.last_swap_s = time.perf_counter() - t0
        obs_metrics.model_swaps.inc()
        obs_metrics.model_version.set(v, component=self.component)
        return True

    def wait_for_model(self, timeout_s: float = 60.0) -> int:
        """Block until the channel names a committed version (the
        predict pod's download-at-start, registry edition)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.poll_once() or self.current_version is not None:
                return self.current_version
            time.sleep(0.05)
        raise TimeoutError(
            f"no committed version on channel {self.channel!r} of "
            f"{self.registry.root} after {timeout_s}s")

    # -------------------------------------------------------- lifecycle
    def unit_loop(self) -> Callable:
        """Watcher body for a ``SupervisedUnit`` (cli.up --supervise)."""

        def loop(unit):
            while not unit.should_stop():
                unit.heartbeat()
                self.poll_once()
                self._stop.wait(self.poll_interval_s)

        return loop

    def start(self) -> "RegistryWatcher":
        from ..supervise.registry import register_thread

        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.poll_interval_s):
                try:
                    self.poll_once()
                except (OSError, ValueError, KeyError):
                    continue  # torn read mid-publish: next poll heals
        self._thread = register_thread(threading.Thread(
            target=run, daemon=True, name="iotml-registry-watcher"))
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


# ------------------------------------------------------------ the gate
def scorer_quality(scorer) -> Dict[str, Optional[float]]:
    """The r04 detection-quality protocol over a live scorer: threshold
    confusion → precision/recall/F1, error histograms → AUC."""
    from ..serve.scorer import hist_auc

    q = scorer.quality
    labeled = q["tp"] + q["fp"] + q["fn"] + q["tn"]
    precision = q["tp"] / (q["tp"] + q["fp"]) if q["tp"] + q["fp"] else None
    recall = q["tp"] / (q["tp"] + q["fn"]) if q["tp"] + q["fn"] else None
    f1 = (2 * precision * recall / (precision + recall)
          if precision and recall and precision + recall else
          (0.0 if precision is not None or recall is not None else None))
    auc = hist_auc(scorer.err_hist["true"], scorer.err_hist["false"])
    return {"labeled": labeled, "precision": precision, "recall": recall,
            "f1": f1, "auc": auc}


class RolloutGate:
    """Promote/rollback policy over two live quality snapshots.

    The candidate must not regress either F1 or AUC by more than
    ``epsilon`` (absolute).  ``min_records`` labeled rows (per side)
    and at least one positive label are required before a verdict —
    deciding on nothing is how a gate lies."""

    def __init__(self, min_records: int = 300, epsilon: float = 0.02):
        self.min_records = min_records
        self.epsilon = epsilon

    def decide(self, baseline: Dict, candidate: Dict) -> Optional[str]:
        """'promote' | 'rollback' | None (not enough evidence yet)."""
        for side in (baseline, candidate):
            if side["labeled"] < self.min_records:
                return None
        # comparable evidence: a side that saw no positives has an
        # undefined recall/AUC — wait for the stream to show failures
        if baseline["auc"] is None or candidate["auc"] is None:
            return None
        b_f1 = baseline["f1"] if baseline["f1"] is not None else 0.0
        c_f1 = candidate["f1"] if candidate["f1"] is not None else 0.0
        if c_f1 < b_f1 - self.epsilon or \
                candidate["auc"] < baseline["auc"] - self.epsilon:
            return "rollback"
        return "promote"


class ABRollout:
    """Drive baseline + candidate scorers over one stream; gate them.

    Both sides consume the SAME topic with their own groups and write
    to their own predictions topic (``<result_topic>.v<version>``), so
    the comparison artifact — two aligned prediction streams — is
    itself on the log, replayable like everything else.

    Args:
      broker/topic: the labeled input stream.
      registry: source of both versions' weights.
      baseline/candidate: committed version ids.
      deploy_candidate: point ``serving`` at the candidate for the
        duration (watchers swap the production fleet); a rollback
        verdict then re-points serving at the baseline — the
        rollback-on-regression drill shape.  Off, the candidate runs
        shadow-only and promotion is the only serving change.
      from_start: score the retained history (drills/bench); default
        starts both sides at the live log end.
    """

    def __init__(self, broker, topic: str, registry: ModelRegistry,
                 baseline: int, candidate: int, model=None,
                 result_topic: str = "model-predictions",
                 threshold: float = 0.5, gate: Optional[RolloutGate] = None,
                 batch_size: int = 100, normalizer=None,
                 deploy_candidate: bool = False, from_start: bool = False,
                 group_prefix: str = "ab-rollout"):
        from ..data.dataset import SensorBatches
        from ..serve.scorer import StreamScorer
        from ..stream.consumer import StreamConsumer
        from ..stream.producer import OutputSequence

        if model is None:
            from ..models.autoencoder import CAR_AUTOENCODER

            model = CAR_AUTOENCODER
        self.registry = registry
        self.baseline = baseline
        self.candidate = candidate
        self.gate = gate or RolloutGate()
        self.deploy_candidate = deploy_candidate
        self.decision: Optional[str] = None
        self.decided_at_s: Optional[float] = None
        self.sides: Dict[str, StreamScorer] = {}
        self.consumers = {}
        parts = range(broker.topic(topic).partitions)
        batch_kw = {} if normalizer is None else dict(normalizer=normalizer)
        for name, version in (("baseline", baseline),
                              ("candidate", candidate)):
            params = params_from_h5_bytes(
                registry.load_bytes(version, "model.h5"))
            group = f"{group_prefix}-{name}"
            consumer = StreamConsumer.from_committed(
                broker, topic, parts, group=group, eof=False)
            if not from_start:
                for p in parts:
                    consumer.seek(topic, p, broker.end_offset(topic, p))
            out_topic = f"{result_topic}.v{version}"
            broker.create_topic(out_topic)
            out = OutputSequence(broker, out_topic, partition=0)
            batches = SensorBatches(consumer, batch_size=batch_size,
                                    keep_labels=True, **batch_kw)
            scorer = StreamScorer(model, params, batches, out,
                                  threshold=threshold)
            self.sides[name] = scorer
            self.consumers[name] = consumer
        if deploy_candidate:
            registry.promote(candidate)
        self._t0 = time.monotonic()

    # ---------------------------------------------------------- driving
    def step(self, max_rows: Optional[int] = 20_000) -> int:
        """Drain both sides once; apply the gate when evidence
        suffices.  Returns rows scored this step."""
        n = 0
        for scorer in self.sides.values():
            n += scorer.score_available(max_rows=max_rows)
        if self.decision is None:
            verdict = self.gate.decide(self.quality("baseline"),
                                       self.quality("candidate"))
            if verdict is not None:
                self._settle(verdict)
        return n

    def quality(self, side: str) -> Dict:
        return scorer_quality(self.sides[side])

    def _settle(self, verdict: str) -> None:
        self.decision = verdict
        self.decided_at_s = time.monotonic() - self._t0
        if verdict == "promote":
            self.registry.promote(self.candidate)
            obs_metrics.rollouts.inc(outcome="promoted")
        else:
            # rollback: serving returns to (or stays at) the baseline.
            # Recorded even when the candidate never served — the
            # history line is the audit trail either way.
            self.registry.rollback(self.baseline)
            obs_metrics.rollouts.inc(outcome="rolled_back")

    def run(self, stop: Optional[Callable[[], bool]] = None,
            timeout_s: float = 60.0,
            poll_interval_s: float = 0.02) -> Optional[str]:
        """Drive until a verdict (or timeout/stop); returns it."""
        deadline = time.monotonic() + timeout_s
        while self.decision is None and time.monotonic() < deadline \
                and (stop is None or not stop()):
            if self.step() == 0:
                time.sleep(poll_interval_s)
        return self.decision
