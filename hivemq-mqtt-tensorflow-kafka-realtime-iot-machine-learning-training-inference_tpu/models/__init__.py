from .autoencoder import DenseAutoencoder, CAR_AUTOENCODER, CREDITCARD_AUTOENCODER  # noqa: F401
from .lstm import LSTMSeq2Seq  # noqa: F401
from .mnist import MNISTClassifier, MNISTBaseline  # noqa: F401
from .transformer import SensorFormer  # noqa: F401
from .moe import MoESensorFormer, MoEFFN, MoEBlock  # noqa: F401
