"""Dense anomaly-detection autoencoder (flax.linen).

Architecture parity with the reference (cardata-v3.py:176-194 and the
creditcard notebook cell 19):

    input_dim → Dense(encoding_dim, tanh, L1 activity reg)
              → Dense(hidden_dim, relu)
              → Dense(hidden_dim, tanh)
              → Dense(input_dim, relu)

with input_dim/encoding_dim/hidden_dim = 18/14/7 (car) or 30/14/7
(creditcard), L1 activity coefficient 1e-7, Adam lr 1e-3 (Keras default),
loss MSE.

Keras semantics preserved exactly where they affect training dynamics:
- the *activity* regularizer penalizes the first encoder layer's output,
  `l1 * sum(|h|) / batch_size` (tf.keras divides activity-regularizer loss
  by the batch size to make it batch-agnostic);
- Glorot-uniform kernel init, zero bias init (Keras Dense defaults) — flax's
  default is lecun_normal, so we set glorot explicitly.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class DenseAutoencoder(nn.Module):
    input_dim: int = 18
    encoding_dim: int = 14
    hidden_dim: int = 7
    activity_l1: float = 1e-7

    def setup(self):
        # attribute names become the param-tree keys (encoder0/encoder1/...)
        dense = lambda n: nn.Dense(  # noqa: E731
            n, kernel_init=nn.initializers.glorot_uniform())
        self.encoder0 = dense(self.encoding_dim)
        self.encoder1 = dense(self.hidden_dim)
        self.decoder0 = dense(self.hidden_dim)
        self.decoder1 = dense(self.input_dim)

    def __call__(self, x, with_penalty: bool = False):
        h = nn.tanh(self.encoder0(x))
        # Keras activity regularizer: l1 * sum(|h|) / batch  (batch = leading dim)
        penalty = self.activity_l1 * jnp.sum(jnp.abs(h)) / x.shape[0]
        out = nn.relu(self.decoder1(nn.tanh(self.decoder0(nn.relu(self.encoder1(h))))))
        if with_penalty:
            return out, penalty
        return out

    def encode(self, x):
        """Latent code (first two layers) — for downstream embedding use.
        Call as `model.apply({"params": p}, x, method=DenseAutoencoder.encode)`."""
        return nn.relu(self.encoder1(nn.tanh(self.encoder0(x))))


# The two concrete variants the reference ships.
CAR_AUTOENCODER = DenseAutoencoder(input_dim=18)
CREDITCARD_AUTOENCODER = DenseAutoencoder(input_dim=30)


def reconstruction_error(model: DenseAutoencoder, params, x) -> jnp.ndarray:
    """Per-row reconstruction MSE — the anomaly score used by the reference's
    threshold analysis (streaming notebook cells 21-26, threshold 5)."""
    recon = model.apply({"params": params}, x)
    return jnp.mean(jnp.square(recon - x), axis=-1)
