"""Keras-HDF5 checkpoint exporter — the reverse of `h5_import`.

The reference's train→GCS→predict handoff moves models as Keras `.h5`
blobs (reference cardata-v3.py:227-231 uploads, :255-261 downloads into
`tf.keras.models.load_model`).  Round 1 could only *import* those; this
writes repo-trained autoencoder params back out in the exact byte layout
the reference's own checkpoints use (verified field-for-field against
`/root/reference/models/autoencoder_sensor_anomaly_detection.h5`):

- root attrs `backend` / `keras_version` / `model_config` /
  `training_config` — `model_config` is the functional-Model JSON a
  reference-side `tf.keras.models.load_model` rebuilds the architecture
  from (InputLayer + 4 Dense, tanh/relu/tanh/relu, L1 activity regularizer
  on the first layer, GlorotUniform init), and `training_config` carries
  the Adam/MSE/accuracy compile settings (cardata-v3.py:190-194).
- `model_weights/<layer>/<layer>/{kernel:0,bias:0}` datasets with the
  `layer_names` / `weight_names` attributes Keras' HDF5 loader walks.

So a consumer still running the reference stack can score with models
trained here — interop both ways.
"""

from __future__ import annotations

import json

import numpy as np

_LAYER_ORDER = ("encoder0", "encoder1", "decoder0", "decoder1")
_ACTIVATIONS = ("tanh", "relu", "tanh", "relu")


def _dense_config(name: str, units: int, activation: str,
                  activity_l1: float = 0.0) -> dict:
    cfg = {
        "name": name, "trainable": True, "dtype": "float32",
        "units": units, "activation": activation, "use_bias": True,
        "kernel_initializer": {"class_name": "GlorotUniform",
                               "config": {"seed": None}},
        "bias_initializer": {"class_name": "Zeros", "config": {}},
        "kernel_regularizer": None, "bias_regularizer": None,
        "activity_regularizer": None,
        "kernel_constraint": None, "bias_constraint": None,
    }
    if activity_l1:
        cfg["activity_regularizer"] = {
            "class_name": "L1L2", "config": {"l1": activity_l1, "l2": 0.0}}
    return cfg


def _model_config(input_dim: int, units: list, activity_l1: float,
                  style: str = "reference") -> str:
    """Functional-model config JSON.

    style="reference" reproduces the reference checkpoint's byte layout
    exactly — including its pre-TF2 single-nested `inbound_nodes`
    (`[['input_1', 0, 0, {}]]`), which Keras 3 can no longer deserialize
    (it rejects the reference's own artifacts identically).
    style="modern" emits the TF2-era triple-nested form
    (`[[['input_1', 0, 0, {}]]]`) that current Keras' legacy-h5 loader
    accepts — same weights, same architecture, loadable today."""
    modern = style == "modern"
    layers = [{
        "name": "input_1", "class_name": "InputLayer",
        "config": {"batch_input_shape": [None, input_dim],
                   "dtype": "float32", "sparse": False, "name": "input_1"},
        "inbound_nodes": [],
    }]
    prev = "input_1"
    for i, n in enumerate(units):
        name = "dense" if i == 0 else f"dense_{i}"
        node = [prev, 0, 0, {}]
        layers.append({
            "name": name, "class_name": "Dense",
            "config": _dense_config(name, n, _ACTIVATIONS[i],
                                    activity_l1 if i == 0 else 0.0),
            "inbound_nodes": [[node]] if modern else [node],
        })
        prev = name
    io_in, io_out = ["input_1", 0, 0], [prev, 0, 0]
    return json.dumps({"class_name": "Model", "config": {
        "name": "model", "layers": layers,
        "input_layers": [io_in] if modern else io_in,
        "output_layers": [io_out] if modern else io_out}})


_TRAINING_CONFIG = json.dumps({
    "optimizer_config": {"class_name": "Adam", "config": {
        "name": "Adam", "learning_rate": 0.001, "decay": 0.0,
        "beta_1": 0.9, "beta_2": 0.999, "epsilon": 1e-07,
        "amsgrad": False}},
    "loss": "mean_squared_error", "metrics": ["accuracy"],
    "weighted_metrics": None, "sample_weight_mode": None,
    "loss_weights": None,
})


def autoencoder_params_to_h5(params: dict, path: str,
                             activity_l1: float = 1e-7,
                             style: str = "reference") -> str:
    """Write DenseAutoencoder params as a reference-compatible Keras h5.

    `params` is the flax tree {encoder0|encoder1|decoder0|decoder1:
    {kernel, bias}}.  Keras Dense kernels are [in, out] like flax's, so
    tensors pass through unchanged.

    style: "reference" (default) matches the reference checkpoints'
    byte layout field-for-field; "modern" differs only in the
    model_config nesting so CURRENT Keras can `load_model` it (see
    `_model_config` — verified by tests/test_h5_keras_interop.py)."""
    import h5py

    stack = [params[name] for name in _LAYER_ORDER]
    input_dim = int(np.asarray(stack[0]["kernel"]).shape[0])
    units = [int(np.asarray(l["kernel"]).shape[1]) for l in stack]
    keras_names = ["dense" if i == 0 else f"dense_{i}"
                   for i in range(len(stack))]

    with h5py.File(path, "w") as f:
        f.attrs["backend"] = np.bytes_(b"tensorflow")
        f.attrs["keras_version"] = np.bytes_(b"2.2.4-tf")
        f.attrs["model_config"] = np.bytes_(
            _model_config(input_dim, units, activity_l1, style).encode())
        f.attrs["training_config"] = np.bytes_(_TRAINING_CONFIG.encode())
        mw = f.create_group("model_weights")
        layer_names = ["input_1"] + keras_names
        mw.attrs["layer_names"] = np.array(
            [n.encode() for n in layer_names],
            dtype=f"|S{max(len(n) for n in layer_names)}")
        mw.attrs["backend"] = np.bytes_(b"tensorflow")
        mw.attrs["keras_version"] = np.bytes_(b"2.2.4-tf")

        g_in = mw.create_group("input_1")
        g_in.attrs["weight_names"] = np.array([], dtype="float64")
        for kname, layer in zip(keras_names, stack):
            g = mw.create_group(kname)
            wn = [f"{kname}/kernel:0".encode(), f"{kname}/bias:0".encode()]
            g.attrs["weight_names"] = np.array(
                wn, dtype=f"|S{max(len(w) for w in wn)}")
            inner = g.create_group(kname)
            inner.create_dataset(
                "kernel:0", data=np.asarray(layer["kernel"], np.float32))
            inner.create_dataset(
                "bias:0", data=np.asarray(layer["bias"], np.float32))
    return path
