"""Keras-HDF5 checkpoint importer.

The reference ships trained weights as Keras `.h5` files
(`models/autoencoder_sensor_anomaly_detection*.h5`, 30→14→7→7→14→30) and its
whole train→GCS→predict handoff moves models as h5 blobs (cardata-v3.py:227,
:255-261).  This importer reads the Keras v2 HDF5 layout (`model_weights/
<layer>/<layer>/{kernel:0,bias:0}`) into flax param pytrees so

- parity tests can score with the *reference's own* weights, and
- users migrating from the reference can load their existing checkpoints.

Keras Dense kernels are [in, out] — the same layout flax uses — so the map
is name-order only, no transposes.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def _layer_names(f) -> List[str]:
    g = f["model_weights"]
    names = g.attrs.get("layer_names")
    if names is not None:
        return [n.decode() if isinstance(n, bytes) else n for n in names]
    return list(g.keys())


def read_keras_dense_stack(path: str) -> List[dict]:
    """Return [{'kernel': np[in,out], 'bias': np[out]}, ...] for each
    weighted layer, in model order."""
    import h5py

    out = []
    with h5py.File(path, "r") as f:
        g = f["model_weights"]
        for name in _layer_names(f):
            if name not in g:
                continue
            grp = g[name]
            # v2 layout nests once more under the layer name.
            inner = grp[name] if name in grp else grp
            found = {}
            def visit(key, obj):
                import h5py as _h
                if isinstance(obj, _h.Dataset):
                    leaf = key.split("/")[-1].split(":")[0]
                    found[leaf] = np.asarray(obj)
            inner.visititems(visit)
            if "kernel" in found:
                out.append({"kernel": found["kernel"],
                            "bias": found.get("bias")})
    return out


def autoencoder_params_from_h5(path: str, expect_dims: Optional[tuple] = None) -> dict:
    """Map a reference autoencoder h5 onto `DenseAutoencoder` params.

    The reference model is 4 Dense layers; our module names them
    encoder0/encoder1/decoder0/decoder1 in the same order.
    """
    stack = read_keras_dense_stack(path)
    if len(stack) != 4:
        raise ValueError(f"expected 4 Dense layers, found {len(stack)} in {path}")
    names = ["encoder0", "encoder1", "decoder0", "decoder1"]
    params = {}
    for name, layer in zip(names, stack):
        params[name] = {"kernel": layer["kernel"].astype(np.float32),
                        "bias": layer["bias"].astype(np.float32)}
    if expect_dims:
        k0 = params["encoder0"]["kernel"]
        if (k0.shape[0], k0.shape[1]) != tuple(expect_dims[:2]):
            raise ValueError(f"dims mismatch: {k0.shape} vs {expect_dims}")
    return params
