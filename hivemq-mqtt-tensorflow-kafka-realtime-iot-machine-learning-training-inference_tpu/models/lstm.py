"""LSTM sequence-to-sequence next-step predictor (flax.linen).

Parity with the reference supervised model (LSTM cardata-v1.py:170-176):

    LSTM(32, relu, return_sequences) → LSTM(16, relu, last-step)
    → RepeatVector(look_back) → LSTM(16, relu, seq) → LSTM(32, relu, seq)
    → TimeDistributed(Dense(features))

Keras `LSTM(activation='relu')` swaps the cell's candidate/output tanh for
relu; flax's `nn.OptimizedLSTMCell(activation_fn=...)` maps 1:1.  The
reference trains it at batch=1, look_back=1 — pathological for any
accelerator — so the TPU design keeps semantic parity (same architecture,
same next-step objective) while batching windows [B, T, F] produced by the
host-side windower (`data.SensorBatches(window=T)`), and `lax`-scanned cells
keep the step compilable at any T.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LSTMSeq2Seq(nn.Module):
    features: int = 18
    look_back: int = 1
    enc_units: tuple = (32, 16)
    dec_units: tuple = (16, 32)

    def _rnn(self, units, name):
        return nn.RNN(nn.OptimizedLSTMCell(units, activation_fn=nn.relu),
                      name=name)

    @nn.compact
    def __call__(self, x):
        """x: [B, T, F] → [B, look_back, F] next-step prediction."""
        h = x
        for i, u in enumerate(self.enc_units):
            h = self._rnn(u, f"enc{i}")(h)
        code = h[:, -1, :]  # Keras return_sequences=False → last step
        h = jnp.repeat(code[:, None, :], self.look_back, axis=1)  # RepeatVector
        for i, u in enumerate(self.dec_units):
            h = self._rnn(u, f"dec{i}")(h)
        # TimeDistributed(Dense(features)): one Dense applied per step.
        return nn.Dense(self.features, name="head",
                        kernel_init=nn.initializers.glorot_uniform())(h)
