"""MNIST smoke-test models (flax.linen).

The reference uses an MNIST-over-Kafka pair as the ingestion smoke test
(confluent-tensorflow-io-kafka.py:44-58) plus a no-Kafka control
(confluent-tensorflow-io-kafka-simplified.py:9-29) to isolate ingestion bugs
from model bugs.  Same two models here, for the same purpose against the
broker emulator.
"""

from __future__ import annotations

import flax.linen as nn


class MNISTClassifier(nn.Module):
    """Flatten → Dense(128, relu) → Dense(10) (softmax applied in loss)."""

    hidden: int = 128

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)) / 255.0
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(10)(x)  # logits


class MNISTBaseline(nn.Module):
    """Flatten → Dense(512, relu) → Dropout(0.2) → Dense(10) (control model)."""

    hidden: int = 512
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)) / 255.0
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(10)(x)
