"""Mixture-of-experts SensorFormer: sparse FFN capacity for fleet-scale data.

The reference has no MoE (SURVEY §2.7 marks expert parallelism absent); this
is the TPU-native growth path for heterogeneous fleets — experts specialize
per operating regime (highway / city / fault states) while FLOPs per token
stay constant.

TPU-first routing (GShard/Switch style, fully static shapes for XLA):
- top-1 gating with a fixed per-expert capacity C; overflow tokens fall
  through the residual (no dynamic shapes, no sorting networks);
- dispatch/combine are one-hot einsums — they compile to MXU matmuls, not
  scatters;
- expert FFNs are a single batched einsum over the leading expert axis;
- expert parallelism: with `ep_axis` set (inside shard_map), expert weights
  hold only the local slice and two `lax.all_to_all`s over ICI move token
  slots expert-major and back (`parallel/expert_parallel.py` builds the
  mesh plumbing).

Load-balance auxiliary loss follows Shazeer et al.: E * Σ_e f_e · p_e over
fraction-routed f and mean gate probability p.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from .transformer import MultiHeadAttention


class MoEFFN(nn.Module):
    """Top-1 routed expert FFN over tokens. Input/output [N, D] (tokens
    flattened); returns (out, aux_loss)."""

    d_model: int
    num_experts: int = 8
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    ep_axis: Optional[str] = None  # set when called inside shard_map

    @nn.compact
    def __call__(self, x):
        N, D = x.shape
        ep = jax.lax.psum(1, self.ep_axis) if self.ep_axis else 1
        E = self.num_experts
        if E % ep:
            raise ValueError(f"num_experts={E} not divisible by ep={ep}")
        H = self.d_model * self.mlp_ratio
        # capacity per expert over the *local* token block
        C = max(1, int(self.capacity_factor * N / E))

        logits = nn.Dense(E, use_bias=False, name="router")(x)  # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)                      # [N]
        gate = jnp.max(probs, axis=-1)                           # [N]
        onehot = jax.nn.one_hot(expert, E, dtype=x.dtype)        # [N, E]

        # position of each token within its expert's queue; drop overflow
        pos = jnp.cumsum(onehot, axis=0) * onehot - onehot       # [N, E]
        keep = (pos < C) * onehot
        dispatch = keep[..., None] * jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=x.dtype)
        combine = dispatch * gate[:, None, None]                 # [N, E, C]

        slots = jnp.einsum("nec,nd->ecd", dispatch, x)           # [E, C, d]

        # expert weights: local slice when expert-parallel
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E // ep, D, H))
        b1 = self.param("b1", nn.initializers.zeros, (E // ep, H))
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E // ep, H, D))
        b2 = self.param("b2", nn.initializers.zeros, (E // ep, D))

        if self.ep_axis and ep > 1:
            # [E, C, d] -> [E/ep, ep*C, d]: slots for my experts, all shards
            slots = jax.lax.all_to_all(slots, self.ep_axis, 0, 1, tiled=True)
        h = jnp.einsum("ecd,edh->ech", slots, w1) + b1[:, None, :]
        h = nn.gelu(h)
        h = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
        if self.ep_axis and ep > 1:
            h = jax.lax.all_to_all(h, self.ep_axis, 1, 0, tiled=True)

        out = jnp.einsum("nec,ecd->nd", combine, h)              # [N, D]

        # load-balance aux: fraction routed × mean prob, summed over experts
        frac = jnp.mean(onehot, axis=0)
        mean_p = jnp.mean(probs, axis=0)
        aux = E * jnp.sum(frac * mean_p)
        return out, aux


class MoEBlock(nn.Module):
    d_model: int
    num_heads: int
    num_experts: int = 8
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    attn_mode: str = "dense"
    ep_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        B, T, D = x.shape
        x = x + MultiHeadAttention(self.d_model, self.num_heads,
                                   self.attn_mode,
                                   name="attn")(nn.LayerNorm(name="ln1")(x))
        h = nn.LayerNorm(name="ln2")(x)
        out, aux = MoEFFN(self.d_model, self.num_experts, self.mlp_ratio,
                          self.capacity_factor, self.ep_axis,
                          name="moe")(h.reshape(B * T, D))
        return x + out.reshape(B, T, D), aux


class MoESensorFormer(nn.Module):
    """SensorFormer with MoE FFN blocks; returns (pred, aux_loss)."""

    features: int = 18
    d_model: int = 64
    num_heads: int = 4
    num_layers: int = 2
    num_experts: int = 8
    capacity_factor: float = 1.25
    max_len: int = 4096
    attn_mode: str = "dense"
    ep_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, positions: Optional[jnp.ndarray] = None):
        B, T, F = x.shape
        h = nn.Dense(self.d_model, name="embed")(x)
        pos = jnp.arange(T) if positions is None else positions
        h = h + nn.Embed(self.max_len, self.d_model, name="pos")(pos)
        aux_total = 0.0
        for i in range(self.num_layers):
            h, aux = MoEBlock(self.d_model, self.num_heads, self.num_experts,
                              capacity_factor=self.capacity_factor,
                              attn_mode=self.attn_mode, ep_axis=self.ep_axis,
                              name=f"block{i}")(h)
            aux_total = aux_total + aux
        h = nn.LayerNorm(name="ln_f")(h)
        return nn.Dense(self.features, name="head")(h), aux_total
