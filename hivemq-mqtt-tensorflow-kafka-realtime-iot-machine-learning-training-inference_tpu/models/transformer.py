"""SensorFormer: causal transformer over long per-car sensor histories.

The reference's sequence model is a batch-1, look_back-1 LSTM (SURVEY §2.5)
— semantically a next-step predictor.  SensorFormer is the TPU-native
generalization: the same next-step objective (predict sensor vector t+1
from 1..t) over *long* windows, so one model sees hours of per-car context.
Anomaly score = next-step prediction error, the sequence analogue of the
autoencoder's reconstruction error.

TPU mapping: pre-norm blocks, MXU-friendly dims (d_model multiple of 128
recommended at scale; small defaults for the 18-sensor demo), attention
dispatched by mode:
  'dense'  – jnp reference (CPU/tests)
  'flash'  – Pallas kernel (`ops.attention.flash_attention`), single chip
  'ring'   – sequence-parallel ring attention (`parallel.ring_attention`),
             call inside shard_map with T sharded over the mesh 'seq' axis
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import attention_reference, flash_attention


class MultiHeadAttention(nn.Module):
    d_model: int
    num_heads: int
    attn_mode: str = "dense"  # dense | flash | flash_interpret | ring
    ring_axis: str = "seq"

    @nn.compact
    def __call__(self, x):
        B, T, _ = x.shape
        H = self.num_heads
        D = self.d_model // H
        qkv = nn.DenseGeneral((3, H, D), name="qkv")(x)  # [B,T,3,H,D]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.attn_mode == "dense":
            o = attention_reference(q, k, v, causal=True)
        elif self.attn_mode == "flash":
            o = flash_attention(q, k, v, causal=True)
        elif self.attn_mode == "flash_interpret":
            o = flash_attention(q, k, v, causal=True, interpret=True)
        elif self.attn_mode == "ring":
            from ..parallel.ring_attention import ring_attention

            o = ring_attention(q, k, v, axis_name=self.ring_axis, causal=True)
        else:
            raise ValueError(f"unknown attn_mode {self.attn_mode}")
        return nn.DenseGeneral(self.d_model, axis=(-2, -1), name="out")(o)


class Block(nn.Module):
    d_model: int
    num_heads: int
    mlp_ratio: int = 4
    attn_mode: str = "dense"
    ring_axis: str = "seq"

    @nn.compact
    def __call__(self, x):
        x = x + MultiHeadAttention(self.d_model, self.num_heads,
                                   self.attn_mode, self.ring_axis,
                                   name="attn")(nn.LayerNorm(name="ln1")(x))
        h = nn.LayerNorm(name="ln2")(x)
        h = nn.Dense(self.d_model * self.mlp_ratio, name="mlp_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, name="mlp_out")(h)
        return x + h


class SensorFormer(nn.Module):
    """Next-step sensor prediction over [B, T, features]."""

    features: int = 18
    d_model: int = 64
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 4096
    attn_mode: str = "dense"
    ring_axis: str = "seq"

    @nn.compact
    def __call__(self, x, positions: Optional[jnp.ndarray] = None):
        B, T, F = x.shape
        h = nn.Dense(self.d_model, name="embed")(x)
        if positions is None and T > self.max_len:
            raise ValueError(
                f"sequence length {T} exceeds max_len={self.max_len}; "
                f"under jit the Embed gather would silently clamp")
        pos = jnp.arange(T) if positions is None else positions
        pe = nn.Embed(self.max_len, self.d_model, name="pos")(pos)
        h = h + pe  # broadcasts over batch for [T]- or [B,T]-shaped positions
        for i in range(self.num_layers):
            h = Block(self.d_model, self.num_heads, attn_mode=self.attn_mode,
                      ring_axis=self.ring_axis, name=f"block{i}")(h)
        h = nn.LayerNorm(name="ln_f")(h)
        return nn.Dense(self.features, name="head")(h)

    @staticmethod
    def anomaly_scores(pred, x):
        """Per-step next-step prediction error: pred[t] estimates x[t+1]."""
        err = jnp.mean(jnp.square(pred[:, :-1] - x[:, 1:]), axis=-1)
        return err  # [B, T-1]
