"""MQTT ingestion layer: broker core, wire protocol, Kafka bridge,
device-simulator scenarios (reference L1/L2 — SURVEY §1)."""

from .broker import MqttBroker, QueueClient  # noqa: F401
from .bridge import KafkaBridge, TopicMapping  # noqa: F401
from .scenario import (EVALUATION_SCENARIO, Scenario, ScenarioRunner,  # noqa: F401
                       parse_scenario)
from .topic_tree import TopicTree, topic_matches  # noqa: F401
from .wire import MqttClient, MqttProtocol, MqttServer  # noqa: F401
from .eventserver import MqttEventServer  # noqa: F401
