"""MQTT→stream bridge — the HiveMQ Kafka-extension equivalent.

The reference bridges MQTT into Kafka with a broker extension configured by
topic-mappings: every publish matching `vehicles/sensor/data/#` is produced
to Kafka topic `sensor-data` (reference
`infrastructure/hivemq/kafka-config.yaml:20-29`).  The record key is the
MQTT topic, which is what lets the downstream KSQL re-key and the MongoDB
sink HoistField the car id (reference
`infrastructure/kafka-connect/mongodb/mongodb-connector-configmap.yaml:14-16`).

`KafkaBridge` subscribes to the MQTT broker core with each mapping's filter
and produces the payload bytes unchanged into the framework's stream
broker, counting forwards under the reference's metric family name
(`kafka_extension_total_*`, charted by `hivemq.json`).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional

from ..obs import tracing
from ..obs.metrics import default_registry
from ..stream.broker import Broker
from .broker import MqttBroker


@dataclasses.dataclass(frozen=True)
class TopicMapping:
    """One <topic-mapping>: MQTT filter(s) → stream topic.

    ``stream_key`` picks the produced record's key: ``"topic"`` is the
    reference extension's shape (full MQTT topic — downstream KSQL
    re-keys); ``"car"`` keys by the topic's LAST segment (the car id on
    ``vehicles/sensor/data/{car}``), which is what lets a federated
    MQTT front (ISSUE 20) produce straight into the keyed sensor stream
    the twin shards consume — same car, same partition, no re-key hop."""

    mqtt_topic_filters: tuple
    stream_topic: str
    id: str = ""
    stream_key: str = "topic"

    def __post_init__(self):
        if self.stream_key not in ("topic", "car"):
            raise ValueError(f"stream_key must be 'topic' or 'car', "
                             f"got {self.stream_key!r}")

    @classmethod
    def sensor_data(cls) -> "TopicMapping":
        """The reference's single production mapping."""
        return cls(("vehicles/sensor/data/#",), "sensor-data",
                   id="sensor-data")

    @classmethod
    def sensor_data_keyed(cls, stream_topic: str = "SENSOR_DATA_S_AVRO"
                          ) -> "TopicMapping":
        """The federated-front mapping: framed-Avro payloads land on the
        twin shards' source topic keyed by car id."""
        return cls(("vehicles/sensor/data/#",), stream_topic,
                   id="sensor-data-keyed", stream_key="car")


class KafkaBridge:
    """Forward matching MQTT publishes into stream-broker topics."""

    def __init__(self, mqtt: MqttBroker, stream: Broker,
                 mappings: Optional[List[TopicMapping]] = None,
                 partitions: int = 10):
        self.mqtt = mqtt
        self.stream = stream
        self.mappings = mappings or [TopicMapping.sensor_data()]
        self._m_fwd = default_registry.counter(
            "kafka_extension_total_forwarded",
            "MQTT publishes bridged into the stream broker (reference "
            "family kafka_extension_*)")
        # bridge lag: time from MQTT delivery to the stream-broker append.
        # The reference charts its extension's write latency/rate in
        # hivemq.json; here the forward is synchronous, so this histogram
        # IS the end-to-end extension lag an operator watches
        self._m_lag = default_registry.histogram(
            "kafka_extension_forward_seconds",
            "MQTT→stream bridge forward latency per message")
        # the registry counter is process-global (shared across bridges for
        # scrape purposes); per-instance accounting needs its own counter
        self._n_fwd = 0
        self._n_lock = threading.Lock()
        for i, m in enumerate(self.mappings):
            # the reference provisions sensor-data with 10 partitions
            stream.create_topic(m.stream_topic, partitions=partitions)
            cid = f"__bridge__{m.id or i}"
            dest = m.stream_topic
            car_key = m.stream_key == "car"

            def deliver(topic, payload, qos, retain, _dest=dest,
                        _car_key=car_key):
                # the publisher-thread trace context (fan-out latency so
                # far = mqtt_deliver) becomes a stream-record header; the
                # MQTT payload and the produced value stay byte-identical.
                # BOTH marks happen BEFORE produce(): the append hands the
                # live context to consumer threads, and a mark after the
                # handoff would race theirs (mark is owner-serial by
                # contract).  The produce latency itself is the
                # kafka_extension_forward_seconds histogram below; on the
                # trace it rides the downstream stage's span as queue time.
                hdrs = None
                ctx = tracing.current() if tracing.ENABLED else None
                if ctx is not None:
                    ctx.mark("mqtt_deliver")
                    ctx.mark("bridge_produce")
                    hdrs = tracing.headers_for(ctx)
                t0 = time.perf_counter()
                key = (topic.rsplit("/", 1)[-1] if _car_key
                       else topic).encode()
                self.stream.produce(_dest, payload, key=key,
                                    timestamp_ms=int(time.time() * 1000),  # wallclock-ok: record timestamp, not a timeout
                                    headers=hdrs)
                self._m_lag.observe(time.perf_counter() - t0)
                self._m_fwd.inc()
                with self._n_lock:
                    self._n_fwd += 1

            sess = mqtt.connect(cid, deliver, clean_start=True)
            mqtt.deliver_pending(sess)  # in-process consumer: ready at once
            for f in m.mqtt_topic_filters:
                mqtt.subscribe(cid, f)

    def forwarded(self) -> int:
        with self._n_lock:
            return self._n_fwd
