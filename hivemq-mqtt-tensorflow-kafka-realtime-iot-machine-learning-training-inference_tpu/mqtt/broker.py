"""In-process MQTT broker core — the HiveMQ-cluster equivalent.

The reference fronts its pipeline with a 5-node HiveMQ cluster (reference
`infrastructure/hivemq/hivemq-crd.yaml:10-26`): MQTT sessions, wildcard and
shared subscriptions, QoS 0/1, and extension hooks (the Kafka extension
registers for a topic filter and forwards publishes).  This core implements
those broker semantics in-process; `iotml.mqtt.wire` puts a real TCP/MQTT
protocol front on it, and `iotml.mqtt.bridge.KafkaBridge` is the extension
equivalent.  Metrics use the same family names the reference's Grafana
dashboards chart (`com_hivemq_messages_*`, SURVEY §5).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..obs.metrics import default_registry
from .topic_tree import TopicTree, validate_filter

# callback(topic, payload, qos, retain) — delivery to one session
DeliveryFn = Callable[[str, bytes, int, bool], None]


class Session:
    __slots__ = ("client_id", "deliver", "clean_start", "connected_at")

    def __init__(self, client_id: str, deliver: DeliveryFn,
                 clean_start: bool = True):
        self.client_id = client_id
        self.deliver = deliver
        self.clean_start = clean_start
        self.connected_at = time.time()


class MqttBroker:
    """Session + subscription + retained-message state with synchronous
    fan-out delivery.  Thread-safe; delivery callbacks run on the
    publisher's thread (the wire server hands each connection its own
    writer lock, so concurrent fan-out is safe)."""

    def __init__(self, name: str = "iotml-mqtt"):
        self.name = name
        self._sessions: Dict[str, Session] = {}
        self._tree = TopicTree()
        self._retained: Dict[str, Tuple[bytes, int]] = {}
        self._lock = threading.Lock()
        reg = default_registry
        self._m_in = reg.counter(
            "mqtt_messages_incoming_publish_count",
            "PUBLISH packets received (reference family "
            "com_hivemq_messages_incoming_publish_count)")
        self._m_out = reg.counter(
            "mqtt_messages_outgoing_publish_count",
            "PUBLISH packets delivered to subscribers")
        self._m_dropped = reg.counter(
            "mqtt_messages_dropped_count",
            "publishes that matched no subscription")
        self._g_sessions = reg.gauge(
            "mqtt_sessions_overall_current", "live MQTT sessions")

    # ---------------------------------------------------------- sessions
    def connect(self, client_id: str, deliver: DeliveryFn,
                clean_start: bool = True) -> Session:
        """Register a session.  A reconnect with the same client id takes
        over (the old delivery path is dropped — MQTT session takeover)."""
        with self._lock:
            if clean_start:
                self._tree.unsubscribe_all(client_id)
            s = Session(client_id, deliver, clean_start)
            self._sessions[client_id] = s
            self._g_sessions.set(len(self._sessions))
            return s

    def disconnect(self, client_id: str,
                   session: Optional[Session] = None) -> None:
        """End a session.  Pass the Session returned by connect() so a
        stale connection's teardown cannot destroy a session that was
        taken over by a newer connection with the same client id."""
        with self._lock:
            cur = self._sessions.get(client_id)
            if cur is None or (session is not None and cur is not session):
                return
            del self._sessions[client_id]
            if cur.clean_start:
                self._tree.unsubscribe_all(client_id)
            self._g_sessions.set(len(self._sessions))

    def session_count(self) -> int:
        return len(self._sessions)

    # ----------------------------------------------------- subscriptions
    def subscribe(self, client_id: str, filter_: str, qos: int = 0) -> int:
        """Returns granted qos (0/1 supported; 2 downgraded to 1 — the
        reference caps at maxQos 2 but its pipeline only uses 0/1)."""
        validate_filter(filter_)
        granted = min(qos, 1)
        self._tree.subscribe(client_id, filter_, granted)
        # retained delivery on subscribe (spec §3.8.4)
        from .topic_tree import split_share, topic_matches
        group, real = split_share(filter_)
        if group is None:  # retained messages are not sent to shared subs
            sess = self._sessions.get(client_id)
            if sess is not None:
                for topic, (payload, rqos) in list(self._retained.items()):
                    if topic_matches(real, topic):
                        sess.deliver(topic, payload, min(granted, rqos), True)
        return granted

    def unsubscribe(self, client_id: str, filter_: str) -> bool:
        return self._tree.unsubscribe(client_id, filter_)

    # ------------------------------------------------------------ publish
    def publish(self, topic: str, payload: bytes, qos: int = 0,
                retain: bool = False) -> int:
        """Fan a publish out to every matching session; returns the number
        of deliveries."""
        if "+" in topic or "#" in topic:
            raise ValueError(f"wildcards not allowed in publish topic: {topic!r}")
        self._m_in.inc()
        if retain:
            if payload:
                self._retained[topic] = (payload, qos)
            else:
                self._retained.pop(topic, None)  # empty retained = clear
        receivers = self._tree.receivers(topic)
        delivered = 0
        for cid, granted in receivers:
            sess = self._sessions.get(cid)
            if sess is None:
                continue
            sess.deliver(topic, payload, min(qos, granted), False)
            delivered += 1
        if delivered:
            self._m_out.inc(delivered)
        else:
            self._m_dropped.inc()
        return delivered

    def retained(self) -> Dict[str, bytes]:
        return {t: p for t, (p, _q) in self._retained.items()}


class QueueClient:
    """In-process client: collects deliveries into a list (tests, sinks)."""

    def __init__(self, broker: MqttBroker, client_id: str,
                 clean_start: bool = True):
        self.broker = broker
        self.client_id = client_id
        self.messages: List[Tuple[str, bytes, int, bool]] = []
        self._lock = threading.Lock()
        self._session = broker.connect(client_id, self._deliver, clean_start)

    def _deliver(self, topic: str, payload: bytes, qos: int, retain: bool):
        with self._lock:
            self.messages.append((topic, payload, qos, retain))

    def subscribe(self, filter_: str, qos: int = 0) -> int:
        return self.broker.subscribe(self.client_id, filter_, qos)

    def publish(self, topic: str, payload: bytes, qos: int = 0,
                retain: bool = False) -> int:
        return self.broker.publish(topic, payload, qos, retain)

    def disconnect(self):
        self.broker.disconnect(self.client_id, self._session)
