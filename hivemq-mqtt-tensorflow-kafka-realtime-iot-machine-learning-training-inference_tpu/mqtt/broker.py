"""In-process MQTT broker core — the HiveMQ-cluster equivalent.

The reference fronts its pipeline with a 5-node HiveMQ cluster (reference
`infrastructure/hivemq/hivemq-crd.yaml:10-26`): MQTT sessions, wildcard and
shared subscriptions, QoS 0/1/2, and extension hooks (the Kafka extension
registers for a topic filter and forwards publishes).  This core implements
those broker semantics in-process; `iotml.mqtt.wire` puts a real TCP/MQTT
protocol front on it, and `iotml.mqtt.bridge.KafkaBridge` is the extension
equivalent.  Metrics use the same family names the reference's Grafana
dashboards chart (`com_hivemq_messages_*`, SURVEY §5).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..obs.metrics import default_registry
from .topic_tree import TopicTree, validate_filter

# callback(topic, payload, qos, retain) — delivery to one session
DeliveryFn = Callable[[str, bytes, int, bool], None]


class Session:
    __slots__ = ("client_id", "deliver", "clean_start", "connected_at",
                 "pending", "resumed", "qos2_inbound")

    def __init__(self, client_id: str, deliver: DeliveryFn,
                 clean_start: bool = True):
        self.client_id = client_id
        self.deliver = deliver
        self.clean_start = clean_start
        self.connected_at = time.time()
        # (topic, payload, qos, retain) queued while this persistent
        # session was offline, held until the transport is ready (CONNACK
        # sent); live publishes append here until drained so ordering is
        # preserved
        self.pending: Optional[List[Tuple[str, bytes, int, bool]]] = None
        # True when server-side state (subscriptions/backlog) carried over —
        # what CONNACK's session-present flag must report
        self.resumed: bool = False
        # QoS 2 exactly-once receiver state: packet ids of inbound PUBLISHes
        # whose payload was already forwarded but whose PUBREL has not yet
        # arrived.  A retried PUBLISH with one of these ids is a duplicate
        # and must NOT be forwarded again (spec §4.3.3).  Carried across
        # reconnects for persistent sessions — the dedup guarantee is the
        # whole point of the handshake surviving a dropped connection.
        self.qos2_inbound: set = set()


class MqttBroker:
    """Session + subscription + retained-message state with synchronous
    fan-out delivery.  Thread-safe: routing decisions and queue mutations
    happen under the broker lock, but delivery callbacks run on the
    publisher's thread AFTER the lock is released — a stalled subscriber
    socket can slow its publisher, never the whole broker.  Ordering is
    per-publisher (as before); the wire server hands each connection its
    own writer lock, so concurrent fan-out to one socket is safe."""

    def __init__(self, name: str = "iotml-mqtt",
                 offline_queue_limit: int = 1000,
                 offline_session_expiry_s: float = 3600.0):
        self.name = name
        self._sessions: Dict[str, Session] = {}
        self._tree = TopicTree()
        self._retained: Dict[str, Tuple[bytes, int]] = {}
        # disconnected persistent sessions: cid → (queue, expires_at,
        # qos2_inbound).
        # QoS≥1 deliveries queue (oldest dropped past the limit, HiveMQ's
        # offline buffering); a session that never reconnects expires after
        # offline_session_expiry_s (HiveMQ's session expiry) so rotating
        # client ids cannot grow state without bound.
        self._offline: Dict[str, Tuple[deque, float, set]] = {}
        self.offline_queue_limit = offline_queue_limit
        self.offline_session_expiry_s = offline_session_expiry_s
        self._next_offline_sweep = 0.0
        # RLock: delivery callbacks may legally re-enter (a subscriber that
        # publishes from its handler, e.g. a bridge)
        self._lock = threading.RLock()
        reg = default_registry
        self._m_in = reg.counter(
            "mqtt_messages_incoming_publish_count",
            "PUBLISH packets received (reference family "
            "com_hivemq_messages_incoming_publish_count)")
        self._m_out = reg.counter(
            "mqtt_messages_outgoing_publish_count",
            "PUBLISH packets delivered to subscribers")
        self._m_dropped = reg.counter(
            "mqtt_messages_dropped_count",
            "publishes that matched no subscription")
        self._m_queued = reg.counter(
            "mqtt_messages_queued_count",
            "QoS>=1 publishes buffered for offline persistent sessions")
        self._g_sessions = reg.gauge(
            "mqtt_sessions_overall_current", "live MQTT sessions")

    # ---------------------------------------------------------- sessions
    def connect(self, client_id: str, deliver: DeliveryFn,
                clean_start: bool = True) -> Session:
        """Register a session.  A reconnect with the same client id takes
        over (the old delivery path is dropped — MQTT session takeover).

        A persistent session (clean_start=False) that reconnects has the
        QoS≥1 messages queued while it was offline staged on
        `session.pending`; the transport calls `deliver_pending(session)`
        once it is ready (AFTER sending CONNACK — a PUBLISH before CONNACK
        breaks the handshake).  Until that drain, live publishes for the
        session append behind the queued ones, preserving order."""
        with self._lock:
            self._expire_offline()
            pending: List[Tuple[str, bytes, int, bool]] = []
            qos2_inbound: set = set()
            old = self._sessions.get(client_id)
            if old is not None:
                if old.pending:
                    # session takeover mid-handshake: the superseded
                    # connection must not drain the backlog to its (likely
                    # dead) socket — the new session inherits it
                    pending = old.pending
                    old.pending = []
                qos2_inbound = old.qos2_inbound
            resumed = False
            if clean_start:
                self._tree.unsubscribe_all(client_id)
                self._offline.pop(client_id, None)
                pending = []
                qos2_inbound = set()
            else:
                entry = self._offline.pop(client_id, None)
                if entry is not None:
                    pending = list(entry[0]) + pending
                    qos2_inbound |= entry[2]
                # session-present: any server-side state carried over
                resumed = (entry is not None or old is not None
                           or bool(self._tree.filters_of(client_id)))
            s = Session(client_id, deliver, clean_start)
            s.resumed = resumed
            s.qos2_inbound = qos2_inbound
            # deliveries are held on `pending` until the transport declares
            # ready via deliver_pending() — this covers both the offline
            # backlog AND live publishes racing the CONNECT handshake (a
            # PUBLISH before CONNACK is a protocol violation)
            s.pending = pending
            self._sessions[client_id] = s
            self._g_sessions.set(len(self._sessions))
            return s

    def deliver_pending(self, session: Session) -> int:
        """Drain a freshly-connected session's queued messages and switch
        it to live delivery.  Call after the transport is ready (CONNACK on
        the wire path; immediately for in-process clients).

        Chunked: queue entries are COPIED under the lock, delivered outside
        it (a slow socket must not wedge the broker), and only removed from
        the backlog after delivery — so a takeover mid-chunk inherits the
        in-flight messages (possible duplicates, never loss: QoS 1's
        at-least-once).  Publishes arriving mid-drain append behind the
        backlog, preserving order."""
        n = 0
        while True:
            with self._lock:
                if self._sessions.get(session.client_id) is not session:
                    return n  # superseded: the new session owns the backlog
                chunk = list(session.pending or [])
                if not chunk:
                    session.pending = None  # live from here on
                    return n
            for topic, payload, qos, retain in chunk:
                session.deliver(topic, payload, qos, retain)
                self._m_out.inc()
                n += 1
            with self._lock:
                if self._sessions.get(session.client_id) is not session:
                    return n  # delivered chunk may be redelivered by heir
                # drop the delivered messages BY IDENTITY: a concurrent
                # overflow drop-oldest may already have removed a prefix of
                # the chunk, and positional deletion would then take an
                # undelivered mid-drain arrival with it (silent loss)
                ci = 0
                while session.pending and ci < len(chunk):
                    if session.pending[0] is chunk[ci]:
                        session.pending.pop(0)
                    ci += 1

    def disconnect(self, client_id: str,
                   session: Optional[Session] = None) -> None:
        """End a session.  Pass the Session returned by connect() so a
        stale connection's teardown cannot destroy a session that was
        taken over by a newer connection with the same client id."""
        with self._lock:
            self._expire_offline()
            cur = self._sessions.get(client_id)
            if cur is None or (session is not None and cur is not session):
                return
            del self._sessions[client_id]
            if cur.clean_start:
                self._tree.unsubscribe_all(client_id)
            else:
                # persistent session goes offline: queue QoS≥1 deliveries
                # until it reconnects (bounded, drop-oldest) or expires
                q = deque(cur.pending or (),
                          maxlen=self.offline_queue_limit)
                self._offline[client_id] = (
                    q, time.time() + self.offline_session_expiry_s,
                    cur.qos2_inbound)
            self._g_sessions.set(len(self._sessions))

    def _expire_offline(self) -> None:
        """Drop offline persistent sessions past their expiry (HiveMQ's
        session-expiry): queue AND subscriptions go. Caller holds _lock."""
        now = time.time()
        dead = [cid for cid, (_q, exp, _r) in self._offline.items()
                if exp < now]
        for cid in dead:
            del self._offline[cid]
            self._tree.unsubscribe_all(cid)

    def session_count(self) -> int:
        return len(self._sessions)

    def session_ids(self) -> List[str]:
        with self._lock:
            return list(self._sessions)

    # ------------------------------------------------------------- qos 2
    def qos2_begin(self, session: Session, packet_id: int) -> bool:
        """Exactly-once receiver step 1: returns True when this packet id
        is NEW for the session (caller must forward the publish), False
        when it is a retry of an unreleased id (caller must NOT forward —
        just re-acknowledge with PUBREC).  Spec §4.3.3 receiver flow."""
        with self._lock:
            if packet_id in session.qos2_inbound:
                return False
            session.qos2_inbound.add(packet_id)
            return True

    def qos2_release(self, session: Session, packet_id: int) -> None:
        """Exactly-once receiver step 2 (PUBREL): the sender has seen our
        PUBREC, so the id can never be retried — forget it."""
        with self._lock:
            session.qos2_inbound.discard(packet_id)

    # ----------------------------------------------------- subscriptions
    def subscribe(self, client_id: str, filter_: str, qos: int = 0) -> int:
        """Returns granted qos (0/1/2 — the reference broker advertises
        maxQos 2, hivemq-crd.yaml:13)."""
        validate_filter(filter_)
        granted = min(qos, 2)
        self._tree.subscribe(client_id, filter_, granted)
        # retained delivery on subscribe (spec §3.8.4) — through the same
        # gate as publish(): routing under the lock, a not-yet-ready
        # session's messages join its pending backlog (never a PUBLISH
        # before CONNACK / ahead of the queued backlog), sockets written
        # only after the lock is released
        from .topic_tree import split_share, topic_matches
        group, real = split_share(filter_)
        live: List[Tuple[str, bytes, int]] = []
        if group is None:  # retained messages are not sent to shared subs
            with self._lock:
                sess = self._sessions.get(client_id)
                if sess is not None:
                    for topic, (payload, rqos) in list(self._retained.items()):
                        if not topic_matches(real, topic):
                            continue
                        eff = min(granted, rqos)
                        # retain=True rides along: spec 3.3.1.3 requires the
                        # flag on messages sent due to a new subscription
                        if sess.pending is not None:
                            sess.pending.append((topic, payload, eff, True))
                        else:
                            live.append((topic, payload, eff))
            for topic, payload, eff in live:
                sess.deliver(topic, payload, eff, True)
        return granted

    def unsubscribe(self, client_id: str, filter_: str) -> bool:
        return self._tree.unsubscribe(client_id, filter_)

    # ------------------------------------------------------------ publish
    def publish(self, topic: str, payload: bytes, qos: int = 0,
                retain: bool = False) -> int:
        """Fan a publish out to every matching session; returns the number
        of deliveries."""
        if "+" in topic or "#" in topic:
            raise ValueError(f"wildcards not allowed in publish topic: {topic!r}")
        self._m_in.inc()
        delivered = queued = 0
        live: List[Tuple[Session, int]] = []
        with self._lock:  # routing + queue mutation atomic; delivery after
            now = time.time()
            if now >= self._next_offline_sweep:
                self._expire_offline()
                self._next_offline_sweep = now + 5.0
            if retain:
                if payload:
                    self._retained[topic] = (payload, qos)
                else:
                    self._retained.pop(topic, None)  # empty retained = clear
            def is_live(cid: str) -> bool:
                s = self._sessions.get(cid)
                return s is not None and s.pending is None

            for cid, granted in self._tree.receivers(topic, is_live=is_live):
                eff = min(qos, granted)
                sess = self._sessions.get(cid)
                if sess is None:
                    entry = self._offline.get(cid)
                    if entry is not None and eff >= 1:
                        entry[0].append((topic, payload, eff, False))
                        queued += 1
                    continue
                if sess.pending is not None:
                    # reconnect in progress: keep order behind the queued
                    # backlog instead of jumping ahead of it (same bound as
                    # the offline queue: drop-oldest)
                    sess.pending.append((topic, payload, eff, False))
                    if len(sess.pending) > self.offline_queue_limit:
                        del sess.pending[0]
                    else:
                        queued += 1
                    continue
                live.append((sess, eff))
        for sess, eff in live:  # outside the lock: a slow socket blocks
            sess.deliver(topic, payload, eff, False)  # only its publisher
            delivered += 1
        if delivered:
            self._m_out.inc(delivered)
        if queued:
            self._m_queued.inc(queued)
        if not delivered and not queued:
            self._m_dropped.inc()
        return delivered

    def retained(self) -> Dict[str, bytes]:
        return {t: p for t, (p, _q) in self._retained.items()}


class QueueClient:
    """In-process client: collects deliveries into a list (tests, sinks)."""

    def __init__(self, broker: MqttBroker, client_id: str,
                 clean_start: bool = True):
        self.broker = broker
        self.client_id = client_id
        self.messages: List[Tuple[str, bytes, int, bool]] = []
        self._lock = threading.Lock()
        self._session = broker.connect(client_id, self._deliver, clean_start)
        broker.deliver_pending(self._session)  # in-process: ready at once

    def _deliver(self, topic: str, payload: bytes, qos: int, retain: bool):
        with self._lock:
            self.messages.append((topic, payload, qos, retain))

    def subscribe(self, filter_: str, qos: int = 0) -> int:
        return self.broker.subscribe(self.client_id, filter_, qos)

    def publish(self, topic: str, payload: bytes, qos: int = 0,
                retain: bool = False) -> int:
        return self.broker.publish(topic, payload, qos, retain)

    def disconnect(self):
        self.broker.disconnect(self.client_id, self._session)
