"""In-process MQTT broker core — the HiveMQ-cluster equivalent.

The reference fronts its pipeline with a 5-node HiveMQ cluster (reference
`infrastructure/hivemq/hivemq-crd.yaml:10-26`): MQTT sessions, wildcard and
shared subscriptions, QoS 0/1/2, and extension hooks (the Kafka extension
registers for a topic filter and forwards publishes).  This core implements
those broker semantics in-process; `iotml.mqtt.wire` puts a real TCP/MQTT
protocol front on it, and `iotml.mqtt.bridge.KafkaBridge` is the extension
equivalent.  Metrics use the same family names the reference's Grafana
dashboards chart (`com_hivemq_messages_*`, SURVEY §5).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..chaos import faults as chaos
from ..obs import tracing
from ..obs.metrics import default_registry
from .topic_tree import TopicTree, validate_filter

# callback(topic, payload, qos, retain) — delivery to one session
DeliveryFn = Callable[[str, bytes, int, bool], None]


class Session:
    __slots__ = ("client_id", "deliver", "clean_start", "connected_at",
                 "pending", "resumed", "qos2_inbound", "will",
                 "will_delay_s")

    def __init__(self, client_id: str, deliver: DeliveryFn,
                 clean_start: bool = True):
        self.client_id = client_id
        self.deliver = deliver
        self.clean_start = clean_start
        self.connected_at = time.time()  # wallclock-ok: display stat (when the session connected), not a timeout
        # Last Will from CONNECT: (topic, payload, qos, retain) published on
        # abnormal disconnect (socket drop, keepalive timeout, protocol
        # violation, session takeover), DISCARDED on clean DISCONNECT.
        # will_delay_s is the v5 Will Delay Interval: a persistent session
        # that reconnects within the delay cancels the will.
        self.will: Optional[Tuple[str, bytes, int, bool]] = None
        self.will_delay_s: float = 0.0
        # (topic, payload, qos, retain) queued while this persistent
        # session was offline, held until the transport is ready (CONNACK
        # sent); live publishes append here until drained so ordering is
        # preserved
        self.pending: Optional[List[Tuple[str, bytes, int, bool]]] = None
        # True when server-side state (subscriptions/backlog) carried over —
        # what CONNACK's session-present flag must report
        self.resumed: bool = False
        # QoS 2 exactly-once receiver state: packet ids of inbound PUBLISHes
        # whose payload was already forwarded but whose PUBREL has not yet
        # arrived.  A retried PUBLISH with one of these ids is a duplicate
        # and must NOT be forwarded again (spec §4.3.3).  Carried across
        # reconnects for persistent sessions — the dedup guarantee is the
        # whole point of the handshake surviving a dropped connection.
        self.qos2_inbound: set = set()


class MqttBroker:
    """Session + subscription + retained-message state with synchronous
    fan-out delivery.  Thread-safe: routing decisions and queue mutations
    happen under the broker lock, but delivery callbacks run on the
    publisher's thread AFTER the lock is released — a stalled subscriber
    socket can slow its publisher, never the whole broker.  Ordering is
    per-publisher (as before); the wire server hands each connection its
    own writer lock, so concurrent fan-out to one socket is safe."""

    def __init__(self, name: str = "iotml-mqtt",
                 offline_queue_limit: int = 1000,
                 offline_session_expiry_s: float = 3600.0,
                 backpressure_hwm: Optional[int] = None):
        self.name = name
        self._sessions: Dict[str, Session] = {}
        self._tree = TopicTree()
        self._retained: Dict[str, Tuple[bytes, int]] = {}
        # disconnected persistent sessions: cid → [queue, expires_at,
        # qos2_inbound, delayed_will] where delayed_will is None or
        # ((topic, payload, qos, retain), due_time) — a v5 will-delay will
        # pending publication unless the session reconnects first.
        # QoS≥1 deliveries queue (oldest dropped past the limit, HiveMQ's
        # offline buffering); a session that never reconnects expires after
        # offline_session_expiry_s (HiveMQ's session expiry) so rotating
        # client ids cannot grow state without bound.
        self._offline: Dict[str, list] = {}
        self.offline_queue_limit = offline_queue_limit
        self.offline_session_expiry_s = offline_session_expiry_s
        self._next_offline_sweep = 0.0
        # Backpressure: once any receiver queue (a reconnecting
        # session's pending backlog or an offline session's queue)
        # crosses the high-water mark, the broker raises a "slow down"
        # signal cooperative publishers poll via `saturated()` —
        # deferral at the SOURCE instead of drop-oldest at the limit.
        # A publish that lands on a saturated queue counts into
        # iotml_mqtt_backpressure_total either way, so non-cooperative
        # fleets still show up on the dashboard before data is lost.
        self.backpressure_hwm = backpressure_hwm if backpressure_hwm \
            is not None else max(1, (offline_queue_limit * 4) // 5)
        self._bp_sessions: set = set()
        # ONE consolidated timer for all pending delayed wills, armed for
        # the earliest due time (a timer thread per will would mean
        # thousands of stacks during a fleet-scale disconnect wave —
        # exactly the silent-fleet event wills exist for)
        self._will_timer: Optional[threading.Timer] = None
        self._will_timer_due = float("inf")
        # RLock: delivery callbacks may legally re-enter (a subscriber that
        # publishes from its handler, e.g. a bridge)
        self._lock = threading.RLock()
        reg = default_registry
        self._m_in = reg.counter(
            "mqtt_messages_incoming_publish_count",
            "PUBLISH packets received (reference family "
            "com_hivemq_messages_incoming_publish_count)")
        self._m_out = reg.counter(
            "mqtt_messages_outgoing_publish_count",
            "PUBLISH packets delivered to subscribers")
        self._m_dropped = reg.counter(
            "mqtt_messages_dropped_count",
            "publishes that matched no subscription")
        self._m_queued = reg.counter(
            "mqtt_messages_queued_count",
            "QoS>=1 publishes buffered for offline persistent sessions")
        self._g_sessions = reg.gauge(
            "mqtt_sessions_overall_current", "live MQTT sessions")
        self._m_backpressure = reg.counter(
            "iotml_mqtt_backpressure_total",
            "publishes that landed on a receiver queue at/over the "
            "backpressure high-water mark (cooperative publishers "
            "poll saturated() and defer instead)")

    # ------------------------------------------------------ backpressure
    def _note_queue_depth(self, cid: str, depth: int,
                          count: bool = True) -> None:
        """Track a receiver queue against the high-water mark (caller
        holds _lock).  Crossing raises the saturation signal; draining
        below it clears the signal.  ``count`` marks publish-path
        updates (the ones the backpressure counter measures) —
        connect/disconnect bookkeeping only moves the signal."""
        if depth >= self.backpressure_hwm:
            self._bp_sessions.add(cid)
            if count:
                self._m_backpressure.inc()
        else:
            self._bp_sessions.discard(cid)

    def saturated(self) -> bool:
        """The bounded-queue "slow down" signal: True while any
        receiver queue sits at/over the backpressure high-water mark.
        Cooperative publishers (`iotml.gen.scenarios` fleet agents, the
        rush-hour burst drill) poll this between publishes and defer
        into their own bounded buffer instead of pushing the broker's
        queues into drop-oldest — deferral at the source is recoverable,
        a dropped-oldest message is not."""
        return bool(self._bp_sessions)

    # ---------------------------------------------------------- sessions
    def connect(self, client_id: str, deliver: DeliveryFn,
                clean_start: bool = True,
                will: Optional[Tuple[str, bytes, int, bool]] = None,
                will_delay_s: float = 0.0) -> Session:
        """Register a session.  A reconnect with the same client id takes
        over (the old delivery path is dropped — MQTT session takeover).

        A persistent session (clean_start=False) that reconnects has the
        QoS≥1 messages queued while it was offline staged on
        `session.pending`; the transport calls `deliver_pending(session)`
        once it is ready (AFTER sending CONNACK — a PUBLISH before CONNACK
        breaks the handshake).  Until that drain, live publishes for the
        session append behind the queued ones, preserving order.

        `will`/`will_delay_s` register the connection's Last Will
        (published on abnormal disconnect — see `disconnect`).  Takeover
        counts as abnormal FOR THE OLD CONNECTION: its will is published
        here, unless a will delay applies (the new connection to the same
        session cancels a delayed will, MQTT 5 §3.1.3.2.2)."""
        takeover_will = None
        with self._lock:
            due_wills = self._expire_offline()
            pending: List[Tuple[str, bytes, int, bool]] = []
            qos2_inbound: set = set()
            old = self._sessions.get(client_id)
            if old is not None:
                if old.pending:
                    # session takeover mid-handshake: the superseded
                    # connection must not drain the backlog to its (likely
                    # dead) socket — the new session inherits it
                    pending = old.pending
                    old.pending = []
                qos2_inbound = old.qos2_inbound
                if old.will is not None and (old.will_delay_s <= 0
                                             or clean_start):
                    # a delayed will survives a non-clean takeover (the new
                    # connection resumes the session and cancels it,
                    # §3.1.3.2.2) — but a clean-start connect ENDS the old
                    # session, and §3.1.2.5 publishes the will at the
                    # earlier of delay expiry and session end
                    takeover_will = old.will
                # either way the old connection's will is settled now —
                # its late teardown must not publish it again
                old.will = None
            resumed = False
            entry = self._offline.pop(client_id, None)
            if clean_start:
                self._tree.unsubscribe_all(client_id)
                if entry is not None and entry[3] is not None:
                    # the offline session carried a pending delayed will;
                    # clean-start ends that session rather than resuming
                    # it, so the will fires NOW (§3.1.2.5: earlier of
                    # delay expiry and session end) — a crashed device
                    # re-provisioned clean must still report as dead
                    due_wills.append(entry[3][0])
                pending = []
                qos2_inbound = set()
            else:
                if entry is not None:
                    # reconnect before the will delay fired: cancel it
                    pending = list(entry[0]) + pending
                    qos2_inbound |= entry[2]
                # session-present: any server-side state carried over
                resumed = (entry is not None or old is not None
                           or bool(self._tree.filters_of(client_id)))
            s = Session(client_id, deliver, clean_start)
            s.resumed = resumed
            s.qos2_inbound = qos2_inbound
            s.will = will
            s.will_delay_s = will_delay_s
            # deliveries are held on `pending` until the transport declares
            # ready via deliver_pending() — this covers both the offline
            # backlog AND live publishes racing the CONNECT handshake (a
            # PUBLISH before CONNACK is a protocol violation)
            s.pending = pending
            self._note_queue_depth(client_id, len(pending), count=False)
            self._sessions[client_id] = s
            self._g_sessions.set(len(self._sessions))
        # outside the lock: will fan-out must not stall the broker
        for w in due_wills:
            self.publish(*w)
        if takeover_will is not None:
            self.publish(*takeover_will)
        return s

    def deliver_pending(self, session: Session) -> int:
        """Drain a freshly-connected session's queued messages and switch
        it to live delivery.  Call after the transport is ready (CONNACK on
        the wire path; immediately for in-process clients).

        Chunked: queue entries are COPIED under the lock, delivered outside
        it (a slow socket must not wedge the broker), and only removed from
        the backlog after delivery — so a takeover mid-chunk inherits the
        in-flight messages (possible duplicates, never loss: QoS 1's
        at-least-once).  Publishes arriving mid-drain append behind the
        backlog, preserving order."""
        n = 0
        while True:
            with self._lock:
                if self._sessions.get(session.client_id) is not session:
                    return n  # superseded: the new session owns the backlog
                chunk = list(session.pending or [])
                if not chunk:
                    session.pending = None  # live from here on
                    self._bp_sessions.discard(session.client_id)
                    return n
            for topic, payload, qos, retain in chunk:
                session.deliver(topic, payload, qos, retain)
                self._m_out.inc()
                n += 1
            with self._lock:
                if self._sessions.get(session.client_id) is not session:
                    return n  # delivered chunk may be redelivered by heir
                # drop the delivered messages BY IDENTITY: a concurrent
                # overflow drop-oldest may already have removed a prefix of
                # the chunk, and positional deletion would then take an
                # undelivered mid-drain arrival with it (silent loss)
                ci = 0
                while session.pending and ci < len(chunk):
                    if session.pending[0] is chunk[ci]:
                        session.pending.pop(0)
                    ci += 1
                # draining below the high-water mark releases the
                # backpressure signal (deferred publishers resume)
                self._note_queue_depth(session.client_id,
                                       len(session.pending or ()),
                                       count=False)

    def discard_will(self, session: Session) -> None:
        """Clean DISCONNECT received: the will must never be published
        (§3.1.2-10).  Called by the transport BEFORE teardown."""
        with self._lock:
            session.will = None

    def disconnect(self, client_id: str,
                   session: Optional[Session] = None) -> None:
        """End a session.  Pass the Session returned by connect() so a
        stale connection's teardown cannot destroy a session that was
        taken over by a newer connection with the same client id.

        Any will still registered on the session is published here — the
        transport discards it first on a clean DISCONNECT, so reaching
        this point with a will set means the disconnect was abnormal.  A
        v5 will delay on a persistent session defers publication: the will
        rides the offline entry and fires in the expiry sweep unless the
        session reconnects first (which cancels it)."""
        will = None
        delayed = None
        with self._lock:
            due_wills = self._expire_offline()
            cur = self._sessions.get(client_id)
            if cur is not None and \
                    (session is None or cur is session):
                del self._sessions[client_id]
                will, cur.will = cur.will, None
                if will is not None and not cur.clean_start \
                        and cur.will_delay_s > 0:
                    # spec: publish at the earlier of will-delay expiry and
                    # session expiry — both bounds land in the sweep, and a
                    # timer guarantees the sweep happens even on an
                    # otherwise-quiet broker
                    delay = min(cur.will_delay_s,
                                self.offline_session_expiry_s)
                    # monotonic domain: will/session deadlines must not
                    # stretch or collapse on a wall-clock step (NTP)
                    delayed = (will, time.monotonic() + delay)
                    will = None
                if cur.clean_start:
                    self._tree.unsubscribe_all(client_id)
                    # the session's queue dies with it: a saturated
                    # clean-start client must not wedge the broker-wide
                    # backpressure signal forever after leaving
                    self._bp_sessions.discard(client_id)
                else:
                    # persistent session goes offline: queue QoS≥1
                    # deliveries until it reconnects (bounded, drop-oldest)
                    # or expires
                    q = deque(cur.pending or (),
                              maxlen=self.offline_queue_limit)
                    self._offline[client_id] = [
                        q, time.monotonic() + self.offline_session_expiry_s,
                        cur.qos2_inbound, delayed]
                    # the deque bound may have clipped the carried-over
                    # backlog; re-judge the signal on the real depth
                    self._note_queue_depth(client_id, len(q), count=False)
                    if delayed is not None:
                        self._arm_will_timer(delayed[1])
                self._g_sessions.set(len(self._sessions))
        # outside the lock: will fan-out must not stall the broker
        for w in due_wills:
            self.publish(*w)
        if will is not None:
            self.publish(*will)

    def _expire_offline(self) -> list:
        """Drop offline persistent sessions past their expiry (HiveMQ's
        session-expiry): queue AND subscriptions go.  Returns due delayed
        wills (v5 will-delay-interval) for the CALLER to publish after
        releasing _lock — fan-out under the broker lock would let one slow
        subscriber socket stall every connect/disconnect/publish."""
        now = time.monotonic()
        due_wills = []
        dead = []
        for cid, entry in self._offline.items():
            if entry[3] is not None and entry[3][1] <= now:
                due_wills.append(entry[3][0])
                entry[3] = None
            if entry[1] < now:
                dead.append(cid)
        for cid in dead:
            del self._offline[cid]
            self._tree.unsubscribe_all(cid)
            self._bp_sessions.discard(cid)
        return due_wills

    def _arm_will_timer(self, due_time: float) -> None:
        """Ensure the consolidated will timer fires by `due_time`.
        Caller holds _lock."""
        if due_time >= self._will_timer_due:
            return  # an earlier firing is already scheduled
        if self._will_timer is not None:
            self._will_timer.cancel()
        self._will_timer_due = due_time
        t = threading.Timer(max(due_time - time.monotonic(), 0.0),
                            self._sweep_due_wills)
        t.daemon = True
        t.start()
        self._will_timer = t

    def _sweep_due_wills(self) -> None:
        """Timer target: publish any delayed wills that have come due and
        re-arm for the next pending one.  Without this, a will on a quiet
        broker (no connects/publishes to trigger the lazy sweep) would
        never fire — and a silent fleet is exactly the condition a will
        exists to report."""
        with self._lock:
            self._will_timer = None
            self._will_timer_due = float("inf")
            due = self._expire_offline()
            nxt = min((e[3][1] for e in self._offline.values()
                       if e[3] is not None), default=None)
            if nxt is not None:
                self._arm_will_timer(nxt)
        for w in due:
            self.publish(*w)

    def session_count(self) -> int:
        return len(self._sessions)

    def session_ids(self) -> List[str]:
        with self._lock:
            return list(self._sessions)

    # ------------------------------------------------------------- qos 2
    def qos2_begin(self, session: Session, packet_id: int) -> bool:
        """Exactly-once receiver step 1: returns True when this packet id
        is NEW for the session (caller must forward the publish), False
        when it is a retry of an unreleased id (caller must NOT forward —
        just re-acknowledge with PUBREC).  Spec §4.3.3 receiver flow."""
        with self._lock:
            if packet_id in session.qos2_inbound:
                return False
            session.qos2_inbound.add(packet_id)
            return True

    def qos2_release(self, session: Session, packet_id: int) -> None:
        """Exactly-once receiver step 2 (PUBREL): the sender has seen our
        PUBREC, so the id can never be retried — forget it."""
        with self._lock:
            session.qos2_inbound.discard(packet_id)

    # ----------------------------------------------------- subscriptions
    def subscribe(self, client_id: str, filter_: str, qos: int = 0) -> int:
        """Returns granted qos (0/1/2 — the reference broker advertises
        maxQos 2, hivemq-crd.yaml:13)."""
        validate_filter(filter_)
        granted = min(qos, 2)
        self._tree.subscribe(client_id, filter_, granted)
        # retained delivery on subscribe (spec §3.8.4) — through the same
        # gate as publish(): routing under the lock, a not-yet-ready
        # session's messages join its pending backlog (never a PUBLISH
        # before CONNACK / ahead of the queued backlog), sockets written
        # only after the lock is released
        from .topic_tree import split_share, topic_matches
        group, real = split_share(filter_)
        live: List[Tuple[str, bytes, int]] = []
        if group is None:  # retained messages are not sent to shared subs
            with self._lock:
                sess = self._sessions.get(client_id)
                if sess is not None:
                    for topic, (payload, rqos) in list(self._retained.items()):
                        if not topic_matches(real, topic):
                            continue
                        eff = min(granted, rqos)
                        # retain=True rides along: spec 3.3.1.3 requires the
                        # flag on messages sent due to a new subscription
                        if sess.pending is not None:
                            sess.pending.append((topic, payload, eff, True))
                        else:
                            live.append((topic, payload, eff))
            for topic, payload, eff in live:
                sess.deliver(topic, payload, eff, True)
        return granted

    def unsubscribe(self, client_id: str, filter_: str) -> bool:
        return self._tree.unsubscribe(client_id, filter_)

    # ------------------------------------------------------------ publish
    def publish(self, topic: str, payload: bytes, qos: int = 0,
                retain: bool = False) -> int:
        """Fan a publish out to every matching session; returns the number
        of deliveries."""
        if "+" in topic or "#" in topic:
            raise ValueError(f"wildcards not allowed in publish topic: {topic!r}")
        self._m_in.inc()
        # Trace injection: a record is born here.  Delivery is synchronous
        # on THIS thread (fan-out after the lock is released), so the
        # context rides a thread-local slot to every subscriber callback —
        # the bridge reads it and forwards it as a stream-record header.
        # MQTT 3 has no per-message metadata slot, so no wire change.
        # Re-entrant publishes (a will fired mid-publish, a subscriber
        # republishing) inherit the outer record's context rather than
        # starting their own.
        _tctx = _tprev = None
        if tracing.ENABLED and tracing.current() is None:
            _tctx = tracing.start("mqtt_publish")
            if _tctx is not None:
                _tprev = tracing.set_current(_tctx)
        try:
            return self._publish_locked_fanout(topic, payload, qos, retain)
        finally:
            if _tctx is not None:
                tracing.set_current(_tprev)

    def _publish_locked_fanout(self, topic: str, payload: bytes, qos: int,
                               retain: bool) -> int:
        delivered = queued = 0
        live: List[Tuple[Session, int]] = []
        due_wills: list = []
        with self._lock:  # routing + queue mutation atomic; delivery after
            now = time.monotonic()
            if now >= self._next_offline_sweep:
                due_wills = self._expire_offline()
                self._next_offline_sweep = now + 5.0
            if retain:
                if payload:
                    self._retained[topic] = (payload, qos)
                else:
                    self._retained.pop(topic, None)  # empty retained = clear
            def is_live(cid: str) -> bool:
                s = self._sessions.get(cid)
                return s is not None and s.pending is None

            for cid, granted in self._tree.receivers(topic, is_live=is_live):
                eff = min(qos, granted)
                sess = self._sessions.get(cid)
                if sess is None:
                    entry = self._offline.get(cid)
                    if entry is not None and eff >= 1:
                        entry[0].append((topic, payload, eff, False))
                        self._note_queue_depth(cid, len(entry[0]))
                        queued += 1
                    continue
                if sess.pending is not None:
                    # reconnect in progress: keep order behind the queued
                    # backlog instead of jumping ahead of it (same bound as
                    # the offline queue: drop-oldest)
                    sess.pending.append((topic, payload, eff, False))
                    self._note_queue_depth(cid, len(sess.pending))
                    if len(sess.pending) > self.offline_queue_limit:
                        del sess.pending[0]
                    else:
                        queued += 1
                    continue
                live.append((sess, eff))
        for sess, eff in live:  # outside the lock: a slow socket blocks
            # chaos faultpoint: a "drop" models the flapping device link
            # (the publish happened, the delivery is lost — ledgered as
            # intentional loss), a "dup" the QoS-1 retry duplicate the
            # at-least-once contract must absorb; delays apply inline
            act = chaos.point("mqtt.deliver")
            if act is not None and act.kind == "drop":
                continue
            sess.deliver(topic, payload, eff, False)  # only its publisher
            delivered += 1
            if act is not None and act.kind == "dup":
                sess.deliver(topic, payload, eff, False)
                delivered += 1
        for w in due_wills:  # due delayed wills, also outside the lock
            self.publish(*w)
        if delivered:
            self._m_out.inc(delivered)
        if queued:
            self._m_queued.inc(queued)
        if not delivered and not queued:
            self._m_dropped.inc()
        return delivered

    def retained(self) -> Dict[str, bytes]:
        return {t: p for t, (p, _q) in self._retained.items()}


class QueueClient:
    """In-process client: collects deliveries into a list (tests, sinks)."""

    def __init__(self, broker: MqttBroker, client_id: str,
                 clean_start: bool = True):
        self.broker = broker
        self.client_id = client_id
        self.messages: List[Tuple[str, bytes, int, bool]] = []
        self._lock = threading.Lock()
        self._session = broker.connect(client_id, self._deliver, clean_start)
        broker.deliver_pending(self._session)  # in-process: ready at once

    def _deliver(self, topic: str, payload: bytes, qos: int, retain: bool):
        with self._lock:
            self.messages.append((topic, payload, qos, retain))

    def subscribe(self, filter_: str, qos: int = 0) -> int:
        return self.broker.subscribe(self.client_id, filter_, qos)

    def publish(self, topic: str, payload: bytes, qos: int = 0,
                retain: bool = False) -> int:
        return self.broker.publish(topic, payload, qos, retain)

    def disconnect(self):
        self.broker.disconnect(self.client_id, self._session)
