"""Fleet-scale MQTT listener: one epoll loop, thousands of connections.

The reference fronts its 100,000-car fleet with a 5-node HiveMQ cluster
(reference `infrastructure/hivemq/hivemq-crd.yaml:10-18`: 5 nodes × 4 CPU ×
10G heap; reference `infrastructure/test-generator/scenario.xml:13-14`:
100k clients at ~10k msgs/s fleet-wide).  A thread-per-connection front
(`wire.MqttServer`) cannot hold that many sockets in Python; this listener
is the scale path: non-blocking sockets multiplexed by one
`selectors.DefaultSelector` (epoll on Linux) event loop, per-connection
input/output buffers, and the same `MqttProtocol` state machine the
threaded front drives — so both transports stay protocol-identical by
construction.

Flow-control stance (HiveMQ's "overload protection" analogue, charted by
its Grafana credit-system panels): a consumer connection whose output
buffer exceeds `max_outbuf` is disconnected (slow-consumer eviction —
the broker must never buffer unboundedly for one stalled socket), and
publishers are throttled by aggregate output pressure: when the total
bytes buffered for delivery exceed `high_watermark`, the listener stops
READING from the connections that are feeding it, pushing backpressure
into the publishers' TCP windows — the same stop-reading mechanism
HiveMQ's credit system uses — and resumes them once the backlog drains
below `low_watermark`.

Delivery threading: broker fan-out calls `MqttProtocol.deliver` on the
*publisher's* thread.  For wire-to-wire traffic that is the event-loop
thread itself; for in-process publishers (e.g. platform components) it is
a foreign thread.  `_send_to` is therefore thread-safe: it appends to the
connection's locked output buffer, marks the connection write-pending, and
wakes the loop through a socketpair.  Only the loop thread touches the
selector, so no cross-thread selector mutation ever happens.
"""

from __future__ import annotations

import selectors
import socket
import struct
import threading
import time
from typing import Dict, Optional

from ..obs.metrics import default_registry
from .broker import MqttBroker
from .wire import MqttProtocol, parse_frame

# Overload-protection metric families — the rebuilt twins of the credit-
# system panels in the reference's HiveMQ dashboard (hivemq.json charts
# overload-protection credits and backpressure); the generated Grafana
# dashboards pick these up from the registry automatically.
_m_paused = default_registry.gauge(
    "mqtt_overload_publishers_paused_current",
    "publisher connections currently read-suspended by backpressure")
_m_backlog = default_registry.gauge(
    "mqtt_overload_delivery_backlog_bytes",
    "bytes buffered for delivery across all connections (the watermark "
    "quantity)")
_m_evicted = default_registry.counter(
    "mqtt_overload_slow_consumers_evicted_total",
    "consumer connections dropped for not draining their delivery buffer")


class _EConn:
    """Per-socket state owned by the event loop."""

    __slots__ = ("sock", "proto", "inbuf", "outbuf", "lock", "closing",
                 "paused", "registered", "last_recv")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.proto: Optional[MqttProtocol] = None
        self.inbuf = bytearray()
        self.outbuf = bytearray()
        self.lock = threading.Lock()
        self.closing = False
        self.paused = False  # reads suspended (publisher backpressure)
        self.registered = True  # currently in the selector (loop thread)
        self.last_recv = time.monotonic()  # keepalive clock (loop thread)


class MqttEventServer:
    """Selector-based TCP front for MqttBroker (the 10k-connection path).

    Same context-manager surface as `wire.MqttServer`:
    `with MqttEventServer(broker) as s:` serves on `s.port` until exit.

    Args:
      max_outbuf: slow-consumer eviction threshold (bytes buffered for one
        connection before it is dropped).
      high_watermark / low_watermark: aggregate delivery-backlog bounds for
        publisher backpressure (reads suspended above high, resumed below
        low).
      stall_timeout_s: overload-protection escape.  If the backlog has not
        drained below the low watermark after this long with publishers
        paused, the slowest consumer (largest output buffer) is evicted —
        repeatedly, one per loop pass — until the backlog sinks and the
        publishers resume.  Without this, enough stalled consumers each
        sitting under max_outbuf could hold every publisher paused (and
        their closed sockets unobserved) forever.
      handshake_timeout_s: a connection that has not completed CONNECT
        within this bound is dropped (same 30s stance as the threaded
        front) — otherwise half-open sockets that never speak MQTT would
        hold fds and selector slots forever.
    """

    def __init__(self, broker: MqttBroker, host: str = "127.0.0.1",
                 port: int = 0, max_outbuf: int = 4 << 20,
                 high_watermark: int = 16 << 20,
                 low_watermark: int = 4 << 20,
                 stall_timeout_s: float = 10.0,
                 handshake_timeout_s: float = 30.0):
        self.broker = broker
        self.max_outbuf = max_outbuf
        self.high_watermark = high_watermark
        self.low_watermark = low_watermark
        self.stall_timeout_s = stall_timeout_s
        self.handshake_timeout_s = handshake_timeout_s
        self._pause_started: Optional[float] = None  # loop-thread only
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # generous receive buffers, set on the LISTENER so accepted sockets
        # inherit them with the right window scale (post-connect shrinking
        # wedges TCP).  Under a fleet-scale burst, per-conn buffer overflow
        # on loopback manifests as packet loss → RTO exponential backoff →
        # sockets stuck for tens of seconds with cwnd 1 (observed at
        # backoff 7 / rto 29s in the 9k-conn drain phase) — a deep buffer
        # absorbs a pass's worth of backlog instead.
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1 << 20)
        self._lsock.bind((host, port))
        self._lsock.listen(1024)
        self._lsock.setblocking(False)
        self.host, self.port = self._lsock.getsockname()[:2]
        self._sel = selectors.DefaultSelector()
        self._conns: Dict[socket.socket, _EConn] = {}
        # cross-thread write wake-up: foreign threads add to _pending and
        # poke the socketpair; the loop drains both
        self._pending: set = set()
        self._pending_lock = threading.Lock()
        # aggregate bytes queued for delivery across all connections — the
        # quantity the publisher-backpressure watermarks act on
        self._total_out = 0
        self._out_lock = threading.Lock()
        self._paused_conns: set = set()  # loop-thread only
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._next_ka_sweep = 0.0  # loop-thread only

    # --------------------------------------------------------- lifecycle
    def start(self) -> "MqttEventServer":
        from ..supervise.registry import register_thread

        self._running = True
        self._thread = register_thread(threading.Thread(
            target=self._loop, daemon=True,
            name=f"mqtt-evloop-{self.port}"))
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        self._wake()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for conn in list(self._conns.values()):
            self._close(conn)
        try:
            self._lsock.close()
        finally:
            self._sel.close()
            self._wake_r.close()
            self._wake_w.close()

    def __enter__(self) -> "MqttEventServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def connection_count(self) -> int:
        return len(self._conns)

    @property
    def paused_count(self) -> int:
        """Connections currently read-suspended by backpressure."""
        return len(self._paused_conns)

    # --------------------------------------------------------- internals
    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    def _send_to(self, conn: _EConn, data: bytes) -> None:
        """Thread-safe outbound enqueue (MqttProtocol's send).

        The watermark counter is updated INSIDE conn.lock so it cannot race
        _close's leftover accounting: either the bytes are appended+counted
        before close snapshots them (close subtracts them), or close has
        already marked the connection and this raises without counting.
        Lock order conn.lock → _out_lock everywhere."""
        with conn.lock:
            if conn.closing:
                raise OSError("connection closing")
            conn.outbuf += data
            over = len(conn.outbuf) > self.max_outbuf
            with self._out_lock:
                self._total_out += len(data)
        with self._pending_lock:
            self._pending.add(conn)
        if over:
            # slow-consumer eviction: mark and let the loop tear it down
            conn.closing = True
            _m_evicted.inc()
        if threading.current_thread() is not self._thread:
            self._wake()

    def _loop(self) -> None:
        self._sel.register(self._lsock, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        while self._running:
            events = self._sel.select(timeout=0.1)
            for key, mask in events:
                tag = key.data
                if tag == "accept":
                    self._accept()
                elif tag == "wake":
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                else:
                    conn = tag
                    if mask & selectors.EVENT_READ:
                        self._readable(conn)
                    if mask & selectors.EVENT_WRITE:
                        self._flush(conn)
            # drain cross-thread sends
            with self._pending_lock:
                pending, self._pending = self._pending, set()
            for conn in pending:
                if conn.sock in self._conns:
                    self._flush(conn)
            # overload gauges refresh once per loop pass, not per message
            # (the registry lock must not ride the enqueue hot path); with
            # one listener per process — the deployment shape — the gauges
            # read as this listener's state
            with self._out_lock:
                _m_backlog.set(self._total_out)
            _m_paused.set(len(self._paused_conns))
            # keepalive enforcement (§3.1.2-24): every second, close any
            # connection silent for over 1.5× its announced keepalive —
            # abnormal close, so teardown publishes its will.  Paused
            # connections are exempt: WE stopped reading them, so their
            # pings may be sitting unread in the kernel buffer.
            now = time.monotonic()
            if now >= self._next_ka_sweep:
                self._next_ka_sweep = now + 1.0
                for conn in list(self._conns.values()):
                    if conn.paused:
                        continue
                    proto = conn.proto
                    if proto is None or proto.session is None:
                        # pre-CONNECT: bound the handshake wait
                        if now - conn.last_recv > self.handshake_timeout_s:
                            self._close(conn)
                    elif proto.keepalive and \
                            now - conn.last_recv > 1.5 * proto.keepalive:
                        self._close(conn)
            # backpressure release: resume paused publishers once the
            # aggregate delivery backlog has drained below the low mark
            if self._paused_conns:
                with self._out_lock:
                    below = self._total_out < self.low_watermark
                if below:
                    for conn in list(self._paused_conns):
                        conn.paused = False
                        if conn.sock in self._conns:
                            self._rearm(conn)
                    self._paused_conns.clear()
                    self._pause_started = None
                elif self._pause_started is not None and \
                        time.monotonic() - self._pause_started > \
                        self.stall_timeout_s:
                    # the backlog is not draining: evict the slowest
                    # consumer so the system unwedges instead of holding
                    # every publisher paused indefinitely.  The clock is
                    # NOT reset — once stalled, one eviction per loop pass
                    # until the backlog sinks below the low mark (the
                    # resume branch above then clears the clock).
                    victim = max(self._conns.values(),
                                 key=lambda c: len(c.outbuf), default=None)
                    if victim is not None and victim.outbuf \
                            and not victim.closing:
                        # not victim.closing: an outbuf-cap eviction may
                        # have marked (and counted) it already this pass
                        victim.closing = True  # eviction, not courtesy close
                        _m_evicted.inc()
                        self._close(victim)

    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _EConn(sock)
            conn.proto = MqttProtocol(
                self.broker, lambda data, c=conn: self._send_to(c, data))
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _events_for(self, conn: _EConn) -> int:
        ev = 0
        if not conn.paused:
            ev |= selectors.EVENT_READ
        with conn.lock:
            if conn.outbuf:
                ev |= selectors.EVENT_WRITE
        return ev

    def _rearm(self, conn: _EConn) -> None:
        """Loop-thread only: sync the selector with the connection state.
        A paused connection with nothing to write is UNREGISTERED — keeping
        it readable would defeat the pause (the selector would keep firing
        and the loop keep ingesting).  A remote close is then observed on
        resume, when reads re-arm."""
        ev = self._events_for(conn)
        try:
            if ev:
                if conn.registered:
                    self._sel.modify(conn.sock, ev, conn)
                else:
                    self._sel.register(conn.sock, ev, conn)
                    conn.registered = True
            elif conn.registered:
                self._sel.unregister(conn.sock)
                conn.registered = False
        except (KeyError, ValueError, OSError):
            pass

    def _readable(self, conn: _EConn) -> None:
        # drain up to 4 chunks per event: one 64KB recv per pass left the
        # kernel buffer refilling faster than the loop could circle back
        # under burst load, overflowing it (→ loopback drops → RTO
        # exponential backoff: stuck senders observed at rto ~29s, cwnd 1);
        # bounded so one firehose connection cannot starve the rest of the
        # pass.  Frames read together with an EOF are parsed BEFORE the
        # close — the FIN does not void the data in front of it.
        eof = False
        got_any = False
        for _ in range(4):
            try:
                data = conn.sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                # error-close (e.g. RST after a burst): frames already
                # received THIS pass still parse below — same invariant as
                # the FIN case, the close does not void the data before it
                eof = True
                break
            if not data:
                eof = True
                break
            conn.inbuf += data
            got_any = True
            if len(data) < (1 << 16):
                break
        if not got_any:
            if eof:
                self._close(conn)
            return
        conn.last_recv = time.monotonic()
        pos = 0
        try:
            while True:
                frame = parse_frame(conn.inbuf, pos)
                if frame is None:
                    break
                ptype, flags, body, pos = frame
                if not conn.proto.handle_packet(ptype, flags, body):
                    self._close(conn)
                    return
        except (ValueError, struct.error, IndexError, OSError):
            # protocol violation (malformed varint, truncated body →
            # IndexError from short reads) → drop the connection (MQTT
            # semantics, same stance as the threaded front).  Only THIS
            # connection dies; the loop serves everyone else on.
            self._close(conn)
            return
        if pos:
            del conn.inbuf[:pos]
        if conn.closing or eof:
            self._close(conn)
            return
        # publisher backpressure: this connection just fed us input; if the
        # aggregate delivery backlog is over the high mark, stop reading it
        # (its TCP window fills → the client blocks) until the drain below
        # the low mark resumes it
        with self._out_lock:
            over = self._total_out > self.high_watermark
        if over:
            conn.paused = True
            self._paused_conns.add(conn)
            if self._pause_started is None:
                self._pause_started = time.monotonic()
        self._rearm(conn)

    def _flush(self, conn: _EConn) -> None:
        if conn.closing:
            # eviction (outbuf cap exceeded): the peer is not draining, so
            # waiting for the buffer to empty would keep it alive forever —
            # drop the connection and its buffered output now
            self._close(conn)
            return
        try:
            with conn.lock:
                if conn.outbuf:
                    n = conn.sock.send(conn.outbuf)
                    del conn.outbuf[:n]
                else:
                    n = 0
            if n:
                with self._out_lock:
                    self._total_out -= n
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close(conn)
            return
        self._rearm(conn)

    def _close(self, conn: _EConn) -> None:
        self._paused_conns.discard(conn)
        if not self._paused_conns:
            # last paused conn gone: clear the stall clock, or a LATER
            # pause would inherit this one's start time and evict a
            # healthy consumer instantly
            self._pause_started = None
        with conn.lock:
            # eviction (_send_to's outbuf-cap mark) arrives with closing
            # already True; a graceful close (protocol reject/DISCONNECT)
            # sets it here — under conn.lock, so no _send_to can slip bytes
            # in after our leftover accounting
            closing_was = conn.closing
            conn.closing = True
            leftover = bytes(conn.outbuf)
            conn.outbuf.clear()
            if leftover:
                with self._out_lock:
                    self._total_out -= len(leftover)
        if leftover and not closing_was:
            # graceful close: give the final packets — e.g. the
            # spec-mandated CONNACK rejection code — one best-effort
            # non-blocking send before the FIN, matching the threaded
            # front's synchronous send.  (An evicted stalled reader gets
            # no such courtesy: its buffer is the problem.)
            try:
                conn.sock.send(leftover)
            except OSError:
                pass
        if conn.registered:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
            conn.registered = False
        self._conns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn.proto is not None:
            conn.proto.teardown()
