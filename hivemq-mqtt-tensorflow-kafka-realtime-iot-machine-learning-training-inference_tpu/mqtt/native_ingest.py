"""ctypes front for the native MQTT ingest engine (cpp/mqtt_ingest.cc).

The third MQTT transport, for raw fleet throughput: CONNECT/PUBLISH
parsing and acking happen in C++ (epoll loop + frame parser), and Python
sees only bulk drains — one ctypes call returns every (topic, payload)
extracted since the last drain as a flat arena, so the per-message Python
cost of the ingest hot path drops to a couple of list-slice operations.

`NativeIngestBridge` pairs the engine with the Kafka-extension role:
drained publishes matching the topic mapping are produced onto the stream
topic with the MQTT topic as key — identical record shape to
`mqtt.bridge.KafkaBridge`, same metric families — on a pump thread.

This front is ingest-ONLY by design (no subscriptions, no retained
messages, no QoS 2): the full broker semantics live on the Python fronts
that share `MqttProtocol`.  SUBSCRIBE is answered with the 0x80 failure
code, and a QoS 2 PUBLISH drops the connection.
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import List, Optional, Tuple

from ..obs.metrics import default_registry
from ..stream.broker import Broker
from .bridge import TopicMapping
from .topic_tree import topic_matches


def _load_lib():
    from ..stream.native import load

    lib = load()
    if lib is None or not hasattr(lib, "iotml_mqtt_ingest_create"):
        return None
    lib.iotml_mqtt_ingest_create.restype = ctypes.c_void_p
    lib.iotml_mqtt_ingest_create.argtypes = [ctypes.c_uint16]
    lib.iotml_mqtt_ingest_port.restype = ctypes.c_int
    lib.iotml_mqtt_ingest_port.argtypes = [ctypes.c_void_p]
    lib.iotml_mqtt_ingest_conns.restype = ctypes.c_long
    lib.iotml_mqtt_ingest_conns.argtypes = [ctypes.c_void_p]
    lib.iotml_mqtt_ingest_poll.restype = ctypes.c_long
    lib.iotml_mqtt_ingest_poll.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.iotml_mqtt_ingest_drain.restype = ctypes.c_long
    lib.iotml_mqtt_ingest_drain.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32))]
    lib.iotml_mqtt_ingest_clear.argtypes = [ctypes.c_void_p]
    lib.iotml_mqtt_ingest_close.argtypes = [ctypes.c_void_p]
    return lib


class NativeMqttIngest:
    """Own the engine handle; poll + drain batches of (topic, payload)."""

    def __init__(self, port: int = 0):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native stream engine unavailable")
        self._lib = lib
        self._h = lib.iotml_mqtt_ingest_create(port)
        if not self._h:
            raise OSError(f"cannot bind native MQTT ingest on port {port}")
        self.port = lib.iotml_mqtt_ingest_port(self._h)
        self._lock = threading.Lock()

    @property
    def connection_count(self) -> int:
        with self._lock:
            if self._h is None:
                return 0
            return self._lib.iotml_mqtt_ingest_conns(self._h)

    def poll(self, timeout_ms: int = 50) -> List[Tuple[bytes, bytes]]:
        """One epoll pass + bulk drain → [(topic, payload), ...]."""
        with self._lock:
            if self._h is None:
                return []
            n = self._lib.iotml_mqtt_ingest_poll(self._h, timeout_ms)
            if n <= 0:
                return []
            blob = ctypes.POINTER(ctypes.c_uint8)()
            tl = ctypes.POINTER(ctypes.c_int32)()
            pl = ctypes.POINTER(ctypes.c_int32)()
            n = self._lib.iotml_mqtt_ingest_drain(
                self._h, ctypes.byref(blob), ctypes.byref(tl),
                ctypes.byref(pl))
            total = sum(tl[i] + pl[i] for i in range(n))
            raw = ctypes.string_at(blob, total)
            out = []
            off = 0
            for i in range(n):
                t, p = tl[i], pl[i]
                out.append((raw[off:off + t], raw[off + t:off + t + p]))
                off += t + p
            self._lib.iotml_mqtt_ingest_clear(self._h)
            return out

    def close(self) -> None:
        with self._lock:
            if self._h is not None:
                self._lib.iotml_mqtt_ingest_close(self._h)
                self._h = None

    def __enter__(self) -> "NativeMqttIngest":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NativeIngestBridge:
    """Native listener + Kafka-extension forwarding on a pump thread.

    The drained batch is filtered against the topic mapping (per-distinct-
    topic match result cached — fleets publish on stable per-car topics)
    and produced onto the stream topic keyed by MQTT topic, exactly the
    record shape `KafkaBridge` emits."""

    def __init__(self, stream: Broker,
                 mapping: Optional[TopicMapping] = None,
                 partitions: int = 10, port: int = 0):
        self.stream = stream
        self.mapping = mapping or TopicMapping.sensor_data()
        spec = stream.create_topic(self.mapping.stream_topic,
                                   partitions=partitions)
        # the topic may pre-exist with a different count: partition by
        # the REAL count or keyed routing forks across producers
        partitions = getattr(spec, "partitions", partitions) or partitions
        self.ingest = NativeMqttIngest(port)
        self.port = self.ingest.port
        self._match_cache: dict = {}
        # mqtt topic bytes → stream record key bytes (the mapping's
        # stream_key policy, cached like the match result — fleets
        # publish on stable per-car topics)
        self._key_cache: dict = {}
        self._car_key = getattr(self.mapping, "stream_key",
                                "topic") == "car"
        self._n_fwd = 0
        #: cumulative seconds spent in the stream-produce call (the
        #: bridge leg of the e2e produce breakdown)
        self.produce_seconds = 0.0
        #: zero-copy produce leg (ISSUE 12): drained batches are framed
        #: ONCE (ops.framing.frame_entries, native) and shipped as
        #: RAW_PRODUCE batches to a WIRE/cluster stream broker — the
        #: remote-front shape.  An in-process broker keeps produce_many
        #: (its durable backend fuses the framing internally, and its
        #: in-memory backend would only decode the frames right back).
        self._raw = None
        self._partitions = partitions
        self._part_cache: dict = {}  # record key bytes → partition
        if getattr(stream, "produce_raw", None) is not None and \
                not isinstance(stream, Broker):
            from ..stream.producer import RawBatchProducer

            rp = RawBatchProducer(stream, self.mapping.stream_topic)
            if rp.engaged is not False:
                self._raw = rp
        self._m_fwd = default_registry.counter(
            "kafka_extension_total_forwarded",
            "MQTT publishes bridged into the stream broker (reference "
            "family kafka_extension_*)")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _matches(self, topic: bytes) -> bool:
        hit = self._match_cache.get(topic)
        if hit is None:
            t = topic.decode(errors="replace")
            hit = any(topic_matches(f, t)
                      for f in self.mapping.mqtt_topic_filters)
            if len(self._match_cache) < 1_000_000:
                self._match_cache[topic] = hit
        return hit

    def _key_for(self, topic: bytes) -> bytes:
        if not self._car_key:
            return topic
        key = self._key_cache.get(topic)
        if key is None:
            key = topic.rsplit(b"/", 1)[-1]
            if len(self._key_cache) < 1_000_000:
                self._key_cache[topic] = key
        return key

    def pump_once(self, timeout_ms: int = 50) -> int:
        batch = self.ingest.poll(timeout_ms)
        if not batch:
            return 0
        ts = int(time.time() * 1000)  # wallclock-ok: record timestamp, not a timeout
        matches = self._matches
        key_for = self._key_for
        entries = [(key_for(topic), payload, ts)
                   for topic, payload in batch if matches(topic)]
        n = len(entries)
        if entries:
            t0 = time.perf_counter()
            if self._raw is not None and self._raw.engaged is not False:
                self._produce_raw(entries)
            else:
                # bulk append under one broker lock — the per-message
                # produce loop was this bridge's bottleneck once parsing
                # went native.  produce_many is the Broker duck-type
                # contract (emulator, wire client, native client alike),
                # so a real cluster swap stays a constructor change.
                # Durable in-process brokers fuse the framing inside
                # produce_many (ISSUE 12), so this leg is columnar too.
                self.stream.produce_many(self.mapping.stream_topic,
                                         entries)
            self.produce_seconds += time.perf_counter() - t0
            self._n_fwd += n
            self._m_fwd.inc(n)
        return n

    def _produce_raw(self, entries) -> None:
        """Frame a drained batch ONCE and ship it per-partition as
        RAW_PRODUCE (key-hash partitioning identical to produce_many's
        — per-key ordering is a cross-client invariant; the per-topic
        partition is cached because fleets publish on stable per-car
        topics, like the match cache above).  Accumulations past
        IOTML_PRODUCE_BATCH_BYTES — a drained backlog after a pump
        stall — split at frame boundaries, honoring the operator's
        request-size bound."""
        import zlib

        from ..data.pipeline import produce_batch_bytes
        from ..ops.framing import frame_entries

        cache = self._part_cache
        npart = self._partitions
        by_part: dict = {}
        for e in entries:
            key = e[0]
            p = cache.get(key)
            if p is None:
                p = zlib.crc32(key) % npart
                if len(cache) < 1_000_000:
                    cache[key] = p
            by_part.setdefault(p, []).append(e)
        cap = produce_batch_bytes()
        for p, ents in by_part.items():
            start = 0
            size = 0
            for i, e in enumerate(ents):
                # frame size ≈ key + value + fixed head (the same slack
                # the emulator's read_raw budget uses)
                size += len(e[0]) + len(e[1] or b"") + 64
                if size >= cap and i > start:
                    chunk = ents[start:i]
                    self._raw.produce_frames(p, frame_entries(chunk),
                                             len(chunk), entries=chunk)
                    start, size = i, len(e[0]) + len(e[1] or b"") + 64
            chunk = ents[start:]
            if chunk:
                self._raw.produce_frames(p, frame_entries(chunk),
                                         len(chunk), entries=chunk)

    def forwarded(self) -> int:
        return self._n_fwd

    def start(self) -> "NativeIngestBridge":
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self._run, daemon=True,
            name=f"mqtt-native-{self.port}"))
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.pump_once(timeout_ms=50)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # final drain so nothing ACCEPTED before stop is lost: one pass
        # moves at most one arena, so loop until two consecutive empty
        # polls (level-triggered epoll guarantees pending kernel data keeps
        # reporting).  Bounded: quiesce publishers before stop, or the
        # deadline cuts the drain off.
        idle = 0
        deadline = time.monotonic() + 30
        while idle < 2 and time.monotonic() < deadline:
            idle = idle + 1 if self.pump_once(timeout_ms=0) == 0 else 0
        self.ingest.close()

    def __enter__(self) -> "NativeIngestBridge":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
