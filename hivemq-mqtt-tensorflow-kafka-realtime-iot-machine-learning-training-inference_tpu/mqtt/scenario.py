"""Device-simulator scenario: XML config + commander/agent runner.

The reference drives load through the HiveMQ device simulator, configured
by a scenario XML (brokers / clientGroups / topicGroups / subscriptions /
stages — reference `infrastructure/test-generator/scenario.xml`) and run by
a commander that fans agents out over Kubernetes (reference
`infrastructure/test-generator/kube-cli.sh:347-428`).  Here the same
scenario document drives an in-process agent fleet: client-id patterns
expand to car ids, publish lifecycles pull payloads from `FleetGenerator`,
shared-subscription consumer groups attach like the reference's six
`$share/consumers/...` clients, and per-agent publish metrics are exported
under the reference's `agent_publish_*` family names (devsim.json panels).

Agents can speak in-process (fast path for tests/benchmarks) or real MQTT
over TCP via `iotml.mqtt.wire.MqttClient` (`transport="tcp"`).
"""

from __future__ import annotations

import dataclasses
import json
import re
import threading
import time
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional

from ..core.schema import CAR_SCHEMA
from ..gen.simulator import FleetGenerator, FleetScenario
from ..obs.metrics import default_registry
from .broker import MqttBroker, QueueClient
from .wire import MqttClient

_RATE_RE = re.compile(r"(\d+)\s*/\s*(\d+)?\s*s")
_DUR_RE = re.compile(r"(\d+)\s*(ms|s|m)?")


def parse_rate(rate: str) -> float:
    """'1/10s' → 0.1 msgs/s; '5/s' → 5.0."""
    m = _RATE_RE.fullmatch(rate.strip())
    if not m:
        raise ValueError(f"bad rate: {rate!r}")
    return int(m.group(1)) / int(m.group(2) or 1)


def parse_duration_s(dur: str) -> float:
    m = _DUR_RE.fullmatch(dur.strip())
    if not m:
        raise ValueError(f"bad duration: {dur!r}")
    v = int(m.group(1))
    return {"ms": v / 1000.0, "s": float(v), "m": v * 60.0}[m.group(2) or "s"]


def expand_pattern(pattern: str, i: int) -> str:
    """'electric-vehicle-[0-9]{5}' + 7 → 'electric-vehicle-00007'."""
    def sub(m):
        return f"{i:0{int(m.group(1))}d}"
    return re.sub(r"\[0-9\]\{(\d+)\}", sub, pattern)


@dataclasses.dataclass
class ClientGroup:
    id: str
    pattern: str
    count: int
    mqtt_version: int = 5


@dataclasses.dataclass
class TopicGroup:
    id: str
    pattern: str
    count: int


@dataclasses.dataclass
class Subscription:
    id: str
    topic_filter: Optional[str] = None   # explicit (may be $share/...)
    topic_group: Optional[str] = None    # or: wildcard over a topic group
    wildcard: bool = False


@dataclasses.dataclass
class PublishSpec:
    topic_group: str
    qos: int = 0
    count: int = 1
    rate_per_s: float = 1.0


@dataclasses.dataclass
class LifeCycle:
    id: str
    client_group: str
    ramp_up_s: float = 0.0
    connect: bool = False
    publish: Optional[PublishSpec] = None
    disconnect: bool = False


@dataclasses.dataclass
class Stage:
    id: str
    lifecycles: List[LifeCycle]


@dataclasses.dataclass
class Scenario:
    client_groups: Dict[str, ClientGroup]
    topic_groups: Dict[str, TopicGroup]
    subscriptions: List[Subscription]
    stages: List[Stage]
    broker_address: str = "127.0.0.1"
    broker_port: int = 1883


def parse_scenario(xml_text: str) -> Scenario:
    """Parse a reference-shaped scenario XML document."""
    root = ET.fromstring(xml_text)
    addr, port = "127.0.0.1", 1883
    b = root.find("brokers/broker")
    if b is not None:
        addr = b.findtext("address", addr)
        port = int(b.findtext("port", str(port)))
    cgs = {}
    for cg in root.findall("clientGroups/clientGroup"):
        g = ClientGroup(cg.get("id"),
                        cg.findtext("clientIdPattern"),
                        int(cg.findtext("count", "1")),
                        int(cg.findtext("mqttVersion", "5")))
        cgs[g.id] = g
    tgs = {}
    for tg in root.findall("topicGroups/topicGroup"):
        g = TopicGroup(tg.get("id"), tg.findtext("topicNamePattern"),
                       int(tg.findtext("count", "1")))
        tgs[g.id] = g
    subs = []
    for s in root.findall("subscriptions/subscription"):
        subs.append(Subscription(
            s.get("id"),
            topic_filter=s.findtext("topicFilter"),
            topic_group=s.findtext("topicGroup"),
            wildcard=s.findtext("wildCard", "false").lower() == "true"))
    stages = []
    for st in root.findall("stages/stage"):
        lcs = []
        for lc in st.findall("lifeCycle"):
            ramp = lc.find("rampUp")
            pub = lc.find("publish")
            spec = None
            if pub is not None:
                spec = PublishSpec(
                    topic_group=pub.get("topicGroup"),
                    qos=int(pub.get("qos", "0")),
                    count=int(pub.get("count", "1")),
                    rate_per_s=parse_rate(pub.get("rate", "1/1s")))
            lcs.append(LifeCycle(
                lc.get("id"), lc.get("clientGroup"),
                ramp_up_s=parse_duration_s(ramp.get("duration"))
                if ramp is not None else 0.0,
                connect=lc.find("connect") is not None,
                publish=spec,
                disconnect=lc.find("disconnect") is not None))
        stages.append(Stage(st.get("id"), lcs))
    return Scenario(cgs, tgs, subs, stages, addr, port)


EVALUATION_SCENARIO = Scenario(
    client_groups={"cg1": ClientGroup("cg1", "electric-vehicle-[0-9]{5}", 25)},
    topic_groups={"tg1": TopicGroup(
        "tg1", "vehicles/sensor/data/electric-vehicle-[0-9]{5}", 25)},
    subscriptions=[Subscription(
        "sub-1-shared", topic_filter="$share/consumers/vehicles/sensor/data/#")],
    stages=[Stage("publish", [LifeCycle(
        "publ", "cg1", ramp_up_s=5.0, connect=True,
        publish=PublishSpec("tg1", qos=1, count=40, rate_per_s=0.2),
        disconnect=True)])],
)


class ScenarioRunner:
    """Commander: expands client groups into agents and runs the stages.

    `time_scale=0` (default) runs as fast as possible — rates and ramp-ups
    become ordering only, which is the deterministic test/benchmark mode.
    A positive value sleeps `interval * time_scale` between ticks.
    """

    def __init__(self, scenario: Scenario, broker: MqttBroker,
                 transport: str = "inproc", port: Optional[int] = None,
                 time_scale: float = 0.0, seed: int = 7):
        self.scenario = scenario
        self.broker = broker
        self.transport = transport
        self.port = port
        self.time_scale = time_scale
        self.seed = seed
        reg = default_registry
        self._m_pub_ok = reg.counter(
            "agent_publish_success_total",
            "simulator agent publishes delivered (reference devsim family)")
        self._m_conn = reg.counter(
            "agent_connect_success_total", "simulator agent connects")
        self.consumer_counts: Dict[str, int] = {}
        # deliveries arrive on broker fan-out threads under tcp transport
        self._count_lock = threading.Lock()

    def _make_client(self, client_id: str, version: int):
        if self.transport == "tcp":
            # explicit port= means "the local test server" and overrides the
            # scenario's (typically cluster-internal) broker address too
            if self.port is not None:
                host, port = "127.0.0.1", self.port
            else:
                host = self.scenario.broker_address or "127.0.0.1"
                port = self.scenario.broker_port
            if port is None:
                raise ValueError(
                    "tcp transport needs a port: pass port= or set "
                    "<broker><port> in the scenario")
            return MqttClient(host, port, client_id,
                              protocol_level=4 if version < 5 else 5)
        return QueueClient(self.broker, client_id)

    def _group_filters(self, tg, wildcard: bool):
        """Subscription filters for a topic group.

        wildcard=True collapses the group to one valid filter: every level
        from the first one containing a pattern construct onward becomes a
        single trailing '#' ('vehicles/sensor/data/electric-vehicle-[0-9]{5}'
        → 'vehicles/sensor/data/#', the shape the reference's consumers use,
        scenario.xml sub-1).  wildcard=False subscribes each expanded topic
        of the group individually — the pattern itself is not a topic.
        """
        if wildcard:
            levels = tg.pattern.split("/")
            keep = []
            for lv in levels:
                if re.search(r"[\[\]{}()*+?\\]", lv):
                    break
                keep.append(lv)
            return ["/".join(keep + ["#"]) if len(keep) < len(levels)
                    else tg.pattern]
        return [expand_pattern(tg.pattern, i) for i in range(tg.count)]

    def _attach_consumers(self):
        consumers = []
        for sub in self.scenario.subscriptions:
            filters = [sub.topic_filter] if sub.topic_filter else []
            if not filters and sub.topic_group:
                tg = self.scenario.topic_groups[sub.topic_group]
                filters = self._group_filters(tg, sub.wildcard)
            if not filters:
                continue
            cid = f"consumer-{sub.id}"
            self.consumer_counts[cid] = 0

            def deliver(topic, payload, qos, retain, _cid=cid):
                with self._count_lock:
                    self.consumer_counts[_cid] += 1

            sess = self.broker.connect(cid, deliver)
            self.broker.deliver_pending(sess)  # in-process: ready at once
            for filt in filters:
                self.broker.subscribe(cid, filt)
            consumers.append(cid)
        return consumers

    def run(self, payload_encoding: str = "json") -> Dict[str, int]:
        """Execute all stages; returns summary counters."""
        self._attach_consumers()
        published = 0
        for stage in self.scenario.stages:
            for lc in stage.lifecycles:
                cg = self.scenario.client_groups[lc.client_group]
                if lc.publish is None:
                    if lc.connect:
                        self._m_conn.inc(cg.count)
                    continue
                tg = self.scenario.topic_groups[lc.publish.topic_group]
                gen = FleetGenerator(FleetScenario(
                    num_cars=cg.count,
                    msgs_per_car=lc.publish.count,
                    interval_s=1.0 / max(lc.publish.rate_per_s, 1e-9),
                    ramp_up_s=lc.ramp_up_s, seed=self.seed))
                clients = [self._make_client(expand_pattern(cg.pattern, i),
                                             cg.mqtt_version)
                           for i in range(cg.count)]
                self._m_conn.inc(cg.count)
                # agents wrap around the topic group's declared size — a
                # client group larger than the topic group must not invent
                # topics its subscribers never declared
                topics = [expand_pattern(tg.pattern, i % tg.count)
                          for i in range(cg.count)]
                for tick in range(lc.publish.count):
                    cols = gen.step_columns()
                    for i, client in enumerate(clients):
                        rec = gen.row_record(cols, i, schema=CAR_SCHEMA)
                        rec["failure_occurred"] = \
                            str(cols["failure_occurred"][i])
                        client.publish(topics[i], json.dumps(rec).encode(),
                                       qos=lc.publish.qos)
                        published += 1
                        self._m_pub_ok.inc()
                    if self.time_scale > 0:
                        time.sleep(gen.scenario.interval_s * self.time_scale)
                # quiesce: qos-0 over TCP is fire-and-forget, so drain each
                # connection with a ping round-trip (in-order processing
                # makes PINGRESP a fan-out barrier) before counting/closing
                if self.transport == "tcp":
                    for client in clients:
                        client.ping()
                if lc.disconnect:
                    for client in clients:
                        client.disconnect()
        out = {"published": published}
        out.update(self.consumer_counts)
        return out
