"""MQTT topic matching and subscription storage.

The reference's ingestion front-end is a HiveMQ cluster whose Kafka
extension forwards every publish matching an MQTT *topic filter* into a
Kafka topic (reference `infrastructure/hivemq/kafka-config.yaml:20-29`,
filter `vehicles/sensor/data/#`), and whose load test subscribes six
consumers through a *shared* subscription `$share/consumers/...`
(reference `infrastructure/test-generator/scenario.xml:33-35`).  Both
behaviors live here: spec-correct filter matching (`+` one level, `#`
trailing multi-level, `$`-topics shielded from root wildcards) and a trie
of subscriptions with HiveMQ-style shared-group round-robin delivery.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Tuple

SHARE_PREFIX = "$share/"


def split_share(filter_: str) -> Tuple[Optional[str], str]:
    """(share_group, real_filter) — group is None for ordinary filters."""
    if filter_.startswith(SHARE_PREFIX):
        rest = filter_[len(SHARE_PREFIX):]
        group, sep, real = rest.partition("/")
        if not sep or not group or not real:
            raise ValueError(f"malformed shared subscription: {filter_!r}")
        return group, real
    return None, filter_


def validate_filter(filter_: str) -> None:
    _, real = split_share(filter_)
    if not real:
        raise ValueError("empty topic filter")
    levels = real.split("/")
    for i, lv in enumerate(levels):
        if "#" in lv and (lv != "#" or i != len(levels) - 1):
            raise ValueError(f"'#' must be the final whole level: {filter_!r}")
        if "+" in lv and lv != "+":
            raise ValueError(f"'+' must occupy a whole level: {filter_!r}")


def topic_matches(filter_: str, topic: str) -> bool:
    """MQTT-spec filter matching (without $share handling)."""
    f_levels = filter_.split("/")
    t_levels = topic.split("/")
    # topics beginning with '$' are not matched by filters starting with
    # a wildcard (MQTT 3.1.1 §4.7.2 / MQTT 5 §4.7.2)
    if t_levels[0].startswith("$") and f_levels[0] in ("#", "+"):
        return False
    i = 0
    for i, f in enumerate(f_levels):
        if f == "#":
            return True
        if i >= len(t_levels):
            return False
        if f != "+" and f != t_levels[i]:
            return False
    if len(t_levels) > len(f_levels):
        return False
    return True


class _Node:
    __slots__ = ("children", "subs")

    def __init__(self):
        self.children: Dict[str, _Node] = {}
        # (client_id, share_group) → qos
        self.subs: Dict[Tuple[str, Optional[str]], int] = {}


class TopicTree:
    """Subscription trie: add/remove filters, match a publish topic to
    (client_id, qos) receivers with shared-group round-robin."""

    def __init__(self):
        self._root = _Node()
        self._lock = threading.Lock()
        self._rr: Dict[Tuple[str, str], int] = {}  # (group, filter) → cursor

    def subscribe(self, client_id: str, filter_: str, qos: int = 0) -> None:
        validate_filter(filter_)
        group, real = split_share(filter_)
        with self._lock:
            node = self._root
            for lv in real.split("/"):
                node = node.children.setdefault(lv, _Node())
            node.subs[(client_id, group)] = qos

    def unsubscribe(self, client_id: str, filter_: str) -> bool:
        group, real = split_share(filter_)
        with self._lock:
            node = self._root
            for lv in real.split("/"):
                node = node.children.get(lv)
                if node is None:
                    return False
            return node.subs.pop((client_id, group), None) is not None

    def unsubscribe_all(self, client_id: str) -> None:
        with self._lock:
            stack = [self._root]
            while stack:
                node = stack.pop()
                for key in [k for k in node.subs if k[0] == client_id]:
                    del node.subs[key]
                stack.extend(node.children.values())

    # ------------------------------------------------------------- match
    def _collect(self, node: _Node, levels: List[str], i: int,
                 skip_wild_root: bool, out: List[Tuple[_Node, str]],
                 path: List[str]) -> None:
        if i == len(levels):
            if node.subs:
                out.append((node, "/".join(path)))
            # "sport/#" also matches "sport" (the parent level itself)
            child = node.children.get("#")
            if child is not None and child.subs:
                out.append((child, "/".join(path + ["#"])))
            return
        lv = levels[i]
        for key in (lv, "+", "#"):
            if skip_wild_root and i == 0 and key in ("+", "#"):
                continue
            child = node.children.get(key)
            if child is None:
                continue
            if key == "#":
                if child.subs:
                    out.append((child, "/".join(path + ["#"])))
            else:
                self._collect(child, levels, i + 1, skip_wild_root, out,
                              path + [key])

    def receivers(self, topic: str,
                  is_live=None) -> List[Tuple[str, int]]:
        """All (client_id, granted_qos) that should receive a publish on
        `topic`; each shared group contributes exactly one member, rotated
        per matching filter.

        `is_live(cid) -> bool`, when given, steers shared-group selection:
        the rotation skips to the next LIVE member so an offline persistent
        member does not swallow its share of the group's traffic (HiveMQ
        queues for a shared group only when no member is connected).  Falls
        back to the plain rotation pick when every member is offline."""
        levels = topic.split("/")
        shield = levels[0].startswith("$")
        matched: List[Tuple[_Node, str]] = []
        with self._lock:
            self._collect(self._root, levels, 0, shield, matched, [])
            out: List[Tuple[str, int]] = []
            seen = set()
            for node, filter_str in matched:
                groups: Dict[str, List[Tuple[str, int]]] = {}
                for (cid, group), qos in sorted(node.subs.items(),
                                                key=lambda kv: kv[0][0]):
                    if group is None:
                        if cid not in seen:
                            seen.add(cid)
                            out.append((cid, qos))
                    else:
                        groups.setdefault(group, []).append((cid, qos))
                for group, members in groups.items():
                    cur = self._rr.get((group, filter_str), 0)
                    pick = None
                    for i in range(len(members)):
                        cand = members[(cur + i) % len(members)]
                        if is_live is None or is_live(cand[0]):
                            pick = cand
                            cur = cur + i
                            break
                    if pick is None:  # nobody live: queue at rotation pick
                        pick = members[cur % len(members)]
                    cid, qos = pick
                    self._rr[(group, filter_str)] = cur + 1
                    if cid not in seen:
                        seen.add(cid)
                        out.append((cid, qos))
            return out

    def filters_of(self, client_id: str) -> List[str]:
        out: List[str] = []
        with self._lock:
            stack: List[Tuple[_Node, List[str]]] = [(self._root, [])]
            while stack:
                node, path = stack.pop()
                for (cid, group) in node.subs:
                    if cid == client_id:
                        real = "/".join(path)
                        out.append(f"$share/{group}/{real}" if group else real)
                for lv, child in node.children.items():
                    stack.append((child, path + [lv]))
        return sorted(out)
