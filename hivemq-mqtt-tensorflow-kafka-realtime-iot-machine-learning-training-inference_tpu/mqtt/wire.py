"""MQTT wire protocol: packet codec, TCP server, and a small client.

The reference's device fleet speaks real MQTT over TCP to HiveMQ on :1883
(reference `infrastructure/hivemq/hivemq-mqtt.yaml:12-14`, scenario clients
`mqttVersion 5`).  This module gives the framework the same boundary: an
MQTT 3.1.1 server (protocol level 4; level-5 CONNECT/SUBSCRIBE/PUBLISH
packets are accepted by parsing and skipping their properties block) in
front of `MqttBroker`, plus a blocking client used by the load-generator
agents.  QoS 0, 1, and 2 are implemented end to end (PUBLISH→PUBACK;
PUBLISH→PUBREC→PUBREL→PUBCOMP with broker-side dedup surviving reconnect)
— the reference broker advertises maxQos 2 (hivemq-crd.yaml:13); its
scenarios use qos 0 (full) and qos 1 (evaluation).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .broker import MqttBroker

# packet types
CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
PUBREC, PUBREL, PUBCOMP = 5, 6, 7
SUBSCRIBE, SUBACK, UNSUBSCRIBE, UNSUBACK = 8, 9, 10, 11
PINGREQ, PINGRESP, DISCONNECT = 12, 13, 14


# ------------------------------------------------------------------ codec
def encode_varlen(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | 0x80 if n else b)
        if not n:
            return bytes(out)


def decode_varlen(read: Callable[[int], bytes]) -> int:
    mult, val = 1, 0
    for _ in range(4):
        (b,) = read(1)
        val += (b & 0x7F) * mult
        if not b & 0x80:
            return val
        mult *= 128
    raise ValueError("malformed remaining-length")


def _mqtt_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _read_str(buf: bytes, pos: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from(">H", buf, pos)
    return buf[pos + 2:pos + 2 + n].decode(), pos + 2 + n


def _skip_props(buf: bytes, pos: int) -> int:
    """Skip an MQTT 5 properties block: variable-byte-integer length, then
    that many bytes (spec §2.2.2 — NOT a single length byte)."""
    cur = [pos]

    def read(n: int) -> bytes:
        chunk = buf[cur[0]:cur[0] + n]
        cur[0] += n
        return chunk

    length = decode_varlen(read)  # advances cur past the varint itself
    return cur[0] + length


def _parse_will_props(buf: bytes, pos: int) -> Tuple[int, int]:
    """Parse an MQTT 5 will-properties block; returns (will_delay_s,
    next_pos).  Table-driven over the property ids the spec allows in a
    will block (§3.1.3.2): the Will Delay Interval (0x18) is extracted,
    the rest are validated-and-skipped."""
    cur = [pos]

    def read(n: int) -> bytes:
        chunk = buf[cur[0]:cur[0] + n]
        cur[0] += n
        return chunk

    length = decode_varlen(read)
    p, end = cur[0], cur[0] + length
    delay = 0
    while p < end:
        pid = buf[p]
        p += 1
        if pid == 0x18:    # will delay interval: 4-byte int
            (delay,) = struct.unpack_from(">I", buf, p)
            p += 4
        elif pid == 0x01:  # payload format indicator: 1 byte
            p += 1
        elif pid == 0x02:  # message expiry interval: 4-byte int
            p += 4
        elif pid in (0x03, 0x08):   # content type / response topic: utf8
            _, p = _read_str(buf, p)
        elif pid == 0x09:  # correlation data: binary (u16 length)
            (n,) = struct.unpack_from(">H", buf, p)
            p += 2 + n
        elif pid == 0x26:  # user property: utf8 pair
            _, p = _read_str(buf, p)
            _, p = _read_str(buf, p)
        else:
            raise ValueError(f"bad will property id 0x{pid:02x}")
    if p != end:
        raise ValueError("will properties overrun")
    return delay, end


def packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + encode_varlen(len(body)) + body


def connect_packet(client_id: str, protocol_level: int = 4,
                   keepalive: int = 60, clean: bool = True,
                   will: Optional[Tuple[str, bytes, int, bool]] = None,
                   will_delay_s: int = 0) -> bytes:
    """CONNECT.  `will` is (topic, payload, qos, retain) — the Last Will
    registered with the broker, published on abnormal disconnect (spec
    §3.1.2-8).  `will_delay_s` emits the v5 Will Delay Interval property
    (0x18) inside the will-properties block."""
    name = "MQTT"
    flags = 0x02 if clean else 0x00
    if will is not None:
        wtopic, wpayload, wqos, wretain = will
        flags |= 0x04 | ((wqos & 0x03) << 3) | (0x20 if wretain else 0x00)
    body = _mqtt_str(name) + bytes([protocol_level, flags]) + \
        struct.pack(">H", keepalive)
    if protocol_level == 5:
        body += b"\x00"  # empty properties
    body += _mqtt_str(client_id)
    if will is not None:
        if protocol_level == 5:
            if will_delay_s:
                props = b"\x18" + struct.pack(">I", will_delay_s)
                body += encode_varlen(len(props)) + props
            else:
                body += b"\x00"  # empty will properties
        body += _mqtt_str(wtopic) + struct.pack(">H", len(wpayload)) + wpayload
    return packet(CONNECT, 0, body)


def publish_packet(topic: str, payload: bytes, qos: int = 0,
                   retain: bool = False, packet_id: int = 0,
                   protocol_level: int = 4, dup: bool = False) -> bytes:
    flags = (qos << 1) | (1 if retain else 0) | (0x08 if dup else 0)
    body = _mqtt_str(topic)
    if qos > 0:
        body += struct.pack(">H", packet_id)
    if protocol_level == 5:
        body += b"\x00"
    body += payload
    return packet(PUBLISH, flags, body)


def subscribe_packet(packet_id: int, filters: List[Tuple[str, int]],
                     protocol_level: int = 4) -> bytes:
    body = struct.pack(">H", packet_id)
    if protocol_level == 5:
        body += b"\x00"
    for f, q in filters:
        body += _mqtt_str(f) + bytes([q])
    return packet(SUBSCRIBE, 0x02, body)


from ..utils.net import recv_exact as _recv_exact


def parse_frame(buf, pos: int):
    """Parse one MQTT frame out of buf[pos:].

    Returns (ptype, flags, body, next_pos), or None while the frame is
    still incomplete.  Raises ValueError on a malformed remaining-length.
    This is the zero-copy-ish framing step both transports share: the
    blocking server reads exact counts, the event server feeds recv()
    chunks through this."""
    n = len(buf)
    if n - pos < 2:
        return None
    h = buf[pos]
    mult, length, i = 1, 0, pos + 1
    for _ in range(4):
        if i >= n:
            return None
        b = buf[i]
        i += 1
        length += (b & 0x7F) * mult
        if not b & 0x80:
            break
        mult *= 128
    else:
        raise ValueError("malformed remaining-length")
    if n - i < length:
        return None
    return h >> 4, h & 0x0F, bytes(buf[i:i + length]), i + length


class MqttProtocol:
    """Transport-independent per-connection MQTT state machine.

    Both TCP fronts (`MqttServer`, thread-per-connection, and
    `MqttEventServer`, epoll loop) drive this one class: they feed it
    decoded frames via `handle_packet` and give it a `send(bytes)` to
    answer on.  Broker fan-out arrives through `deliver` (registered as
    the session's delivery callback), which may run on any thread — the
    supplied `send` must be thread-safe."""

    def __init__(self, broker: MqttBroker, send: Callable[[bytes], None]):
        self.broker = broker
        self._send = send
        self.level = 4
        self.client_id: Optional[str] = None
        self.session = None
        #: keepalive seconds from CONNECT (0 = disabled).  The transport
        #: enforces the spec's 1.5× rule: no packet for keepalive*1.5 →
        #: abnormal close (which publishes the will, §3.1.2-10).
        self.keepalive = 0
        self._next_pid = 0
        self._pid_lock = threading.Lock()
        # outbound QoS 2 sender state: pid → "rec" (awaiting PUBREC) or
        # "comp" (PUBREL sent, awaiting PUBCOMP).  Spec §4.3.3 sender flow.
        self._out_qos2: Dict[int, str] = {}

    # ------------------------------------------------------ broker fan-out
    def deliver(self, topic: str, payload: bytes, qos: int, retain: bool):
        pid = 0
        if qos > 0:
            with self._pid_lock:
                self._next_pid = self._next_pid % 65535 + 1
                pid = self._next_pid
                if qos == 2:
                    self._out_qos2[pid] = "rec"
        try:
            self._send(publish_packet(topic, payload, qos, retain, pid,
                                      protocol_level=self.level))
        except OSError:
            pass  # connection torn down mid-fanout; session cleanup follows

    # ------------------------------------------------------ inbound frames
    def handle_packet(self, ptype: int, flags: int, body: bytes) -> bool:
        """Process one frame; returns False when the connection must close.

        Raises ValueError/struct.error on protocol violations (wildcard
        PUBLISH topic, short body) — MQTT says drop the connection."""
        broker = self.broker
        if ptype != CONNECT and self.session is None:
            # spec §3.1: the first packet MUST be CONNECT.  Without this a
            # pre-CONNECT SUBSCRIBE would register topic-tree state under a
            # None client id that no teardown ever removes.
            raise ValueError(f"packet type {ptype} before CONNECT")
        if ptype == CONNECT:
            if self.session is not None:
                raise ValueError("second CONNECT on one connection")
            _name, pos = _read_str(body, 0)
            self.level = body[pos]
            cflags = body[pos + 1]
            clean = bool(cflags & 0x02)
            (self.keepalive,) = struct.unpack_from(">H", body, pos + 2)
            pos += 4  # level + flags + keepalive
            if self.level >= 5:
                pos = _skip_props(body, pos)
            client_id, pos = _read_str(body, pos)
            # Last Will (§3.1.2-8): will flag → will topic + message follow
            # the client id (after will properties on v5).  Round-1/2 builds
            # silently discarded these — the failure-detection primitive a
            # predictive-maintenance fleet leans on (a dead car's will tells
            # the platform the car is gone).
            will = None
            will_delay_s = 0
            if cflags & 0x04:
                if self.level >= 5:
                    will_delay_s, pos = _parse_will_props(body, pos)
                wtopic, pos = _read_str(body, pos)
                (wlen,) = struct.unpack_from(">H", body, pos)
                wpayload = bytes(body[pos + 2:pos + 2 + wlen])
                if len(wpayload) != wlen:
                    raise ValueError("truncated will payload")
                pos += 2 + wlen
                will = (wtopic, wpayload, (cflags >> 3) & 0x03,
                        bool(cflags & 0x20))
            if not client_id and not clean:
                # §3.1.3-8: a zero-byte client id REQUIRES a clean
                # session — a synthesized persistent id could never
                # be resumed, only leak offline queue state.
                # v5: reason 0x85 (client id not valid) + empty
                # properties; v4: return code 0x02
                reject = (b"\x00\x85\x00" if self.level >= 5
                          else b"\x00\x02")
                self._send(packet(CONNACK, 0, reject))
                return False
            self.client_id = client_id or f"anon-{id(self):x}"
            self.session = broker.connect(self.client_id, self.deliver, clean,
                                          will=will,
                                          will_delay_s=will_delay_s)
            # byte 1 bit 0 = session-present (MQTT 3.1.1 §3.2.2.2):
            # a resumed persistent session must say so, or spec
            # clients discard their subscription state
            sp = b"\x01" if self.session.resumed else b"\x00"
            ack = sp + (b"\x00\x00" if self.level >= 5 else b"\x00")
            self._send(packet(CONNACK, 0, ack))
            # only after CONNACK is on the wire may queued offline
            # PUBLISHes flow (a pre-CONNACK PUBLISH breaks clients)
            broker.deliver_pending(self.session)
        elif ptype == PUBLISH:
            qos = (flags >> 1) & 0x03
            retain = bool(flags & 0x01)
            topic, pos = _read_str(body, 0)
            pid = 0
            if qos > 0:
                (pid,) = struct.unpack_from(">H", body, pos)
                pos += 2
            if self.level >= 5:
                pos = _skip_props(body, pos)
            if qos == 2:
                # exactly-once inbound: forward only the FIRST arrival of
                # this packet id; a DUP retry (PUBREC lost / reconnect
                # before PUBREL) re-acknowledges without re-forwarding
                if self.broker.qos2_begin(self.session, pid):
                    broker.publish(topic, body[pos:], qos, retain)
                self._send(packet(PUBREC, 0, struct.pack(">H", pid)))
            else:
                broker.publish(topic, body[pos:], qos, retain)
                if qos == 1:
                    self._send(packet(PUBACK, 0, struct.pack(">H", pid)))
        elif ptype == PUBREL:
            # sender released the id: complete the handshake and forget it
            (pid,) = struct.unpack_from(">H", body, 0)
            self.broker.qos2_release(self.session, pid)
            self._send(packet(PUBCOMP, 0, struct.pack(">H", pid)))
        elif ptype == PUBREC:
            # receiver acked our QoS 2 delivery: release
            (pid,) = struct.unpack_from(">H", body, 0)
            if self._out_qos2.get(pid) == "rec":
                self._out_qos2[pid] = "comp"
            self._send(packet(PUBREL, 0x02, struct.pack(">H", pid)))
        elif ptype == PUBCOMP:
            (pid,) = struct.unpack_from(">H", body, 0)
            self._out_qos2.pop(pid, None)
        elif ptype == SUBSCRIBE:
            (pid,) = struct.unpack_from(">H", body, 0)
            pos = 2
            if self.level >= 5:
                pos = _skip_props(body, pos)
            codes = bytearray()
            while pos < len(body):
                f, pos = _read_str(body, pos)
                qos = body[pos] & 0x03
                pos += 1
                try:
                    codes.append(broker.subscribe(self.client_id, f, qos))
                except ValueError:
                    codes.append(0x80)  # per-filter failure code
            self._send(packet(SUBACK, 0,
                              struct.pack(">H", pid) +
                              (b"\x00" if self.level >= 5 else b"") +
                              bytes(codes)))
        elif ptype == UNSUBSCRIBE:
            (pid,) = struct.unpack_from(">H", body, 0)
            pos = 2
            if self.level >= 5:
                pos = _skip_props(body, pos)
            while pos < len(body):
                f, pos = _read_str(body, pos)
                broker.unsubscribe(self.client_id, f)
            self._send(packet(UNSUBACK, 0, struct.pack(">H", pid)))
        elif ptype == PINGREQ:
            self._send(packet(PINGRESP, 0, b""))
        elif ptype == PUBACK:
            pass  # client acks for our qos1 deliveries
        elif ptype == DISCONNECT:
            # clean disconnect discards the will (§3.1.2-10) — EXCEPT the
            # v5 "disconnect with will message" reason 0x04 (§3.14.2.1),
            # which closes the network connection normally but still asks
            # for the will to be published
            keep_will = (self.level >= 5 and len(body) >= 1
                         and body[0] == 0x04)
            if not keep_will and self.session is not None:
                self.broker.discard_will(self.session)
            return False
        return True

    def teardown(self):
        if self.client_id is not None:
            # identity-checked: a session taken over by a newer
            # connection with this client id survives our teardown.
            # Any will still registered on the session is published by the
            # broker here — reaching teardown without a clean DISCONNECT
            # (socket error, EOF, keepalive timeout, protocol violation)
            # is exactly the spec's "abnormal disconnection".
            self.broker.disconnect(self.client_id, self.session)


# ------------------------------------------------------------------ server
class _Conn(socketserver.BaseRequestHandler):
    """One MQTT connection on the thread-per-connection front.  The handler
    loop reads packets and drives the shared MqttProtocol; outbound
    publishes are serialized by a per-connection write lock (broker fan-out
    may run on other publishers' threads)."""

    def _read_exact(self, n: int) -> bytes:
        return _recv_exact(self.request, n)

    def _send(self, data: bytes) -> None:
        with self._wlock:
            self.request.sendall(data)

    def handle(self):
        broker: MqttBroker = self.server.broker  # type: ignore[attr-defined]
        self._wlock = threading.Lock()
        proto = MqttProtocol(broker, self._send)
        try:
            # until CONNECT announces a keepalive, bound the handshake wait
            self.request.settimeout(30.0)
            ka_armed = 0
            while True:
                (h,) = self._read_exact(1)
                ptype, flags = h >> 4, h & 0x0F
                length = decode_varlen(self._read_exact)
                body = self._read_exact(length) if length else b""
                if not proto.handle_packet(ptype, flags, body):
                    break
                if not ka_armed and proto.session is not None:
                    # §3.1.2-24: 1.5× the keepalive with no packet →
                    # abnormal close (the timeout surfaces as OSError, so
                    # teardown publishes the will); keepalive 0 disables
                    ka_armed = 1
                    self.request.settimeout(
                        proto.keepalive * 1.5 if proto.keepalive else None)
        except (ConnectionError, OSError):
            pass
        except (ValueError, struct.error, IndexError):
            # protocol violation (wildcard PUBLISH topic, malformed
            # varint/short body — truncated bodies surface as IndexError):
            # MQTT says drop the connection — without letting socketserver
            # dump a traceback per bad client
            pass
        finally:
            proto.teardown()


class MqttServer(socketserver.ThreadingTCPServer):
    """TCP front for MqttBroker.  `with MqttServer(broker) as s:` serves on
    an ephemeral localhost port (`s.port`) until the block exits."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, broker: MqttBroker, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__((host, port), _Conn)
        self.broker = broker
        self.port = self.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MqttServer":
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self.serve_forever, daemon=True,
            name=f"iotml-mqtt-wire-{self.port}"))
        self._thread.start()
        return self

    def __enter__(self) -> "MqttServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()
        self.server_close()


# ------------------------------------------------------------------ client
class MqttClient:
    """Small blocking MQTT client (the simulator agents' network path)."""

    def __init__(self, host: str, port: int, client_id: str,
                 protocol_level: int = 4, clean: bool = True,
                 on_message: Optional[Callable[[str, bytes], None]] = None,
                 keepalive: int = 60,
                 will: Optional[Tuple[str, bytes, int, bool]] = None,
                 will_delay_s: int = 0):
        self.client_id = client_id
        self._level = protocol_level
        self._sock = socket.create_connection((host, port), timeout=10)
        self._on_message = on_message
        self._acks: Dict[int, threading.Event] = {}
        # QoS 2 sender: pid → (PUBREC event, PUBCOMP event)
        self._qos2_acks: Dict[int, Tuple[threading.Event, threading.Event]] = {}
        # QoS 2 receiver dedup: inbound pids seen but not yet PUBREL'd
        self._qos2_inbound: set = set()
        self._suback = threading.Event()
        self._suback_codes: List[int] = []
        # ping pairing: PINGRESPs are FIFO per connection, so response N
        # answers request N — counting both sides lets ping() wait for THE
        # response to ITS request, immune to late responses of abandoned
        # (timed-out keepalive) requests satisfying a later barrier early
        self._ping_sent = 0
        self._ping_rcvd = 0
        self._ping_cv = threading.Condition()
        self._next_pid = 0
        self._wlock = threading.Lock()
        self._sock.sendall(connect_packet(client_id, protocol_level,
                                          keepalive=keepalive, clean=clean,
                                          will=will,
                                          will_delay_s=will_delay_s))
        h, body = self._read_packet()
        if h >> 4 != CONNACK:
            raise ConnectionError(f"expected CONNACK, got {h >> 4}")
        # the connect timeout must not survive into the reader thread: an
        # idle subscriber would hit recv timeout after 10s and die silently
        self._sock.settimeout(None)
        from ..supervise.registry import register_thread

        self._reader = register_thread(threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"iotml-mqtt-reader-{client_id}"))
        self._reader.start()
        # honor our announced keepalive: the server evicts at 1.5× with no
        # inbound packet, so an idle client must ping on its own — one
        # PINGREQ every keepalive/2 (clients may send early, §3.1.2-23)
        self._closed = threading.Event()
        # serializes ping round-trips: with the keepalive thread also
        # pinging, an unpaired PINGREQ's late PINGRESP could otherwise
        # satisfy a user ping()'s wait early and break its quiesce-barrier
        # guarantee (at most ONE outstanding PINGREQ at a time)
        self._ping_lock = threading.Lock()
        if keepalive:
            self._keeper = register_thread(threading.Thread(
                target=self._keepalive_loop, args=(keepalive / 2,),
                daemon=True, name=f"iotml-mqtt-keepalive-{client_id}"))
            self._keeper.start()

    def _keepalive_loop(self, interval_s: float) -> None:
        while not self._closed.wait(interval_s):
            try:
                self.ping(timeout=interval_s)
            except TimeoutError:
                continue  # one slow PINGRESP (GC pause, loaded box) must
                #           not permanently disable keepalive
            except OSError:
                return  # connection gone: the reader owns errors

    def _read_exact(self, n: int) -> bytes:
        return _recv_exact(self._sock, n)

    def _read_packet(self) -> Tuple[int, bytes]:
        (h,) = self._read_exact(1)
        length = decode_varlen(self._read_exact)
        return h, self._read_exact(length) if length else b""

    def _read_loop(self):
        try:
            while True:
                h, body = self._read_packet()
                ptype, flags = h >> 4, h & 0x0F
                if ptype == PUBLISH:
                    qos = (flags >> 1) & 0x03
                    topic, pos = _read_str(body, 0)
                    duplicate = False
                    if qos > 0:
                        (pid,) = struct.unpack_from(">H", body, pos)
                        pos += 2
                        if qos == 1:
                            ack = packet(PUBACK, 0, struct.pack(">H", pid))
                        else:  # exactly-once receiver: dedup until PUBREL
                            duplicate = pid in self._qos2_inbound
                            self._qos2_inbound.add(pid)
                            ack = packet(PUBREC, 0, struct.pack(">H", pid))
                        with self._wlock:
                            self._sock.sendall(ack)
                    if self._level >= 5:
                        pos = _skip_props(body, pos)
                    if self._on_message and not duplicate:
                        self._on_message(topic, body[pos:])
                elif ptype == PUBACK:
                    (pid,) = struct.unpack_from(">H", body, 0)
                    ev = self._acks.pop(pid, None)
                    if ev:
                        ev.set()
                elif ptype == PUBREC:
                    (pid,) = struct.unpack_from(">H", body, 0)
                    pair = self._qos2_acks.get(pid)
                    if pair:
                        pair[0].set()
                    with self._wlock:
                        self._sock.sendall(
                            packet(PUBREL, 0x02, struct.pack(">H", pid)))
                elif ptype == PUBREL:
                    (pid,) = struct.unpack_from(">H", body, 0)
                    self._qos2_inbound.discard(pid)
                    with self._wlock:
                        self._sock.sendall(
                            packet(PUBCOMP, 0, struct.pack(">H", pid)))
                elif ptype == PUBCOMP:
                    (pid,) = struct.unpack_from(">H", body, 0)
                    pair = self._qos2_acks.pop(pid, None)
                    if pair:
                        pair[1].set()
                elif ptype == SUBACK:
                    pos = 2  # packet id
                    if self._level >= 5:
                        pos = _skip_props(body, pos)
                    self._suback_codes = list(body[pos:])
                    self._suback.set()
                elif ptype == PINGRESP:
                    with self._ping_cv:
                        self._ping_rcvd += 1
                        self._ping_cv.notify_all()
        except (ConnectionError, OSError):
            pass

    def publish(self, topic: str, payload: bytes, qos: int = 0,
                retain: bool = False, timeout: float = 10.0) -> None:
        """QoS 0: fire and forget.  QoS 1: blocks until PUBACK.  QoS 2:
        blocks through the full PUBREC→PUBREL→PUBCOMP handshake (the
        reader thread sends the PUBREL on PUBREC arrival)."""
        pid, ev, pair = 0, None, None
        if qos > 0:
            with self._wlock:
                self._next_pid = self._next_pid % 65535 + 1
                pid = self._next_pid
            if qos == 1:
                ev = threading.Event()
                self._acks[pid] = ev
            else:
                pair = (threading.Event(), threading.Event())
                self._qos2_acks[pid] = pair
        with self._wlock:
            self._sock.sendall(publish_packet(topic, payload, qos, retain,
                                              pid, self._level))
        if ev is not None and not ev.wait(timeout):
            raise TimeoutError(f"no PUBACK for packet {pid}")
        if pair is not None:
            if not pair[0].wait(timeout):
                raise TimeoutError(f"no PUBREC for packet {pid}")
            if not pair[1].wait(timeout):
                raise TimeoutError(f"no PUBCOMP for packet {pid}")

    def publish_many(self, items, qos: int = 0) -> int:
        """Pipeline a batch of QoS-0 publishes in ONE socket write.

        The federated fleet driver (iotml.gateway) pushes a tick's
        worth of per-car publishes per front; a sendall per message
        would syscall 100k times per tick.  QoS 0 only: higher QoS
        needs per-packet ids and ack tracking, which defeats the
        point of the batch."""
        if qos != 0:
            raise ValueError("publish_many is QoS 0 only")
        buf = b"".join(publish_packet(topic, payload, 0, False, 0,
                                      self._level)
                       for topic, payload in items)
        with self._wlock:
            self._sock.sendall(buf)
        return len(items)

    def subscribe(self, filter_: str, qos: int = 0,
                  timeout: float = 10.0) -> None:
        with self._wlock:
            self._next_pid = self._next_pid % 65535 + 1
            pid = self._next_pid
        self._suback.clear()
        with self._wlock:
            self._sock.sendall(subscribe_packet(pid, [(filter_, qos)],
                                                self._level))
        if not self._suback.wait(timeout):
            raise TimeoutError("no SUBACK")
        codes = getattr(self, "_suback_codes", [])
        if any(c >= 0x80 for c in codes):
            raise ValueError(
                f"server rejected subscription {filter_!r} (SUBACK {codes})")

    def ping(self, timeout: float = 10.0) -> None:
        """PINGREQ/PINGRESP round-trip.  Because the server processes each
        connection's packets in order, a returned ping guarantees every
        prior qos-0 publish on this connection has been fully fanned out —
        the deterministic quiesce barrier the scenario runner uses.

        Sequence-paired: this waits for the response to ITS OWN request
        (PINGRESP N answers PINGREQ N on an ordered connection), so a late
        response to an earlier abandoned request — e.g. a keepalive ping
        that timed out on a loaded box — can never satisfy the barrier
        early."""
        with self._ping_lock:
            with self._ping_cv:
                self._ping_sent += 1
                target = self._ping_sent
            with self._wlock:
                self._sock.sendall(packet(PINGREQ, 0, b""))
            deadline = time.monotonic() + timeout
            with self._ping_cv:
                while self._ping_rcvd < target:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._ping_cv.wait(remaining):
                        if self._ping_rcvd >= target:
                            break
                        raise TimeoutError("no PINGRESP")

    def disconnect(self) -> None:
        self._closed.set()
        try:
            with self._wlock:
                self._sock.sendall(packet(DISCONNECT, 0, b""))
            self._sock.close()
        except OSError:
            pass

    def drop(self) -> None:
        """Abort the network connection WITHOUT a DISCONNECT packet — the
        abnormal-disconnect path (the broker publishes our will).
        shutdown() first: close() alone would not send the FIN while the
        reader thread is blocked in recv (the blocked syscall holds the
        file description open)."""
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
