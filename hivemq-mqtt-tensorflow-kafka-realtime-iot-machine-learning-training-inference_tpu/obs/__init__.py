from .metrics import (Registry, Counter, Gauge, Histogram, default_registry,  # noqa: F401
                      start_http_server)
from .tb import ScalarLogger, JaxProfiler  # noqa: F401
from .profile import trace, annotate, maybe_trace, trace_files  # noqa: F401
from . import tracing  # noqa: F401  (record-level trace context + spans)
