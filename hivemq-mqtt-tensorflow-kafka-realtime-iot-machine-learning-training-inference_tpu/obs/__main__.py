"""``python -m iotml.obs`` — observability CLI.

    python -m iotml.obs trace SPANS.jsonl [--json] [--top N]
                              [--min-stages N] [--require-e2e]
                              [--require-cross-process N] [--show-trace]
    python -m iotml.obs fleet [--endpoints MANIFEST] [--port 9200]
                              [--bootstrap HOST:PORT] [--once]
                              [--min-processes N]
    python -m iotml.obs tsdb query EXPR --bootstrap HOST:PORT
                              [--time-ms T | --start-ms A --end-ms B
                               [--step-ms S]] [--json]
    python -m iotml.obs tsdb slo-status --bootstrap HOST:PORT [--json]
    python -m iotml.obs tsdb canary-report --bootstrap HOST:PORT
                              [--window 5m] [--json]
    python -m iotml.obs tsdb drill [--seed N] [--records N] [--json]

``trace`` summarizes a span log written by `iotml.obs.tracing`
(``IOTML_TRACE=1 IOTML_TRACE_PATH=spans.jsonl``) into a per-stage
latency breakdown and flags the bottleneck stage — the question the
reference stack's external Prometheus view cannot answer: *which stage
ate the budget between the sensor reading and its anomaly score?*
A FLEET run appends every process's spans to one log (`proc` field);
``--require-cross-process N`` asserts a closed e2e trace really
crossed the wire through N processes and ``--show-trace`` prints that
journey (stages, offset ranges, which process ran what).

``fleet`` is the metrics federation collector (ISSUE 13): scrape every
endpoint in the manifest (processes auto-join it via
``IOTML_OBS_ENDPOINTS`` when they serve /metrics), serve ONE merged
/metrics + /healthz with ``process=`` labels and ``iotml_cluster_*``
rollups, and snapshot fleet state into the compacted
``_IOTML_METRICS`` changelog.

``tsdb`` is the telemetry-plane surface (ISSUE 17): ``query`` evaluates
a PromQL-shaped expression (instant or range) against the log-native
``_IOTML_TSDB`` history over the Kafka wire, ``slo-status`` shows the
burn-rate gauges + latest ``_IOTML_ALERTS`` state per SLO,
``canary-report`` reconstructs the synthetic-probe outcome counters and
e2e latency quantiles from the TSDB, and ``drill`` runs the live
alert-burn drill (fire → /healthz → resolve; exit status is the
verdict — CI runs exactly this).

``--min-stages`` / ``--require-e2e`` / ``--min-processes`` turn the
summaries into assertions (exit 1 on violation) for CI smoke runs.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def _percentile(sorted_vals: List[int], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return float(sorted_vals[idx])


def load_spans(path: str):
    """Parse a span log: returns (stages, e2e) aggregation dicts."""
    stages, e2e, _traces = load_spans_traces(path)
    return stages, e2e


def load_spans_traces(path: str):
    """Parse a span log with per-trace reconstruction: returns
    (stages, e2e, traces) where traces maps trace id → {spans:
    [(start_us, stage, dur_us, proc)], e2e: [(closer, dur_us, proc)],
    batches: [batch docs], procs: set} — the cross-process view a
    fleet run appends into ONE log (O_APPEND lines from every
    process, disambiguated by the `proc` field)."""
    stages: Dict[str, List[int]] = {}
    e2e: Dict[str, List[int]] = {}
    traces: Dict[str, dict] = {}

    def tr(tid):
        t = traces.get(tid)
        if t is None:
            t = traces[tid] = {"spans": [], "e2e": [], "batches": [],
                               "procs": set()}
        return t

    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line of a live run: skip
            kind = doc.get("kind")
            proc = doc.get("proc", "?")
            if kind == "span":
                stages.setdefault(doc["stage"], []).append(int(doc["dur_us"]))
                t = tr(doc.get("trace", "?"))
                t["spans"].append((int(doc.get("start_us", 0)),
                                   doc["stage"], int(doc["dur_us"]),
                                   proc))
                t["procs"].add(proc)
            elif kind == "e2e":
                e2e.setdefault(doc["closer"], []).append(int(doc["dur_us"]))
                t = tr(doc.get("trace", "?"))
                t["e2e"].append((doc["closer"], int(doc["dur_us"]),
                                 proc))
                t["procs"].add(proc)
            elif kind == "batch":
                t = tr(doc.get("trace", "?"))
                t["batches"].append(doc)
                t["procs"].add(proc)
    return stages, e2e, traces


def best_cross_process_trace(traces: Dict[str, dict]):
    """(trace_id, trace) spanning the most processes — closed e2e
    traces preferred, then span count; None when the log has none."""
    best = None
    for tid, t in traces.items():
        key = (len(t["procs"]), 1 if t["e2e"] else 0, len(t["spans"]))
        if best is None or key > best[0]:
            best = (key, tid, t)
    if best is None:
        return None, None
    return best[1], best[2]


def print_trace(tid: str, t: dict) -> None:
    """One trace's cross-process breakdown, stages in birth-relative
    order with the process that ran each."""
    procs = sorted(t["procs"])
    print(f"\ntrace {tid} across {len(procs)} process(es): "
          f"{', '.join(procs)}")
    for start_us, stage, dur_us, proc in sorted(t["spans"]):
        print(f"  +{start_us / 1000.0:9.3f} ms  {stage:<18} "
              f"{dur_us / 1000.0:9.3f} ms  [{proc}]")
    for doc in sorted(t["batches"],
                      key=lambda d: (d.get("topic", ""),
                                     d.get("first_offset", -1))):
        print(f"      batch {doc.get('topic')}:{doc.get('partition')}"
              f" offsets {doc.get('first_offset')}-"
              f"{doc.get('last_offset')} n={doc.get('n')} "
              f"stage={doc.get('stage')} [{doc.get('proc')}]")
    for closer, dur_us, proc in t["e2e"]:
        print(f"  e2e ingest->{closer}: {dur_us / 1000.0:.3f} ms "
              f"[{proc}]")


def summarize(stages: Dict[str, List[int]], e2e: Dict[str, List[int]]) -> dict:
    rows = []
    for stage, durs in stages.items():
        durs = sorted(durs)
        rows.append({
            "stage": stage,
            "count": len(durs),
            "mean_ms": sum(durs) / len(durs) / 1000.0,
            "p50_ms": _percentile(durs, 0.50) / 1000.0,
            "p95_ms": _percentile(durs, 0.95) / 1000.0,
            "max_ms": durs[-1] / 1000.0,
            "total_ms": sum(durs) / 1000.0,
        })
    # attribution by total time: the bottleneck is where the stream's
    # aggregate latency budget went, not one unlucky record's max
    rows.sort(key=lambda r: -r["total_ms"])
    bottleneck = rows[0]["stage"] if rows else None
    grand = sum(r["total_ms"] for r in rows) or 1.0
    for r in rows:
        r["share"] = r["total_ms"] / grand
    e2e_rows = {closer: {
        "count": len(durs),
        "mean_ms": sum(durs) / len(durs) / 1000.0,
        "p95_ms": _percentile(sorted(durs), 0.95) / 1000.0,
        "max_ms": max(durs) / 1000.0,
    } for closer, durs in e2e.items()}
    return {"stages": rows, "e2e": e2e_rows, "bottleneck": bottleneck}


def print_table(summary: dict) -> None:
    rows = summary["stages"]
    if not rows:
        print("no spans found")
        return
    hdr = f"{'stage':<16} {'count':>8} {'mean_ms':>10} {'p50_ms':>10} " \
          f"{'p95_ms':>10} {'max_ms':>10} {'total_ms':>11} {'share':>7}"
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        print(f"{r['stage']:<16} {r['count']:>8} {r['mean_ms']:>10.3f} "
              f"{r['p50_ms']:>10.3f} {r['p95_ms']:>10.3f} "
              f"{r['max_ms']:>10.3f} {r['total_ms']:>11.3f} "
              f"{r['share']:>6.1%}")
    for closer, r in sorted(summary["e2e"].items()):
        print(f"\ne2e ingest->{closer}: {r['count']} records, "
              f"mean {r['mean_ms']:.3f} ms, p95 {r['p95_ms']:.3f} ms, "
              f"max {r['max_ms']:.3f} ms")
    if summary["bottleneck"]:
        b = rows[0]
        print(f"\nbottleneck: {b['stage']} "
              f"({b['share']:.0%} of aggregate stage time)")


def cmd_trace(args) -> int:
    try:
        stages, e2e, traces = load_spans_traces(args.path)
    except OSError as e:
        print(f"cannot read span log: {e}", file=sys.stderr)
        return 2
    if args.top:
        # keep the N slowest stages by total time (post-aggregation cap)
        keep = sorted(stages, key=lambda s: -sum(stages[s]))[: args.top]
        stages = {s: stages[s] for s in keep}
    summary = summarize(stages, e2e)
    if args.json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print_table(summary)
    failures = []
    if args.min_stages and len(summary["stages"]) < args.min_stages:
        failures.append(f"expected >= {args.min_stages} distinct stages, "
                        f"saw {len(summary['stages'])}: "
                        f"{sorted(s['stage'] for s in summary['stages'])}")
    if args.require_e2e:
        closed = sum(r["count"] for r in summary["e2e"].values())
        nonzero = any(r["max_ms"] > 0 for r in summary["e2e"].values())
        if not closed or not nonzero:
            failures.append("expected closed e2e spans with nonzero latency")
    if args.show_trace or args.require_cross_process:
        tid, t = best_cross_process_trace(traces)
        if not args.json and tid is not None and args.show_trace:
            print_trace(tid, t)
        if args.require_cross_process:
            # the fleet assertion: at least one CLOSED trace whose
            # stages were recorded by >= N distinct processes — proof
            # the context really crossed the wire (ISSUE 13)
            ok = any(len(tr["procs"]) >= args.require_cross_process
                     and tr["e2e"]
                     for tr in traces.values())
            if not ok:
                have = max((len(tr["procs"]) for tr in traces.values()
                            if tr["e2e"]), default=0)
                failures.append(
                    f"expected a closed e2e trace spanning >= "
                    f"{args.require_cross_process} processes; best "
                    f"closed trace spans {have}")
    for f in failures:
        print(f"TRACE CHECK FAILED: {f}", file=sys.stderr)
    return 1 if failures else 0


def cmd_fleet(args) -> int:
    """Run (or one-shot) the metrics federation collector."""
    from .federate import FleetCollector, FleetServer, load_manifest

    endpoints = None
    if args.endpoints:
        endpoints = load_manifest(args.endpoints)
        if not endpoints and args.once:
            print(f"no endpoints in manifest {args.endpoints!r}",
                  file=sys.stderr)
            return 2
    collector = FleetCollector(
        endpoints=None if args.follow_manifest else endpoints,
        manifest=args.endpoints)
    broker = None
    if args.bootstrap:
        from ..stream.kafka_wire import KafkaWireBroker

        try:
            broker = KafkaWireBroker(args.bootstrap,
                                     client_id="iotml-obs-fleet")
        except OSError as e:
            print(f"cannot reach broker {args.bootstrap!r}: {e}",
                  file=sys.stderr)
            if args.once:
                return 2
    if args.once:
        snaps = collector.collect()
        if broker is not None:
            collector.snapshot_changelog(broker, snaps)
        hz = collector.healthz(snaps)
        if args.json:
            print(json.dumps(hz, indent=2, sort_keys=True))
        else:
            print(collector.render(snaps), end="")
            print(f"# fleet: {hz['up_count']}/{hz['process_count']} "
                  f"processes up, status={hz['status']}",
                  file=sys.stderr)
        if args.min_processes and hz["up_count"] < args.min_processes:
            print(f"FLEET CHECK FAILED: {hz['up_count']} processes up, "
                  f"expected >= {args.min_processes}", file=sys.stderr)
            return 1
        return 0
    # with a broker attached the long-running server is the full
    # telemetry plane: scrapes append TSDB history and the burn-rate
    # SLO engine (rules from config: IOTML_SLO_*) evaluates beside it
    appender = engine = sup = None
    if broker is not None:
        from ..config import load_config, slo_rules
        from ..supervise.supervisor import Supervisor
        from . import slo as _slo
        from . import tsdb as _tsdb

        cfg, _ = load_config([])
        appender = _tsdb.TsdbAppender(broker,
                                      chunk_ms=cfg.slo.tsdb_chunk_ms)
        engine = _slo.SloEngine(broker, slo_rules(cfg.slo),
                                interval_s=cfg.slo.interval_s)
        sup = Supervisor(name="obs-fleet-supervisor")
        sup.add_loop("slo-engine", engine.loop)
        sup.start()
    srv = FleetServer(collector, port=args.port,
                      interval_s=args.interval, broker=broker,
                      tsdb=appender).start()
    print(f"fleet metrics on :{srv.port}/metrics (+ /healthz), "
          f"scraping every {args.interval}s"
          + ("; TSDB + SLO engine attached" if appender else "")
          + "; ctrl-c to stop")
    try:
        import time as _time

        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        if sup is not None:
            sup.stop()
        srv.stop()
    return 0


def cmd_dlq(args) -> int:
    """Peek a dead-letter topic: decode the JSON envelopes the
    streamproc DLQ writes and show what poisoned the pipeline."""
    from ..stream.broker import OffsetOutOfRangeError
    from ..stream.kafka_wire import KafkaWireBroker
    from ..streamproc.dlq import DLQ_SUFFIX, decode_envelope

    topic = args.topic if args.topic.endswith(DLQ_SUFFIX) \
        else args.topic + DLQ_SUFFIX
    try:
        client = KafkaWireBroker(args.bootstrap, client_id="iotml-dlq-peek")
    except OSError as e:
        print(f"cannot reach broker {args.bootstrap!r}: {e}",
              file=sys.stderr)
        return 2
    try:
        try:
            parts = client.topic(topic).partitions
        except KeyError:
            print(f"no dead letters: topic {topic!r} does not exist")
            return 0
        rows = []
        for p in range(parts):
            off = client.begin_offset(topic, p)
            end = client.end_offset(topic, p)
            resets = 0
            while off < end and len(rows) < args.limit:
                try:
                    msgs = client.fetch(topic, p, off, max_messages=256)
                except OffsetOutOfRangeError as e:  # raced a retention trim
                    # bounded, like the consumer's auto-reset: a broker
                    # reporting earliest=0 (real Kafka sends hwm -1 on
                    # this error) must not spin this CLI forever
                    resets += 1
                    if resets > 3:
                        break
                    off = max(e.earliest, client.begin_offset(topic, p))
                    continue
                resets = 0
                if not msgs:
                    break
                for m in msgs:
                    off = m.offset + 1
                    try:
                        rows.append(decode_envelope(m.value))
                    except (ValueError, KeyError, TypeError):
                        rows.append({"source": topic, "partition": p,
                                     "offset": m.offset,
                                     "error": "unparseable DLQ envelope",
                                     "raw": m.value})
                    if len(rows) >= args.limit:
                        break
    finally:
        client.close()
    if args.json:
        for doc in rows:
            doc = dict(doc)
            doc["raw"] = doc.get("raw", b"")[:256].decode(errors="replace")
            print(json.dumps(doc, sort_keys=True))
        return 0
    if not rows:
        print(f"{topic}: empty")
        return 0
    print(f"{topic}: showing {len(rows)} dead letter(s)")
    for doc in rows:
        raw = doc.get("raw", b"")[:80]
        print(f"  {doc.get('source')}:{doc.get('partition')}"
              f"@{doc.get('offset')} [{doc.get('task') or '-'}] "
              f"{doc.get('error')}"
              + (f" trace={doc['trace']}" if doc.get("trace") else ""))
        print(f"    raw[:80]: {raw!r}")
    return 0


def _tsdb_client(bootstrap: str):
    from ..stream.kafka_wire import KafkaWireBroker

    try:
        return KafkaWireBroker(bootstrap, client_id="iotml-obs-tsdb")
    except OSError as e:
        print(f"cannot reach broker {bootstrap!r}: {e}", file=sys.stderr)
        return None


def cmd_tsdb(args) -> int:
    """The log-native TSDB surface: query / slo-status / canary-report
    over the wire, or the live alert-burn drill in-process."""
    from . import tsdb as _tsdb

    if args.tsdb_cmd == "drill":
        from .drill import drill_alert_burn

        rep = drill_alert_burn(seed=args.seed, records=args.records)
        if args.json:
            print(json.dumps(rep.to_dict(), indent=2, sort_keys=True,
                             default=str))
        else:
            for line in rep.lines():
                print(line)
        return 0 if rep.ok else 1

    client = _tsdb_client(args.bootstrap)
    if client is None:
        return 2
    try:
        series = _tsdb.read_series(client)
        if args.tsdb_cmd == "query":
            try:
                if args.start_ms is not None and args.end_ms is not None:
                    result = _tsdb.query(series, args.expr,
                                         start_ms=args.start_ms,
                                         end_ms=args.end_ms,
                                         step_ms=args.step_ms)
                else:
                    result = _tsdb.query(series, args.expr,
                                         at_ms=args.time_ms)
            except ValueError as e:
                print(f"bad query: {e}", file=sys.stderr)
                return 2
            if args.json:
                print(json.dumps(result, indent=2, sort_keys=True))
            else:
                if not result:
                    print("empty result")
                for r in result:
                    labels = ",".join(f"{k}={v}" for k, v in
                                      sorted(r["labels"].items()))
                    if "values" in r:
                        pts = " ".join(f"{t}:{v:.6g}"
                                       for t, v in r["values"])
                        print(f"{{{labels}}} {pts}")
                    else:
                        print(f"{{{labels}}} {r['value']:.6g}")
            return 0
        if args.tsdb_cmd == "slo-status":
            from . import slo as _slo

            alerts = _slo.read_alerts(client)
            burns = _tsdb.instant(series, "iotml_slo_burn_rate")
            doc = {"alerts": alerts,
                   "burn_rates": [
                       {"slo": r["labels"].get("slo", ""),
                        "window": r["labels"].get("window", ""),
                        "process": r["labels"].get("process", ""),
                        "burn": r["value"]} for r in burns]}
            if args.json:
                print(json.dumps(doc, indent=2, sort_keys=True))
            else:
                if not burns and not alerts:
                    print("no SLO telemetry in the TSDB")
                for r in doc["burn_rates"]:
                    print(f"burn {r['slo']}/{r['window']}: "
                          f"{r['burn']:.2f} [{r['process']}]")
                for name, a in sorted(alerts.items()):
                    state = "FIRING" if a.get("firing") else "resolved"
                    print(f"alert {name}: {state} "
                          f"(last {a.get('action')} window="
                          f"{a.get('window') or '-'}) {a.get('message')}")
            # a firing alert makes the status check itself fail — the
            # CI/cron shape (like fleet --min-processes)
            return 1 if any(a.get("firing")
                            for a in alerts.values()) else 0
        # canary-report: probe outcomes + e2e quantiles from the TSDB
        window_ms = _tsdb.parse_duration_ms(args.window)
        outcomes = {}
        for r in _tsdb.increase(series, "iotml_canary_probes_total",
                                window_ms=window_ms):
            out = r["labels"].get("outcome", "?")
            outcomes[out] = outcomes.get(out, 0.0) + r["value"]
        quantiles = {}
        for q in (0.5, 0.95, 0.99):
            res = _tsdb.histogram_quantile(
                series, q, "iotml_canary_e2e_seconds",
                window_ms=window_ms)
            if res:
                quantiles[f"p{int(q * 100)}"] = max(
                    r["value"] for r in res)
        doc = {"window": args.window, "outcomes": outcomes,
               "e2e_quantiles_s": quantiles}
        if args.json:
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            if not outcomes:
                print(f"no canary probes in the last {args.window}")
            else:
                sent = outcomes.get("sent", 0.0)
                ok = outcomes.get("ok", 0.0)
                lost = outcomes.get("lost", 0.0)
                print(f"canaries last {args.window}: sent={sent:.0f} "
                      f"ok={ok:.0f} lost={lost:.0f}"
                      + (f" delivery={ok / sent:.4f}" if sent else ""))
                for name, v in sorted(quantiles.items()):
                    print(f"  e2e {name}: {v * 1000:.1f} ms")
        return 0
    finally:
        client.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.obs",
        description="observability tools (span-log analysis, DLQ peek)")
    sub = ap.add_subparsers(dest="cmd")
    tp = sub.add_parser(
        "trace", help="summarize a JSONL span log into a per-stage "
                      "latency breakdown and flag the bottleneck stage")
    tp.add_argument("path", help="span log written under IOTML_TRACE_PATH")
    tp.add_argument("--json", action="store_true",
                    help="machine-readable summary")
    tp.add_argument("--top", type=int, default=0,
                    help="only the N slowest stages by total time")
    tp.add_argument("--min-stages", type=int, default=0,
                    help="exit 1 unless at least N distinct stages appear")
    tp.add_argument("--require-e2e", action="store_true",
                    help="exit 1 unless closed e2e spans with nonzero "
                         "latency appear")
    tp.add_argument("--require-cross-process", type=int, default=0,
                    metavar="N",
                    help="exit 1 unless a closed e2e trace spans >= N "
                         "distinct processes (fleet smoke assertion)")
    tp.add_argument("--show-trace", action="store_true",
                    help="print the breakdown of the trace spanning "
                         "the most processes")
    fp = sub.add_parser(
        "fleet", help="metrics federation: scrape every fleet "
                      "process's /metrics and serve one merged view")
    fp.add_argument("--endpoints", default=None,
                    help="endpoints manifest (JSON [{name, address}]); "
                         "defaults to $IOTML_OBS_ENDPOINTS")
    fp.add_argument("--port", type=int, default=9200,
                    help="merged /metrics + /healthz port")
    fp.add_argument("--interval", type=float, default=2.0,
                    help="scrape cadence seconds")
    fp.add_argument("--bootstrap", default=None,
                    help="broker address: snapshot fleet state into "
                         "the compacted _IOTML_METRICS changelog")
    fp.add_argument("--once", action="store_true",
                    help="scrape once, print the merged exposition, "
                         "exit (CI smoke mode)")
    fp.add_argument("--json", action="store_true",
                    help="with --once: print the merged healthz JSON")
    fp.add_argument("--min-processes", type=int, default=0,
                    help="with --once: exit 1 unless >= N processes "
                         "answered their scrape")
    fp.add_argument("--follow-manifest", action="store_true",
                    help="re-read the manifest every pass (processes "
                         "may join after the collector starts)")
    tsp = sub.add_parser(
        "tsdb", help="log-native TSDB: query the _IOTML_TSDB history, "
                     "show SLO/canary status, or run the alert-burn "
                     "drill")
    tsub = tsp.add_subparsers(dest="tsdb_cmd")
    qp = tsub.add_parser(
        "query", help="evaluate a PromQL-shaped expression (selector, "
                      "rate(), increase(), histogram_quantile())")
    qp.add_argument("expr", help='e.g. \'rate(iotml_records_scored_'
                                 'total[5m])\'')
    qp.add_argument("--bootstrap", required=True,
                    help="broker address host:port")
    qp.add_argument("--time-ms", type=int, default=None,
                    help="instant evaluation timestamp (default: newest)")
    qp.add_argument("--start-ms", type=int, default=None)
    qp.add_argument("--end-ms", type=int, default=None,
                    help="with --start-ms: range query")
    qp.add_argument("--step-ms", type=int, default=15_000)
    qp.add_argument("--json", action="store_true")
    sp = tsub.add_parser(
        "slo-status", help="burn-rate gauges + latest _IOTML_ALERTS "
                           "state per SLO (exit 1 while any alert "
                           "fires)")
    sp.add_argument("--bootstrap", required=True)
    sp.add_argument("--json", action="store_true")
    cp = tsub.add_parser(
        "canary-report", help="synthetic-probe outcomes and e2e "
                              "latency quantiles from the TSDB")
    cp.add_argument("--bootstrap", required=True)
    cp.add_argument("--window", default="5m",
                    help="trailing window (e.g. 30s, 5m, 1h)")
    cp.add_argument("--json", action="store_true")
    drp = tsub.add_parser(
        "drill", help="live alert-burn drill: degrade the bridge, "
                      "prove the fast burn pair fires + resolves "
                      "(exit status is the verdict)")
    drp.add_argument("--seed", type=int, default=7)
    drp.add_argument("--records", type=int, default=600)
    drp.add_argument("--json", action="store_true")
    dp = sub.add_parser(
        "dlq", help="peek a dead-letter topic's poisoned-record "
                    "envelopes over the Kafka wire protocol")
    dp.add_argument("--bootstrap", required=True,
                    help="broker address host:port[,host:port...]")
    dp.add_argument("--topic", default="sensor-data",
                    help="source topic (the _DLQ suffix is appended "
                         "unless already present)")
    dp.add_argument("--limit", type=int, default=20,
                    help="show at most N dead letters")
    dp.add_argument("--json", action="store_true",
                    help="one JSON envelope per line")
    args = ap.parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "fleet":
        import os

        if args.endpoints is None:
            args.endpoints = os.environ.get("IOTML_OBS_ENDPOINTS")
        return cmd_fleet(args)
    if args.cmd == "dlq":
        return cmd_dlq(args)
    if args.cmd == "tsdb":
        if not getattr(args, "tsdb_cmd", None):
            tsp.print_help()
            return 2
        return cmd_tsdb(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
