"""``python -m iotml.obs`` — observability CLI.

    python -m iotml.obs trace SPANS.jsonl [--json] [--top N]
                              [--min-stages N] [--require-e2e]

``trace`` summarizes a span log written by `iotml.obs.tracing`
(``IOTML_TRACE=1 IOTML_TRACE_PATH=spans.jsonl``) into a per-stage
latency breakdown and flags the bottleneck stage — the question the
reference stack's external Prometheus view cannot answer: *which stage
ate the budget between the sensor reading and its anomaly score?*

``--min-stages`` / ``--require-e2e`` turn the summary into an
assertion (exit 1 on violation) for CI smoke runs.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def _percentile(sorted_vals: List[int], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return float(sorted_vals[idx])


def load_spans(path: str):
    """Parse a span log: returns (stages, e2e) aggregation dicts."""
    stages: Dict[str, List[int]] = {}
    e2e: Dict[str, List[int]] = {}
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line of a live run: skip
            if doc.get("kind") == "span":
                stages.setdefault(doc["stage"], []).append(int(doc["dur_us"]))
            elif doc.get("kind") == "e2e":
                e2e.setdefault(doc["closer"], []).append(int(doc["dur_us"]))
    return stages, e2e


def summarize(stages: Dict[str, List[int]], e2e: Dict[str, List[int]]) -> dict:
    rows = []
    for stage, durs in stages.items():
        durs = sorted(durs)
        rows.append({
            "stage": stage,
            "count": len(durs),
            "mean_ms": sum(durs) / len(durs) / 1000.0,
            "p50_ms": _percentile(durs, 0.50) / 1000.0,
            "p95_ms": _percentile(durs, 0.95) / 1000.0,
            "max_ms": durs[-1] / 1000.0,
            "total_ms": sum(durs) / 1000.0,
        })
    # attribution by total time: the bottleneck is where the stream's
    # aggregate latency budget went, not one unlucky record's max
    rows.sort(key=lambda r: -r["total_ms"])
    bottleneck = rows[0]["stage"] if rows else None
    grand = sum(r["total_ms"] for r in rows) or 1.0
    for r in rows:
        r["share"] = r["total_ms"] / grand
    e2e_rows = {closer: {
        "count": len(durs),
        "mean_ms": sum(durs) / len(durs) / 1000.0,
        "p95_ms": _percentile(sorted(durs), 0.95) / 1000.0,
        "max_ms": max(durs) / 1000.0,
    } for closer, durs in e2e.items()}
    return {"stages": rows, "e2e": e2e_rows, "bottleneck": bottleneck}


def print_table(summary: dict) -> None:
    rows = summary["stages"]
    if not rows:
        print("no spans found")
        return
    hdr = f"{'stage':<16} {'count':>8} {'mean_ms':>10} {'p50_ms':>10} " \
          f"{'p95_ms':>10} {'max_ms':>10} {'total_ms':>11} {'share':>7}"
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        print(f"{r['stage']:<16} {r['count']:>8} {r['mean_ms']:>10.3f} "
              f"{r['p50_ms']:>10.3f} {r['p95_ms']:>10.3f} "
              f"{r['max_ms']:>10.3f} {r['total_ms']:>11.3f} "
              f"{r['share']:>6.1%}")
    for closer, r in sorted(summary["e2e"].items()):
        print(f"\ne2e ingest->{closer}: {r['count']} records, "
              f"mean {r['mean_ms']:.3f} ms, p95 {r['p95_ms']:.3f} ms, "
              f"max {r['max_ms']:.3f} ms")
    if summary["bottleneck"]:
        b = rows[0]
        print(f"\nbottleneck: {b['stage']} "
              f"({b['share']:.0%} of aggregate stage time)")


def cmd_trace(args) -> int:
    try:
        stages, e2e = load_spans(args.path)
    except OSError as e:
        print(f"cannot read span log: {e}", file=sys.stderr)
        return 2
    if args.top:
        # keep the N slowest stages by total time (post-aggregation cap)
        keep = sorted(stages, key=lambda s: -sum(stages[s]))[: args.top]
        stages = {s: stages[s] for s in keep}
    summary = summarize(stages, e2e)
    if args.json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print_table(summary)
    failures = []
    if args.min_stages and len(summary["stages"]) < args.min_stages:
        failures.append(f"expected >= {args.min_stages} distinct stages, "
                        f"saw {len(summary['stages'])}: "
                        f"{sorted(s['stage'] for s in summary['stages'])}")
    if args.require_e2e:
        closed = sum(r["count"] for r in summary["e2e"].values())
        nonzero = any(r["max_ms"] > 0 for r in summary["e2e"].values())
        if not closed or not nonzero:
            failures.append("expected closed e2e spans with nonzero latency")
    for f in failures:
        print(f"TRACE CHECK FAILED: {f}", file=sys.stderr)
    return 1 if failures else 0


def cmd_dlq(args) -> int:
    """Peek a dead-letter topic: decode the JSON envelopes the
    streamproc DLQ writes and show what poisoned the pipeline."""
    from ..stream.broker import OffsetOutOfRangeError
    from ..stream.kafka_wire import KafkaWireBroker
    from ..streamproc.dlq import DLQ_SUFFIX, decode_envelope

    topic = args.topic if args.topic.endswith(DLQ_SUFFIX) \
        else args.topic + DLQ_SUFFIX
    try:
        client = KafkaWireBroker(args.bootstrap, client_id="iotml-dlq-peek")
    except OSError as e:
        print(f"cannot reach broker {args.bootstrap!r}: {e}",
              file=sys.stderr)
        return 2
    try:
        try:
            parts = client.topic(topic).partitions
        except KeyError:
            print(f"no dead letters: topic {topic!r} does not exist")
            return 0
        rows = []
        for p in range(parts):
            off = client.begin_offset(topic, p)
            end = client.end_offset(topic, p)
            resets = 0
            while off < end and len(rows) < args.limit:
                try:
                    msgs = client.fetch(topic, p, off, max_messages=256)
                except OffsetOutOfRangeError as e:  # raced a retention trim
                    # bounded, like the consumer's auto-reset: a broker
                    # reporting earliest=0 (real Kafka sends hwm -1 on
                    # this error) must not spin this CLI forever
                    resets += 1
                    if resets > 3:
                        break
                    off = max(e.earliest, client.begin_offset(topic, p))
                    continue
                resets = 0
                if not msgs:
                    break
                for m in msgs:
                    off = m.offset + 1
                    try:
                        rows.append(decode_envelope(m.value))
                    except (ValueError, KeyError, TypeError):
                        rows.append({"source": topic, "partition": p,
                                     "offset": m.offset,
                                     "error": "unparseable DLQ envelope",
                                     "raw": m.value})
                    if len(rows) >= args.limit:
                        break
    finally:
        client.close()
    if args.json:
        for doc in rows:
            doc = dict(doc)
            doc["raw"] = doc.get("raw", b"")[:256].decode(errors="replace")
            print(json.dumps(doc, sort_keys=True))
        return 0
    if not rows:
        print(f"{topic}: empty")
        return 0
    print(f"{topic}: showing {len(rows)} dead letter(s)")
    for doc in rows:
        raw = doc.get("raw", b"")[:80]
        print(f"  {doc.get('source')}:{doc.get('partition')}"
              f"@{doc.get('offset')} [{doc.get('task') or '-'}] "
              f"{doc.get('error')}"
              + (f" trace={doc['trace']}" if doc.get("trace") else ""))
        print(f"    raw[:80]: {raw!r}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.obs",
        description="observability tools (span-log analysis, DLQ peek)")
    sub = ap.add_subparsers(dest="cmd")
    tp = sub.add_parser(
        "trace", help="summarize a JSONL span log into a per-stage "
                      "latency breakdown and flag the bottleneck stage")
    tp.add_argument("path", help="span log written under IOTML_TRACE_PATH")
    tp.add_argument("--json", action="store_true",
                    help="machine-readable summary")
    tp.add_argument("--top", type=int, default=0,
                    help="only the N slowest stages by total time")
    tp.add_argument("--min-stages", type=int, default=0,
                    help="exit 1 unless at least N distinct stages appear")
    tp.add_argument("--require-e2e", action="store_true",
                    help="exit 1 unless closed e2e spans with nonzero "
                         "latency appear")
    dp = sub.add_parser(
        "dlq", help="peek a dead-letter topic's poisoned-record "
                    "envelopes over the Kafka wire protocol")
    dp.add_argument("--bootstrap", required=True,
                    help="broker address host:port[,host:port...]")
    dp.add_argument("--topic", default="sensor-data",
                    help="source topic (the _DLQ suffix is appended "
                         "unless already present)")
    dp.add_argument("--limit", type=int, default=20,
                    help="show at most N dead letters")
    dp.add_argument("--json", action="store_true",
                    help="one JSON envelope per line")
    args = ap.parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "dlq":
        return cmd_dlq(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
