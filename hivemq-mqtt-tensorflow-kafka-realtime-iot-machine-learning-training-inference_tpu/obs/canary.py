"""Synthetic e2e canaries: black-box truth beside the white-box gauges.

The white-box telemetry (PR 2/12/16) measures the pipeline from the
inside; it cannot notice the failure mode where every stage looks
healthy but records stop flowing end to end.  `CanaryProbe` closes that
gap the way a hosted monitoring stack's synthetic checks would — except
through the REAL path, not a parallel one:

    probe ──publish──> MQTT broker ──bridge──> sensor-data
          ──JsonToAvro──> SENSOR_DATA_S_AVRO ──probe's own consumer

Each probe is a schema-valid sensor record for a RESERVED car id
(``canary-<seq>``), published to ``vehicles/sensor/data/canary-<seq>``
so the production topic-mapping forwards it like any fleet record.  The
record key on the ML input topic is the MQTT topic (bridge contract),
so the probe's consumer — its own group, its own cursor — recognises
its records by the ``/canary-`` key marker and closes the loop:

- **e2e latency** comes from the PR 2 trace span when tracing is armed
  (the context is born inside ``MqttBroker.publish``; its ``wall0_ns``
  rides the record headers through bridge and converter), with the
  probe's own send clock as the untraced fallback;
- **delivery success** is the fraction of probes observed before the
  timeout — probes never acked are counted ``lost``.

Both feed the SLO engine through the TSDB (`iotml_canary_e2e_seconds`
buckets drive the latency SLO; `iotml_canary_probes_total{outcome=}`
drives the availability ratio).  Scoring pipelines exclude the reserved
ids (`SensorBatches(exclude_key_marker=CANARY_KEY_MARKER)`), so canary
records NEVER reach user-facing prediction topics.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..core.schema import CAR_SCHEMA
from ..gen.simulator import FleetGenerator, FleetScenario
from ..stream.consumer import StreamConsumer
from . import metrics as _metrics
from . import tracing

#: reserved car-id namespace — generator car ids are
#: ``electric-vehicle-<n>``, so the prefix cannot collide with fleet
#: traffic, and every stage that must skip canaries keys off it
CANARY_CAR_PREFIX = "canary-"
#: the marker as it appears in bridged record KEYS (key = MQTT topic,
#: ``vehicles/sensor/data/<car-id>``)
CANARY_KEY_MARKER = b"/" + CANARY_CAR_PREFIX.encode()

canary_probes = _metrics.default_registry.counter(
    "iotml_canary_probes_total",
    "synthetic canary probes by outcome (sent | ok | lost)")
canary_e2e = _metrics.default_registry.histogram(
    "iotml_canary_e2e_seconds",
    "measured MQTT->bridge->converter end-to-end latency of canary "
    "probes (trace-span wall clock when tracing is armed)",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0))
canary_inflight = _metrics.default_registry.gauge(
    "iotml_canary_inflight", "canary probes sent and not yet observed")


def is_canary_key(key: Optional[bytes]) -> bool:
    return bool(key) and CANARY_KEY_MARKER in key


class CanaryProbe:
    """Inject tracer records through the real ingest path and measure
    their round trip.  Drive it either as a supervised unit
    (``sup.add_loop("canary", probe.loop)``) or manually
    (``probe_once()`` + ``observe()``) from a drill."""

    def __init__(self, mqtt, stream, topic: str = "SENSOR_DATA_S_AVRO",
                 interval_s: float = 1.0, timeout_s: float = 5.0,
                 group: str = "canary-probe", qos: int = 1,
                 observe_interval_s: float = 0.02):
        self.mqtt = mqtt
        self.stream = stream
        self.topic = topic
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.observe_interval_s = observe_interval_s
        self.qos = qos
        self._seq = 0
        self._lock = threading.Lock()
        #: seq -> wall-clock ns at publish
        self._inflight: Dict[int, int] = {}
        self._sent = self._ok = self._lost = 0
        self._trace_sourced = 0
        self._last_e2e_s: Optional[float] = None
        # one simulated car supplies schema-valid sensor physics; the
        # probe only swaps the identity for the reserved namespace
        self._gen = FleetGenerator(FleetScenario(num_cars=1, seed=1097))
        self._car0 = np.array([0])
        # the probe tails NEW records only: canaries published before
        # this probe existed belong to a previous incarnation
        n_parts = stream.topic(topic).partitions \
            if topic in stream.topics() else 1
        stream.create_topic(topic, partitions=n_parts)
        self.consumer = StreamConsumer(
            stream,
            [f"{topic}:{p}:{stream.end_offset(topic, p)}"
             for p in range(n_parts)],
            group=group, eof=True)

    # ------------------------------------------------------------ send
    def probe_once(self) -> int:
        """Publish one canary record; returns its sequence number."""
        cols = self._gen.step_columns(self._car0)
        rec = self._gen.row_record(cols, 0, CAR_SCHEMA)
        rec["failure_occurred"] = "false"  # canaries are healthy cars
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._inflight[seq] = time.time_ns()  # wallclock-ok: e2e latency is a wall-clock quantity (trace wall0 domain)
            self._sent += 1
            canary_inflight.set(len(self._inflight))
        car = f"{CANARY_CAR_PREFIX}{seq:08d}"
        canary_probes.inc(outcome="sent")
        self.mqtt.publish(f"vehicles/sensor/data/{car}",
                          json.dumps(rec).encode(), qos=self.qos)
        return seq

    # --------------------------------------------------------- observe
    def observe(self) -> int:
        """Drain the ML input topic for canary arrivals; time out the
        stragglers.  Returns how many probes completed this pass."""
        done = 0
        while True:
            msgs = self.consumer.poll(1024)
            if not msgs:
                break
            now_ns = time.time_ns()  # wallclock-ok: e2e span close
            for m in msgs:
                if not is_canary_key(m.key):
                    continue
                seq = self._seq_of(m.key)
                with self._lock:
                    sent_ns = self._inflight.pop(seq, None) \
                        if seq is not None else None
                if sent_ns is None:
                    continue  # duplicate delivery or foreign probe
                # trace-span truth when the header survived the hops;
                # the probe's own clock otherwise
                ctx = tracing.from_headers(m.headers) if m.headers \
                    else None
                t0_ns = ctx.wall0_ns if ctx is not None else sent_ns
                e2e_s = max(now_ns - t0_ns, 0) / 1e9
                canary_e2e.observe(e2e_s)
                canary_probes.inc(outcome="ok")
                with self._lock:
                    self._ok += 1
                    if ctx is not None:
                        self._trace_sourced += 1
                    self._last_e2e_s = e2e_s
                done += 1
        self.consumer.commit()
        self._expire()
        canary_inflight.set(len(self._inflight))
        return done

    def _seq_of(self, key: bytes) -> Optional[int]:
        i = key.rfind(CANARY_KEY_MARKER)
        try:
            return int(key[i + len(CANARY_KEY_MARKER):])
        except ValueError:
            return None

    def _expire(self) -> None:
        deadline = time.time_ns() - int(self.timeout_s * 1e9)
        # wallclock-ok: probe timeout compares publish wall stamps
        with self._lock:
            dead = [s for s, t in self._inflight.items() if t < deadline]
            for s in dead:
                del self._inflight[s]
                self._lost += 1
        for _ in dead:
            canary_probes.inc(outcome="lost")

    # ------------------------------------------------------------ unit
    def loop(self, unit) -> None:
        """SupervisedUnit body: probe on ``interval_s``, observe on the
        much tighter ``observe_interval_s`` — the observe pass is what
        closes the e2e clock, so ITS cadence (not the probe interval)
        sets the floor of the measured latency."""
        next_probe = time.monotonic()
        while not unit.should_stop():
            try:
                if time.monotonic() >= next_probe:
                    self.probe_once()
                    next_probe = time.monotonic() + self.interval_s
                self.observe()
            except (ConnectionError, OSError):
                time.sleep(0.05)  # broker failover: next pass retries
                continue
            unit.heartbeat()
            time.sleep(self.observe_interval_s)

    # ---------------------------------------------------------- report
    def report(self) -> dict:
        with self._lock:
            return {"sent": self._sent, "ok": self._ok,
                    "lost": self._lost,
                    "trace_sourced": self._trace_sourced,
                    "inflight": len(self._inflight),
                    "last_e2e_s": self._last_e2e_s}


def default_slo_rules(window_scale: float = 1.0) -> List[dict]:
    """The canary-backed SLO pair every deployment starts from — e2e
    latency from the probe histogram, delivery from the outcome
    counters (config.SloConfig materialises these)."""
    return [
        {"name": "canary-e2e-latency", "objective": 0.99,
         "indicator": {"kind": "latency",
                       "metric": "iotml_canary_e2e_seconds",
                       "threshold_s": 0.25},
         "window_scale": window_scale},
        {"name": "canary-delivery", "objective": 0.999,
         "indicator": {"kind": "ratio",
                       "bad": "iotml_canary_probes_total",
                       "total": "iotml_canary_probes_total",
                       "bad_matchers": {"outcome": "lost"},
                       "total_matchers": {"outcome": "sent"}},
         "window_scale": window_scale},
    ]
