"""Platform control center — the C3 / HiveMQ Control Center stand-in.

The reference operates through two web UIs: Confluent Control Center
(topics/consumers/KSQL at `infrastructure/confluent/README.md:226-241`) and
the HiveMQ Control Center (`infrastructure/hivemq/README.md:21`).  This is
the one-page equivalent for the native platform: a live overview of topics
(offsets/partitions), KSQL queries, connectors, MQTT sessions, and the
metric snapshot — as JSON for machines and a self-refreshing HTML page for
humans.

  GET /            HTML overview (auto-refreshes)
  GET /api/status  the same data as JSON
"""

from __future__ import annotations

import html
import json

from ..utils.rest import RestServer


class ControlCenter(RestServer):
    """Status UI over a running `cli.up.Platform` (or compatible parts)."""

    def __init__(self, platform, host: str = "127.0.0.1", port: int = 0):
        super().__init__(host, port, name="iotml-control-center")
        self.platform = platform
        self.route("GET", r"/api/status", self._status)
        self.route("GET", r"/", self._page)

    # ------------------------------------------------------------- data
    def snapshot(self) -> dict:
        p = self.platform
        topics = []
        for name in p.broker.topics():
            spec = p.broker.topic(name)
            end = sum(p.broker.end_offset(name, q)
                      for q in range(spec.partitions))
            begin = sum(p.broker.begin_offset(name, q)
                        for q in range(spec.partitions))
            topics.append({"name": name, "partitions": spec.partitions,
                           "messages": end - begin, "end_offset": end})
        queries = [q.describe() for q in p.sql.queries.values()]
        streams = [m.describe() for m in p.sql.sources.values()]
        connectors = sorted(p.connect._configs)
        from .metrics import default_registry
        metrics = default_registry.collect()
        out = {
            "endpoints": p.endpoints(),
            "topics": topics,
            "ksql": {"queries": queries, "sources": streams},
            "connectors": connectors,
            "mqtt_sessions": p.mqtt_broker.session_count(),
            "metrics": metrics,
        }
        # car-health digital twin (the predictive-maintenance surface):
        # active alerts by car, latest state from the twin sink
        twin = getattr(p, "car_twin", None)
        if twin is not None:
            # snapshot the dict first: the ConnectServer driver thread
            # upserts concurrently and a live generator would raise
            # "dict changed size during iteration"
            docs = list(twin.docs.values())
            alerts = sorted(
                (d for d in docs if d.get("state") == "ALERT"),
                key=lambda d: d.get("t", 0), reverse=True)
            out["car_health"] = {
                "cars_tracked": len(docs),
                "active_alerts": alerts[:100],
                "n_active": len(alerts),
            }
        return out

    def _status(self, m, body):
        return 200, self.snapshot()

    # ------------------------------------------------------------- page
    def _page(self, m, body):
        s = self.snapshot()
        rows = "".join(
            f"<tr><td>{html.escape(t['name'])}</td>"
            f"<td>{t['partitions']}</td><td>{t['messages']}</td></tr>"
            for t in s["topics"])
        qrows = "".join(
            f"<tr><td>{html.escape(q['id'])}</td>"
            f"<td>{html.escape(q['sink'])}</td></tr>"
            for q in s["ksql"]["queries"])
        mrows = "".join(
            f"<tr><td>{html.escape(k)}</td><td>{v:g}</td></tr>"
            for k, v in sorted(s["metrics"].items()))
        ch = s.get("car_health")
        chsec = ""
        if ch is not None:
            arows = "".join(
                f"<tr><td>{html.escape(str(d.get('car')))}</td>"
                f"<td>{html.escape(str(d.get('source', '')))}</td>"
                f"<td>{d.get('ema', 0):g}</td></tr>"
                for d in ch["active_alerts"])
            chsec = (f"<h2>Car health — {ch['n_active']} active alert(s), "
                     f"{ch['cars_tracked']} cars tracked</h2>"
                     f"<table><tr><th>car</th><th>source</th><th>ema</th>"
                     f"</tr>{arows}</table>")
        page = f"""<!doctype html><html><head><title>iotml control center</title>
<meta http-equiv="refresh" content="3">
<style>body{{font-family:monospace;margin:2em}}table{{border-collapse:collapse;margin:1em 0}}
td,th{{border:1px solid #999;padding:2px 8px;text-align:left}}h2{{margin-bottom:0}}</style>
</head><body>
<h1>iotml control center</h1>
<p>MQTT sessions: {s['mqtt_sessions']} · connectors: {len(s['connectors'])}
· endpoints: {html.escape(json.dumps(s['endpoints']))}</p>
{chsec}
<h2>Topics</h2><table><tr><th>topic</th><th>partitions</th><th>messages</th></tr>{rows}</table>
<h2>KSQL queries</h2><table><tr><th>id</th><th>sink</th></tr>{qrows}</table>
<h2>Metrics</h2><table>{mrows}</table>
</body></html>"""
        return 200, page.encode(), "text/html; charset=utf-8"
