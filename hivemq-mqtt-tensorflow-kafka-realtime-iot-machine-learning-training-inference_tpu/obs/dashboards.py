"""Grafana dashboard generation from the metric registry.

The reference ships two hand-maintained dashboard JSONs — `hivemq.json`
(35 panels: Kafka-extension write rates, MQTT sessions/packets, overload
protection, JVM) and `devsim.json` (24 panels: connect/publish success-fail
counts and rates) — mounted as labeled ConfigMaps (reference
`infrastructure/hivemq/setup.sh:18-19`, `test-generator/run_scenario.sh:8-10`).
Hand-maintained dashboards drift; here panels are *generated* from the
metric registry, so every metric the framework exports gets a panel and the
dashboard is always in sync with the code.  Output is Grafana dashboard
schema JSON (schemaVersion 16, like the reference's files) accepted by the
dashboard-provisioning ConfigMap flow.
"""

from __future__ import annotations

import json
from typing import List, Optional

from . import metrics as m


def _panel(panel_id: int, title: str, expr: str, x: int, y: int,
           unit: str = "short", w: int = 12, h: int = 8) -> dict:
    return {
        "id": panel_id,
        "type": "graph",
        "title": title,
        "datasource": "Prometheus",
        "gridPos": {"h": h, "w": w, "x": x, "y": y},
        "targets": [{"expr": expr, "refId": "A", "legendFormat": title}],
        "yaxes": [{"format": unit, "show": True},
                  {"format": "short", "show": False}],
        "lines": True,
        "fill": 1,
        "linewidth": 2,
        "nullPointMode": "null",
    }


def _expr_for(metric) -> tuple:
    """(PromQL expr, unit) appropriate to the metric type."""
    if isinstance(metric, m.Histogram):
        return (f"rate({metric.name}_sum[1m]) / rate({metric.name}_count[1m])",
                "s")
    if isinstance(metric, m.Gauge):
        return metric.name, "short"
    return f"rate({metric.name}[1m])", "ops"


#: metric-name prefixes → dashboard family, mirroring the reference's split
#: into `hivemq.json` (broker-side) and `devsim.json` (load-generator)
#: plus the ML family the reference never charted.
FAMILIES = {
    "broker": ("mqtt_", "kafka_extension_"),
    "devsim": ("agent_",),
    "ml": ("iotml_",),
    # the continuous-learning loop + per-car failure detection: trainer
    # rounds/loss, scorer hot-swaps, live verdict quality, car alerts
    "live": ("live_", "car_health_"),
}


def generate_dashboard(title: str = "iotml",
                       registry: Optional[m.Registry] = None,
                       uid: Optional[str] = None,
                       family: Optional[str] = None) -> dict:
    """One dashboard with a panel per registered metric (2 per row).

    `family` restricts to one of FAMILIES' prefix groups — the reference's
    per-concern dashboards; None charts everything."""
    registry = registry or m.default_registry
    panels: List[dict] = []
    names = sorted(registry._metrics) if hasattr(registry, "_metrics") else []
    if family is not None:
        prefixes = FAMILIES[family]
        names = [n for n in names if n.startswith(prefixes)]
    for i, name in enumerate(names):
        metric = registry._metrics[name]
        expr, unit = _expr_for(metric)
        panels.append(_panel(
            panel_id=i + 1,
            title=getattr(metric, "help", "") or name,
            expr=expr,
            x=(i % 2) * 12,
            y=(i // 2) * 8,
            unit=unit))
    return {
        "uid": uid or title,
        "title": title,
        "schemaVersion": 16,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "panels": panels,
        "templating": {"list": []},
        "annotations": {"list": []},
    }


def dashboard_configmap(name: str = "iotml-dashboard",
                        title: str = "iotml",
                        registry: Optional[m.Registry] = None) -> str:
    """The reference's deployment shape: dashboard JSONs wrapped in a
    grafana_dashboard-labeled ConfigMap (setup.sh:18-19) — one JSON per
    family (the reference ships hivemq.json + devsim.json; the ml family
    is the training/serving view it never had) plus the everything view."""
    data = {f"{title}.json": json.dumps(generate_dashboard(title, registry))}
    for fam in FAMILIES:
        dash = generate_dashboard(f"{title}-{fam}", registry, family=fam)
        if dash["panels"]:
            data[f"{title}-{fam}.json"] = json.dumps(dash)
    doc = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": name,
                     "labels": {"grafana_dashboard": "1"}},
        "data": data,
    }
    return json.dumps(doc, indent=2)


if __name__ == "__main__":
    # emit the dashboard ConfigMap for `kubectl apply -f -` (deploy/README.md).
    # Metric families register on component construction; build one of each
    # so the emitted dashboards cover every family the platform can export.
    from ..mqtt.bridge import KafkaBridge
    from ..mqtt.broker import MqttBroker
    from ..mqtt.scenario import EVALUATION_SCENARIO, ScenarioRunner
    from ..stream.broker import Broker

    _mqtt = MqttBroker()
    KafkaBridge(_mqtt, Broker(), partitions=1)
    ScenarioRunner(EVALUATION_SCENARIO, _mqtt)
    print(dashboard_configmap())
