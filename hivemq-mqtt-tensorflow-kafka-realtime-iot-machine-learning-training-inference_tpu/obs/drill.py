"""Live alert-burn drill — the telemetry plane under real fire.

The supervise drills prove the *pipeline* heals; this drill proves the
*alerting loop around it* actually pages and un-pages.  It runs the full
chain on real threads:

    fleet + canaries → MQTT → bridge → JsonToAvro → supervised scorer
                         │
      /metrics (per-process HTTP) ← canary + pipeline registries
                         │ federated scrape (FleetServer)
                  _IOTML_TSDB (log-native history)
                         │ burn-rate evaluation (SloEngine, supervised)
            _IOTML_ALERTS + /healthz + iotml_slo_burn_rate{...}

in three phases:

- **A healthy**: fleet traffic and canaries flow through the
  undisturbed path; the SLO engine must stay quiet (no alert on a
  healthy system).
- **B degraded**: the ``alert-burn`` chaos schedule arms a SUSTAINED
  mqtt delivery delay far past the canary latency threshold; the FAST
  burn-rate pair must fire within the drill budget, the transition must
  land on the compacted ``_IOTML_ALERTS`` changelog, and both the
  process and fleet ``/healthz`` must flip to degraded with the alert
  attached.
- **C recovery**: faults disarm; once the degraded samples age out of
  the burn windows the alert must RESOLVE on its own and ``/healthz``
  must clear — un-paging is part of the contract.

Alongside the alert lifecycle the drill asserts the telemetry plane's
hygiene invariants: canary e2e latency is trace-span-sourced (the PR 2
headers survived the hops), canary records NEVER reach the user-facing
prediction topic, and the TSDB topic stays bounded under forced
compaction (per-(series, window) keying actually converges).

Run via ``python -m iotml.obs tsdb drill`` or
``python -m iotml.chaos run --scenario alert-burn`` (exit status is the
verdict — CI runs exactly this).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import List, Optional

from ..chaos import faults, scenarios
from ..chaos.runner import IN_TOPIC, PRED_TOPIC, Invariant
from ..supervise.drill import CARS_PER_TICK, DrillReport, _wait
from ..supervise.supervisor import Supervisor
from . import canary as _canary
from . import federate as _federate
from . import metrics as _metrics
from . import slo as _slo
from . import tracing
from . import tsdb as _tsdb

#: drill-scale burn windows: (name, short_ms, long_ms, threshold) — the
#: SRE-workbook fast/slow pairs compressed to seconds so the full
#: fire→resolve lifecycle fits in a CI drill.  The LOGIC is identical
#: to production DEFAULT_WINDOWS; only the durations shrink.
#: threshold geometry: in a total outage the time to fire is
#: threshold x budget x long-window, so fast (8 x 9 s) must stay well
#: under slow (6 x 18 s) — otherwise the slow pair races the fast one
#: to the transition and the drill's "fast pair pages first" assertion
#: becomes a coin flip (the workbook's 14.4x1h vs 6x6h keeps the same
#: ordering).
DRILL_WINDOWS = (
    ("fast", 3_000, 9_000, 8.0),
    ("slow", 6_000, 18_000, 6.0),
)

#: the drill's SLO rules: e2e latency through the real path (threshold
#: far above a healthy in-process hop, far below the injected delay)
#: and probe delivery.
DRILL_SLO_RULES = (
    {"name": "canary-e2e-latency", "objective": 0.97,
     "indicator": {"kind": "latency",
                   "metric": "iotml_canary_e2e_seconds",
                   "threshold_s": 0.1},
     "windows": DRILL_WINDOWS},
    {"name": "canary-delivery", "objective": 0.97,
     "indicator": {"kind": "ratio",
                   "bad": "iotml_canary_probes_total",
                   "total": "iotml_canary_probes_total",
                   "bad_matchers": {"outcome": "lost"},
                   "total_matchers": {"outcome": "sent"}},
     "windows": DRILL_WINDOWS},
)


def _http_json(url: str, timeout_s: float = 2.0) -> Optional[dict]:
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.loads(r.read())
    except (OSError, ValueError):
        return None


def _make_firewalled_scorer(stream, consumer):
    """The supervise-drill scorer with the canary firewall armed: the
    batcher drops reserved-id records BEFORE the model (they must never
    reach the user-facing prediction topic)."""
    import numpy as np

    from ..data.dataset import SensorBatches
    from ..models.autoencoder import CAR_AUTOENCODER
    from ..serve.scorer import StreamScorer
    from ..stream.producer import OutputSequence
    from ..train.loop import Trainer

    trainer = Trainer(CAR_AUTOENCODER)
    trainer._ensure_state(np.zeros((100, 18), np.float32))
    batches = SensorBatches(consumer, batch_size=100,
                            exclude_key_marker=_canary.CANARY_KEY_MARKER)
    out = OutputSequence(stream, PRED_TOPIC, partition=0)
    return StreamScorer(CAR_AUTOENCODER, trainer.state.params, batches,
                        out)


def drill_alert_burn(seed: int = 7, records: int = 600,
                     events=None,
                     healthy_s: float = 10.0,
                     degraded_budget_s: float = 12.0,
                     resolve_budget_s: float = 30.0) -> DrillReport:
    """The full fire→resolve alert lifecycle against the live threaded
    telemetry plane (module docstring has the phase map)."""
    import tempfile

    from ..core.schema import CAR_SCHEMA
    from ..gen.simulator import FleetGenerator, FleetScenario
    from ..mqtt.bridge import KafkaBridge
    from ..mqtt.broker import MqttBroker
    from ..stream.broker import Broker
    from ..stream.consumer import StreamConsumer
    from ..streamproc.tasks import JsonToAvro

    if events is None:
        events = scenarios.build("alert-burn", seed=seed,
                                 records=records).events

    # tracing ON: canary e2e must come from real trace spans
    prev = (tracing.ENABLED, tracing._SAMPLE, tracing._PATH)
    tracing.flush()
    tracing.configure(enabled=True, sample=1.0)
    tracing.reset()

    mqtt = MqttBroker()
    # durable broker: the TSDB-boundedness invariant needs REAL segment
    # compaction, which only the store-backed log implements
    tmp = tempfile.TemporaryDirectory(prefix="iotml-obs-drill-")
    stream = Broker(store_dir=tmp.name)
    KafkaBridge(mqtt, stream, partitions=2)
    task = JsonToAvro(stream, src="sensor-data", dst=IN_TOPIC,
                      partitions=2)
    parts = stream.topic(IN_TOPIC).partitions
    consumer = StreamConsumer(
        stream, [f"{IN_TOPIC}:{p}:0" for p in range(parts)],
        group="obs-drill-scorer")
    scorer = _make_firewalled_scorer(stream, consumer)

    # telemetry plane: per-process /metrics + fleet scrape into the
    # TSDB on a tight cadence (2 s chunks: forced compaction later
    # must still find multiple windows to converge on)
    srv = _metrics.start_http_server(port=0)
    proc_port = srv.server_address[1]
    proc_name = tracing.proc_name()
    appender = _tsdb.TsdbAppender(stream, chunk_ms=2_000)
    collector = _federate.FleetCollector()
    fleet = _federate.FleetServer(collector, port=0, interval_s=0.25,
                                  broker=stream, tsdb=appender).start()
    engine = _slo.SloEngine(stream, DRILL_SLO_RULES, interval_s=0.25)
    probe = _canary.CanaryProbe(mqtt, stream, topic=IN_TOPIC,
                                interval_s=0.15, timeout_s=3.0)

    def task_loop(unit):
        while not unit.should_stop():
            try:
                n = task.process_available()
            except ConnectionError:
                task.consumer.rewind_to_committed()
                time.sleep(0.02)
                continue
            unit.heartbeat()
            time.sleep(0.002 if n else 0.01)

    def scorer_loop(unit):
        consumer.rewind_to_committed()
        while not unit.should_stop():
            try:
                scorer.score_available()
            except ConnectionError:
                consumer.rewind_to_committed()
                time.sleep(0.02)
                continue
            unit.heartbeat()
            time.sleep(0.005)

    # fleet traffic rides beside the canaries for the whole drill (the
    # firewall invariant is only meaningful with real records flowing)
    gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK, seed=seed))
    pub_stop = threading.Event()
    published = {"n": 0}

    def publish_loop():
        ticks = max(1, -(-records // CARS_PER_TICK))
        for _ in range(ticks):
            cols = gen.step_columns()
            for i in range(len(cols["car"])):
                if pub_stop.is_set():
                    return
                rec = gen.row_record(cols, i, CAR_SCHEMA)
                rec["failure_occurred"] = str(cols["failure_occurred"][i])
                mqtt.publish(
                    f"vehicles/sensor/data/{gen.scenario.car_id(i)}",
                    json.dumps(rec).encode(), qos=1)
                published["n"] += 1
            if pub_stop.wait(0.25):
                return

    from ..supervise.registry import register_thread
    publisher = register_thread(threading.Thread(
        target=publish_loop, daemon=True, name="obs-drill-fleet"))

    sup = Supervisor(poll_interval_s=0.02, name="obs-drill-supervisor")
    sup.add_loop("ksql-task", task_loop, heartbeat_timeout_s=30.0)
    sup.add_loop("scorer", scorer_loop, heartbeat_timeout_s=30.0)
    sup.add_loop("slo-engine", engine.loop, heartbeat_timeout_s=30.0)
    sup.add_loop("canary", probe.loop, heartbeat_timeout_s=30.0)
    sup.start()
    publisher.start()

    fired_alert: Optional[dict] = None
    proc_hz_fire: Optional[dict] = None
    fleet_hz_fire: Optional[dict] = None
    t_fire_s: Optional[float] = None
    t_resolve_s: Optional[float] = None
    eng = None
    firing = {}
    healthy_clean = True
    try:
        # ---------------------------------------------------- A healthy
        deadline = time.monotonic() + healthy_s
        while time.monotonic() < deadline:
            if _slo.firing_alerts():
                healthy_clean = False
            time.sleep(0.05)

        # --------------------------------------------------- B degraded
        eng = faults.arm(faults.ChaosEngine(events))
        t_degraded = time.monotonic()
        latency_st = engine.states["canary-e2e-latency"]
        _wait(lambda: latency_st.firing and latency_st.window == "fast",
              degraded_budget_s)
        firing = {n: st for n, st in engine.states.items() if st.firing}
        if latency_st.firing:
            t_fire_s = time.monotonic() - t_degraded
            fired_alert = _slo.read_alerts(stream).get(
                "canary-e2e-latency")
            # the process healthz is instantaneous; the fleet healthz
            # lags one scrape — poll it up to a few intervals
            proc_hz_fire = _http_json(
                f"http://127.0.0.1:{proc_port}/healthz")
            _wait(lambda: ((_http_json(
                f"http://127.0.0.1:{fleet.port}/healthz") or {})
                .get("processes", {}).get(proc_name, {})
                .get("status")) == "degraded", 3.0, interval_s=0.1)
            fleet_hz_fire = _http_json(
                f"http://127.0.0.1:{fleet.port}/healthz")

        # --------------------------------------------------- C recovery
        faults.disarm()
        t_recover = time.monotonic()
        _wait(lambda: not any(st.firing
                              for st in engine.states.values()),
              resolve_budget_s, interval_s=0.1)
        if firing and not any(st.firing for st in engine.states.values()):
            t_resolve_s = time.monotonic() - t_recover
        # quiesce: everything published has flowed through to the scorer
        pub_stop.set()
        publisher.join(timeout=10.0)
        _wait(lambda: task.consumer.at_end(), 20.0)
        _wait(lambda: consumer.at_end(), 20.0)
        # the canary unit is still looping: give it a beat to observe
        # (or expire) every probe still in flight
        _wait(lambda: probe.report()["inflight"] == 0, 6.0,
              interval_s=0.1)
    finally:
        pub_stop.set()
        sup.stop()
        faults.disarm()
        fleet.stop()
        srv.shutdown()
        srv.server_close()
        tracing.flush()
        tracing.configure(enabled=prev[0], sample=prev[1],
                          path=prev[2] if prev[2] else "")

    # ------------------------------------------------------- invariants
    rep = probe.report()
    alerts_log = _slo.read_alerts(stream)
    final_firing = _slo.firing_alerts()

    # canary firewall: every canary delivered to the input topic must
    # be filtered before the model — rows scored == non-canary rows
    delivered = sum(stream.end_offset(IN_TOPIC, p) for p in range(parts))
    canary_delivered = 0
    for p in range(parts):
        off = 0
        while off < stream.end_offset(IN_TOPIC, p):
            batch = stream.fetch(IN_TOPIC, p, off, 4096)
            if not batch:
                break
            for m in batch:
                off = m.offset + 1
                if _canary.is_canary_key(m.key):
                    canary_delivered += 1

    # TSDB boundedness: after forced compaction the topic holds exactly
    # one record per live (series, window) chunk key.  Seal the active
    # segment first — compaction only rewrites sealed segments.
    pre_count = _count_records(stream, _tsdb.TSDB_TOPIC)
    distinct_keys = len(_read_tsdb_keys(stream))
    stream.store.log_for(_tsdb.TSDB_TOPIC, 0).roll()
    stream.run_compaction(force=True)
    post_count = _count_records(stream, _tsdb.TSDB_TOPIC)

    proc_hz_alerts = (proc_hz_fire or {}).get("alerts") or {}
    fleet_hz_status = ((fleet_hz_fire or {}).get("processes", {})
                       .get(proc_name, {}).get("status"))
    invariants: List[Invariant] = [
        Invariant("healthy_phase_quiet", healthy_clean,
                  "no alert fired on the undisturbed pipeline"),
        Invariant("alert_fired_fast_within_budget",
                  t_fire_s is not None and t_fire_s <= degraded_budget_s,
                  "fast burn pair fired "
                  + (f"{t_fire_s:.2f}s" if t_fire_s is not None
                     else "NEVER")
                  + f" after degradation (budget {degraded_budget_s}s)"),
        Invariant("alert_in_changelog",
                  fired_alert is not None
                  and fired_alert.get("action") == "fire"
                  and fired_alert.get("window") == "fast",
                  f"_IOTML_ALERTS fire transition: {fired_alert}"),
        Invariant("alert_in_healthz",
                  bool(proc_hz_alerts) and fleet_hz_status == "degraded",
                  f"process /healthz alerts={sorted(proc_hz_alerts)}; "
                  f"fleet saw {proc_name}={fleet_hz_status}"),
        Invariant("alert_resolved",
                  t_resolve_s is not None and not final_firing,
                  "alert resolved "
                  + (f"{t_resolve_s:.2f}s" if t_resolve_s is not None
                     else "NEVER")
                  + " after recovery; still firing: "
                  f"{sorted(final_firing) or 'none'}"),
        Invariant("resolve_in_changelog",
                  bool(alerts_log) and all(
                      not doc.get("firing")
                      for doc in alerts_log.values()),
                  "_IOTML_ALERTS final states: "
                  + str({k: v.get("action")
                         for k, v in sorted(alerts_log.items())})),
        Invariant("canary_e2e_trace_sourced",
                  rep["ok"] > 0 and rep["trace_sourced"] > 0,
                  f"{rep['trace_sourced']}/{rep['ok']} observed canary "
                  f"round-trips carried a live trace span"),
        Invariant("zero_canaries_scored",
                  canary_delivered > 0
                  and scorer.scored == delivered - canary_delivered,
                  f"delivered={delivered} canaries={canary_delivered} "
                  f"scored={scorer.scored} (must equal non-canary "
                  f"deliveries)"),
        Invariant("tsdb_bounded_after_compaction",
                  0 < post_count == distinct_keys < pre_count,
                  f"TSDB records {pre_count} -> {post_count} after "
                  f"forced compaction ({distinct_keys} distinct chunk "
                  f"keys)"),
        Invariant("no_degraded_units", not sup.degraded(),
                  f"degraded units: {sup.degraded() or 'none'}"
                  + "".join(f"; {u.name}: {u.last_error}"
                            for u in sup.units()
                            if u.name in sup.degraded())),
    ]
    stream.close()
    tmp.cleanup()
    return DrillReport(
        drill="alert-burn", seed=seed, records=records,
        published=published["n"] + rep["sent"], scored=scorer.scored,
        restarts={u.name: u.restarts for u in sup.units()},
        slos={"time_to_fire_s": t_fire_s,
              "time_to_resolve_s": t_resolve_s,
              "canary_last_e2e_s": rep["last_e2e_s"]},
        invariants=invariants,
        injected=dict(sorted(eng.injected.items())) if eng is not None
        else {})


def _read_tsdb_keys(stream) -> set:
    keys = set()
    off = stream.begin_offset(_tsdb.TSDB_TOPIC, 0)
    end = stream.end_offset(_tsdb.TSDB_TOPIC, 0)
    while off < end:
        batch = stream.fetch(_tsdb.TSDB_TOPIC, 0, off, 4096)
        if not batch:
            break
        for m in batch:
            off = m.offset + 1
            if m.key is not None and m.value is not None:
                keys.add(m.key)
    return keys


def _count_records(stream, topic: str, partition: int = 0) -> int:
    """Actual retained records (offsets keep their gaps across a
    compaction pass, so end - begin over-counts)."""
    n = 0
    off = stream.begin_offset(topic, partition)
    end = stream.end_offset(topic, partition)
    while off < end:
        batch = stream.fetch(topic, partition, off, 4096)
        if not batch:
            break
        for m in batch:
            off = m.offset + 1
            n += 1
    return n
