"""Cluster metrics federation — one merged view of a fleet of processes.

The reference operates through ONE Prometheus that scrapes every pod
(SURVEY §5); our obs layer grew up per-process (PR 2) while PRs 6-11
made the system multi-process — shard servers, scorer/pump fleets, a
trainer, an MQTT front, supervised children — each serving its own
/metrics that nobody aggregates.  This module is the missing collector:

- an **endpoints manifest** (JSON, atomically rewritten) that every
  process publishes its metrics address into at startup (the file twin
  of the supervise ``Topology``: the supervisor publishes leadership,
  processes publish observability endpoints) — also mirrored into the
  in-process ``supervise.registry`` so a single-process deployment
  needs no file at all;
- a **FleetCollector** that scrapes every manifest endpoint, re-labels
  every sample with ``process=<name>`` (Prometheus federation shape)
  and synthesizes cluster-level series: ``iotml_cluster_up``,
  counter sums (records consumed/scored/trained fleet-wide), consumer-
  group lag rollups summed over partitions and processes, replica-lag
  and watermark-lag worst-of;
- a **FleetServer** (``python -m iotml.obs fleet``) serving the merged
  ``/metrics`` + ``/healthz`` on one port, scraping on a cadence;
- a compacted ``_IOTML_METRICS`` **changelog**: each scrape snapshots
  per-process fleet state keyed by process name, so dashboards replay
  cluster history from the log like everything else (latest-per-key
  compaction bounds it at ~one record per process).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import metrics as _metrics

#: the compacted fleet-state changelog (key = process name).  Like
#: CAR_TWIN (lint R12) this has ONE writer family: federation
#: collectors.
METRICS_TOPIC = "_IOTML_METRICS"

federation_scrapes = _metrics.default_registry.counter(
    "iotml_federation_scrapes_total",
    "endpoint scrapes performed by the federation collector")
federation_scrape_errors = _metrics.default_registry.counter(
    "iotml_federation_scrape_errors_total",
    "endpoint scrapes that failed (process down/unreachable)")
federation_snapshots = _metrics.default_registry.counter(
    "iotml_federation_snapshots_total",
    "fleet-state snapshots appended to the _IOTML_METRICS changelog")


# -------------------------------------------------- endpoints manifest
def manifest_path(env: Optional[dict] = None) -> Optional[str]:
    """The fleet's endpoints manifest path (IOTML_OBS_ENDPOINTS), None
    when federation is not configured for this process."""
    env = os.environ if env is None else env
    return env.get("IOTML_OBS_ENDPOINTS") or None


def load_manifest(path: str) -> List[dict]:
    """[{name, address}] from the manifest; [] when absent/torn (a
    half-written manifest must degrade to 'scrape nothing yet', never
    crash the collector)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return []
    out = []
    for e in doc if isinstance(doc, list) else []:
        if isinstance(e, dict) and e.get("name") and e.get("address"):
            out.append({"name": str(e["name"]),
                        "address": str(e["address"])})
    return out


def publish_endpoint(path: str, name: str, address: str) -> None:
    """Register (name, address) in the manifest — read-modify-write
    under an fcntl lock, atomic rename, replace-by-name (a restarted
    process re-publishes its new port under its old name).  Also
    mirrored into the in-process supervise registry so same-process
    collectors need no file."""
    register_local_endpoint(name, address)
    lock_path = path + ".lock"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    import fcntl

    with open(lock_path, "a+") as lk:
        fcntl.lockf(lk, fcntl.LOCK_EX)
        try:
            entries = [e for e in load_manifest(path)
                       if e["name"] != name]
            entries.append({"name": name, "address": address})
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(sorted(entries, key=lambda e: e["name"]), fh,
                          indent=2)
            os.replace(tmp, path)
        finally:
            fcntl.lockf(lk, fcntl.LOCK_UN)


#: in-process endpoint registry — the Topology-style cell for
#: single-process fleets (cli.up runs broker+scorer+trainer in one
#: process: one /metrics, but drills register logical roles too)
_local_endpoints: Dict[str, str] = {}
_local_lock = threading.Lock()


def register_local_endpoint(name: str, address: str) -> None:
    with _local_lock:
        _local_endpoints[name] = address


def local_endpoints() -> List[dict]:
    with _local_lock:
        return [{"name": n, "address": a}
                for n, a in sorted(_local_endpoints.items())]


# ------------------------------------------------ prometheus text parse
def parse_prom_text(text: str) -> Tuple[Dict[str, str], List[tuple]]:
    """Prometheus text exposition → ({family: type}, [(name, labels,
    value)]).  Tolerant: unparsable lines are skipped (a scrape must
    merge what it can, not die on one odd line)."""
    types: Dict[str, str] = {}
    samples: List[tuple] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        try:
            name, labels, value = _parse_sample(line)
        except ValueError:
            continue
        samples.append((name, labels, value))
    return types, samples


def _parse_sample(line: str) -> tuple:
    if "{" in line:
        name, rest = line.split("{", 1)
        # the value follows the LAST close brace; a `}` INSIDE a quoted
        # label value cannot be last (escape-aware label parsing below
        # rejects a truncated pair, so a mis-split fails loudly instead
        # of yielding a corrupt sample)
        lab_str, brace, val_str = rest.rpartition("}")
        if not brace:
            raise ValueError(line)  # `{` without `}` — truncated line
        labels = _parse_labels(lab_str)
    else:
        parts = line.split(None, 1)  # any whitespace run separates
        if len(parts) != 2:
            raise ValueError(line)
        name, val_str = parts
        labels = {}
    val_str = val_str.strip()
    if not val_str:
        raise ValueError(line)
    # optional trailing timestamp (Prometheus exposition): value is the
    # first field.  float() covers NaN / +Inf / -Inf spellings.
    return name.strip(), labels, float(val_str.split()[0])


#: exposition escape sequences (the render-side _escape_label_value
#: inverse): backslash, double-quote, line feed.  Unknown escapes pass
#: the escaped character through (OpenMetrics's lenient reading).
_LABEL_ESCAPES = {"n": "\n", "\\": "\\", '"': '"'}


def _parse_labels(lab_str: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    i, n = 0, len(lab_str)
    while i < n:
        eq = lab_str.find("=", i)
        if eq < 0:
            if lab_str[i:].strip(", \t"):
                raise ValueError(lab_str)  # trailing garbage, not a pair
            break
        key = lab_str[i:eq].strip().lstrip(",").strip()
        if not key:
            raise ValueError(lab_str)
        if eq + 1 >= n or lab_str[eq + 1] != '"':
            raise ValueError(lab_str)
        j = eq + 2
        out = []
        closed = False
        while j < n:
            c = lab_str[j]
            if c == "\\" and j + 1 < n:
                nxt = lab_str[j + 1]
                out.append(_LABEL_ESCAPES.get(nxt, nxt))
                j += 2
                continue
            if c == '"':
                closed = True
                break
            out.append(c)
            j += 1
        if not closed:
            # unterminated value: the line was truncated (or the value
            # sample-split above mis-fired on a `}` inside a quote) —
            # reject the whole sample rather than store a corrupt tail
            raise ValueError(lab_str)
        labels[key] = "".join(out)
        i = j + 1
    return labels


def _fmt(labels: Dict[str, str]) -> str:
    return _metrics._fmt_labels(labels)


# ----------------------------------------------------------- collector
class FleetCollector:
    """Scrape a fleet's /metrics endpoints and merge them.

    ``endpoints``: [{name, address}] (a loaded manifest), or None to
    re-read ``manifest_path()`` + the in-process registry every pass —
    the live mode, where processes may join after the collector."""

    def __init__(self, endpoints: Optional[List[dict]] = None,
                 manifest: Optional[str] = None, timeout_s: float = 3.0):
        self._static = endpoints
        self.manifest = manifest
        self.timeout_s = timeout_s
        self.snapshots: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def endpoints(self) -> List[dict]:
        if self._static is not None:
            return list(self._static)
        out = {e["name"]: e for e in local_endpoints()}
        if self.manifest:
            for e in load_manifest(self.manifest):
                out[e["name"]] = e  # manifest wins: it carries the port
        return [out[k] for k in sorted(out)]

    # ------------------------------------------------------------ scrape
    def _get(self, address: str, path: str) -> Optional[str]:
        import http.client

        host, _, port = address.rpartition(":")
        try:
            conn = http.client.HTTPConnection(host or "127.0.0.1",
                                              int(port),
                                              timeout=self.timeout_s)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                if resp.status != 200:
                    return None
                return resp.read().decode("utf-8", "replace")
            finally:
                conn.close()
        except (OSError, ValueError):
            return None

    def collect(self) -> Dict[str, dict]:
        """One scrape pass over every endpoint; returns (and stores)
        per-process snapshots {name: {up, address, types, samples,
        healthz, ts}}."""
        snaps: Dict[str, dict] = {}
        for e in self.endpoints():
            name, addr = e["name"], e["address"]
            federation_scrapes.inc()
            text = self._get(addr, "/metrics")
            snap = {"up": text is not None, "address": addr,
                    "types": {}, "samples": [], "healthz": None,
                    "ts": time.time()}  # wallclock-ok: snapshot stamp
            if text is None:
                federation_scrape_errors.inc()
            else:
                snap["types"], snap["samples"] = parse_prom_text(text)
                hz = self._get(addr, "/healthz")
                if hz:
                    try:
                        snap["healthz"] = json.loads(hz)
                    except ValueError:
                        pass
            snaps[name] = snap
        with self._lock:
            self.snapshots = snaps
        return snaps

    # ------------------------------------------------------------ render
    #: counter families summed fleet-wide into iotml_cluster_<family>
    SUM_FAMILIES = (
        "iotml_records_consumed_total", "iotml_records_scored_total",
        "iotml_records_trained_total", "iotml_raw_produce_records_total",
        "iotml_dlq_total", "iotml_online_updates_total",
        "iotml_trace_spans_dropped_total",
    )

    def render(self, snapshots: Optional[Dict[str, dict]] = None) -> str:
        """The merged exposition: every scraped sample re-labeled with
        ``process=<name>`` (Prometheus federation shape), then the
        synthesized ``iotml_cluster_*`` rollups."""
        if snapshots is None:
            with self._lock:
                snapshots = dict(self.snapshots)
        out: List[str] = []
        emitted_type: set = set()
        for name in sorted(snapshots):
            snap = snapshots[name]
            for fam, typ in sorted(snap["types"].items()):
                if fam not in emitted_type:
                    out.append(f"# TYPE {fam} {typ}")
                    emitted_type.add(fam)
            for mname, labels, value in snap["samples"]:
                labels = dict(labels)
                labels["process"] = name
                out.append(f"{mname}{_fmt(labels)} {value}")
        out.extend(self._rollups(snapshots))
        return "\n".join(out) + "\n"

    def _rollups(self, snapshots: Dict[str, dict]) -> List[str]:
        up = {n: s["up"] for n, s in snapshots.items()}
        lines = ["# TYPE iotml_cluster_up gauge"]
        for n in sorted(up):
            lines.append(f"iotml_cluster_up{_fmt({'process': n})} "
                         f"{1 if up[n] else 0}")
        lines.append("# TYPE iotml_cluster_processes gauge")
        lines.append(f"iotml_cluster_processes {sum(up.values())}")
        # counter sums: fleet-wide totals per family, with a process
        # breakdown already present above — these are the one-line
        # dashboard numbers
        sums: Dict[str, float] = {}
        lag: Dict[tuple, float] = {}       # (group, topic) → records
        replica_worst: Dict[str, float] = {}   # topic → records
        wm_worst: Dict[str, float] = {}        # stage → newest event ms
        isr_worst: Dict[str, float] = {}       # topic → min |ISR|
        qlag_worst: Dict[str, float] = {}      # topic → max hwm lag
        under_replicated = 0.0                 # fleet-wide sum
        for s in snapshots.values():
            for mname, labels, value in s["samples"]:
                if mname in self.SUM_FAMILIES:
                    sums[mname] = sums.get(mname, 0.0) + value
                elif mname == "iotml_consumer_lag_records":
                    key = (labels.get("group", ""),
                           labels.get("topic", ""))
                    lag[key] = lag.get(key, 0.0) + value
                elif mname == "iotml_replica_lag_records":
                    t = labels.get("topic", "")
                    replica_worst[t] = max(replica_worst.get(t, 0.0),
                                           value)
                elif mname == "iotml_isr_size":
                    # worst-of = the NARROWEST ISR across partitions
                    # and processes: the fleet's durability margin is
                    # its most under-replicated partition's
                    t = labels.get("topic", "")
                    cur = isr_worst.get(t)
                    isr_worst[t] = value if cur is None \
                        else min(cur, value)
                elif mname == "iotml_quorum_hwm_lag_records":
                    t = labels.get("topic", "")
                    qlag_worst[t] = max(qlag_worst.get(t, 0.0), value)
                elif mname == "iotml_under_replicated_partitions":
                    # each leader process reports its own partitions:
                    # the fleet total is the sum
                    under_replicated += value
                elif mname == "iotml_watermark_event_time_ms":
                    st = labels.get("stage", "")
                    # worst-of = the OLDEST frontier across processes:
                    # the fleet's e2e staleness is its slowest member's
                    cur = wm_worst.get(st)
                    wm_worst[st] = value if cur is None \
                        else min(cur, value)
        for fam in sorted(sums):
            cname = "iotml_cluster_" + fam[len("iotml_"):]
            lines.append(f"# TYPE {cname} counter")
            lines.append(f"{cname} {sums[fam]}")
        if lag:
            lines.append("# TYPE iotml_cluster_consumer_lag_records gauge")
            for (g, t) in sorted(lag):
                lines.append(
                    "iotml_cluster_consumer_lag_records"
                    f"{_fmt({'group': g, 'topic': t})} {lag[(g, t)]}")
        if replica_worst:
            lines.append(
                "# TYPE iotml_cluster_replica_lag_worst_records gauge")
            for t in sorted(replica_worst):
                lines.append(
                    "iotml_cluster_replica_lag_worst_records"
                    f"{_fmt({'topic': t})} {replica_worst[t]}")
        if isr_worst:
            lines.append("# TYPE iotml_cluster_isr_size_worst gauge")
            for t in sorted(isr_worst):
                lines.append("iotml_cluster_isr_size_worst"
                             f"{_fmt({'topic': t})} {isr_worst[t]}")
            lines.append(
                "# TYPE iotml_cluster_under_replicated_partitions gauge")
            lines.append("iotml_cluster_under_replicated_partitions "
                         f"{under_replicated}")
        if qlag_worst:
            lines.append(
                "# TYPE iotml_cluster_quorum_hwm_lag_worst_records gauge")
            for t in sorted(qlag_worst):
                lines.append(
                    "iotml_cluster_quorum_hwm_lag_worst_records"
                    f"{_fmt({'topic': t})} {qlag_worst[t]}")
        if wm_worst:
            now_ms = time.time() * 1000.0  # wallclock-ok: event domain
            lines.append(
                "# TYPE iotml_cluster_watermark_lag_worst_seconds gauge")
            for st in sorted(wm_worst):
                lag_s = max(now_ms - wm_worst[st], 0.0) / 1000.0
                lines.append(
                    "iotml_cluster_watermark_lag_worst_seconds"
                    f"{_fmt({'stage': st})} {round(lag_s, 3)}")
        return lines

    def healthz(self, snapshots: Optional[Dict[str, dict]] = None) -> dict:
        if snapshots is None:
            with self._lock:
                snapshots = dict(self.snapshots)
        procs = {}
        degraded = []
        for name in sorted(snapshots):
            s = snapshots[name]
            status = "down" if not s["up"] else \
                (s["healthz"] or {}).get("status", "ok")
            procs[name] = {"address": s["address"], "status": status}
            if status != "ok":
                degraded.append(name)
        return {"status": "ok" if not degraded else "degraded",
                "processes": procs, "degraded": degraded,
                "process_count": len(procs),
                "up_count": sum(1 for s in snapshots.values()
                                if s["up"])}

    # -------------------------------------------------------- changelog
    def fleet_state(self, snapshots: Optional[Dict[str, dict]] = None
                    ) -> Dict[str, dict]:
        """Per-process compact state docs — what the _IOTML_METRICS
        changelog carries (small, keyed, compaction-friendly)."""
        if snapshots is None:
            with self._lock:
                snapshots = dict(self.snapshots)
        out = {}
        for name, s in snapshots.items():
            doc = {"ts_ms": int(s["ts"] * 1000), "up": s["up"],
                   "address": s["address"]}
            for mname, labels, value in s["samples"]:
                if mname in self.SUM_FAMILIES:
                    doc[mname[len("iotml_"):]] = \
                        doc.get(mname[len("iotml_"):], 0.0) + value
                elif mname == "iotml_consumer_lag_records":
                    doc["consumer_lag"] = \
                        doc.get("consumer_lag", 0.0) + value
            hz = s.get("healthz") or {}
            if hz.get("status"):
                doc["status"] = hz["status"]
            out[name] = doc
        return out

    def append_tsdb(self, appender,
                    snapshots: Optional[Dict[str, dict]] = None) -> int:
        """Append every scraped sample to the log-native TSDB (ISSUE
        17), one chunked write set per process with the federation
        ``process=`` relabel applied at write time — history for the
        query engine beside the latest-only _IOTML_METRICS snapshot.
        Returns chunk records appended."""
        if snapshots is None:
            with self._lock:
                snapshots = dict(self.snapshots)
        n = 0
        for name in sorted(snapshots):
            s = snapshots[name]
            if not s["up"] or not s["samples"]:
                continue
            n += appender.append(s["samples"],
                                 ts_ms=int(s["ts"] * 1000),
                                 process=name)
        return n

    def snapshot_changelog(self, broker,
                           snapshots: Optional[Dict[str, dict]] = None
                           ) -> int:
        """Append the fleet state to the compacted _IOTML_METRICS
        changelog (key = process name): dashboards replay cluster
        history from the log like every other materialised view, and
        latest-per-key compaction bounds it at ~one record per
        process."""
        state = self.fleet_state(snapshots)
        if not state:
            return 0
        broker.create_topic(METRICS_TOPIC, cleanup_policy="compact")
        entries = [(name.encode(), json.dumps(doc, sort_keys=True)
                    .encode(), doc["ts_ms"])
                   for name, doc in sorted(state.items())]
        produce_many = getattr(broker, "produce_many", None)
        if produce_many is not None:
            produce_many(METRICS_TOPIC, entries, partition=0)
        else:
            for k, v, ts in entries:
                broker.produce(METRICS_TOPIC, v, key=k, partition=0)
        federation_snapshots.inc(len(entries))
        return len(entries)


def read_fleet_state(broker, partition: int = 0) -> Dict[str, dict]:
    """Latest fleet-state doc per process, replayed from the compacted
    _IOTML_METRICS changelog — the dashboard's cold-start read."""
    if METRICS_TOPIC not in broker.topics():
        return {}
    out: Dict[str, dict] = {}
    off = broker.begin_offset(METRICS_TOPIC, partition)
    end = broker.end_offset(METRICS_TOPIC, partition)
    while off < end:
        batch = broker.fetch(METRICS_TOPIC, partition, off, 4096)
        if not batch:
            break
        for m in batch:
            off = m.offset + 1
            if m.key is None:
                continue
            if m.value is None:
                out.pop(m.key.decode(), None)  # retired process
                continue
            try:
                out[m.key.decode()] = json.loads(m.value)
            except ValueError:
                continue
    return out


# -------------------------------------------------------------- server
class FleetServer:
    """One merged /metrics + /healthz for the whole fleet, scraping the
    manifest endpoints on a cadence (the `python -m iotml.obs fleet`
    runtime)."""

    def __init__(self, collector: FleetCollector, port: int = 9200,
                 interval_s: float = 2.0, broker=None, tsdb=None):
        self.collector = collector
        self.interval_s = interval_s
        self.broker = broker
        #: optional tsdb.TsdbAppender: every scrape's samples append to
        #: the log-native TSDB beside the latest-only changelog
        self.tsdb = tsdb
        self._stop = threading.Event()
        import http.server

        col = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/metrics":
                    body = col.collector.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    body = json.dumps(col.collector.healthz(), indent=2,
                                      sort_keys=True).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self.srv = http.server.ThreadingHTTPServer(("0.0.0.0", port),
                                                   Handler)
        self.port = self.srv.server_address[1]

    def scrape_once(self) -> Dict[str, dict]:
        snaps = self.collector.collect()
        if self.broker is not None:
            try:
                self.collector.snapshot_changelog(self.broker, snaps)
            except (ConnectionError, OSError):
                pass  # broker down: the merged /metrics still serves
        if self.tsdb is not None:
            try:
                self.collector.append_tsdb(self.tsdb, snaps)
            except (ConnectionError, OSError):
                pass  # same degradation contract as the changelog
        return snaps

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.scrape_once()
            self._stop.wait(self.interval_s)

    def start(self) -> "FleetServer":
        from ..supervise.registry import register_thread

        self._srv_thread = register_thread(threading.Thread(
            target=self.srv.serve_forever, daemon=True,
            name=f"iotml-fleet-metrics-{self.port}"))
        self._srv_thread.start()
        self._scrape_thread = register_thread(threading.Thread(
            target=self._loop, daemon=True,
            name="iotml-fleet-scraper"))
        self._scrape_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.srv.shutdown()
        self.srv.server_close()
