"""Metrics registry with Prometheus text exposition.

The reference observes everything through Prometheus + Grafana (operator
installed first thing, `01_installConfluentPlatform.sh:12-15`; simulator and
broker export families like `agent_publish_*`, `kafka_extension_*` — SURVEY
§5).  The framework-native equivalent: every component registers counters/
gauges/histograms here, and `render()` emits Prometheus text format, served
by `start_http_server` for scrape parity with the reference's dashboards.

Standard metric families the framework emits (see `default_registry`):
  iotml_records_consumed_total      stream records decoded
  iotml_records_trained_total       records through the train step
  iotml_records_scored_total        records through the scorer
  iotml_train_step_seconds          train-step latency histogram
  iotml_reconstruction_mse          last reconstruction error gauge
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple


def _escape_label_value(value) -> str:
    """Prometheus text-format label escaping: backslash, double-quote and
    newline must be escaped or the exposition line is unparseable (a
    label value like `car="a\nb"` silently corrupts the whole scrape)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._vals: Dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._vals.get(tuple(sorted(labels.items())), 0.0)

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:  # scrapes race with inc() from worker threads
            vals = dict(self._vals)
        for key, v in sorted(vals.items()):
            out.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        if not vals:
            out.append(f"{self.name} 0")
        return "\n".join(out)


class Gauge(Counter):
    def set(self, value: float, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._vals[key] = float(value)

    def render(self) -> str:
        return super().render().replace(" counter", " gauge", 1)


class Histogram:
    """Fixed-bucket histogram (Prometheus cumulative-bucket convention).

    Optionally labeled: ``observe(v, stage="decode")`` keeps one bucket
    series per label set (the `iotml_stage_seconds{stage=...}` family);
    unlabeled observations are the plain single-series histogram."""

    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)

    def __init__(self, name: str, help_: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        # label-key tuple → [bucket counts..., +Inf count]; () = unlabeled
        self._series: Dict[tuple, list] = {}
        self._sums: Dict[tuple, float] = {}
        self._ns: Dict[tuple, int] = {}
        self._lock = threading.Lock()

    def _counts_for(self, key: tuple) -> list:
        counts = self._series.get(key)
        if counts is None:
            counts = self._series[key] = [0] * (len(self.buckets) + 1)
            self._sums[key] = 0.0
            self._ns[key] = 0
        return counts

    def observe(self, value: float, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts_for(key)
            self._sums[key] += value
            self._ns[key] += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    return
            counts[-1] += 1

    def time(self, **labels):
        """Context manager: observe elapsed seconds (optionally into a
        labeled series, e.g. ``checkpoint_seconds.time(phase="fsync")``)."""
        hist = self

        class _T:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self.t0, **labels)

        return _T()

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:  # consistent bucket/sum/count snapshot under load
            series = {k: list(v) for k, v in self._series.items()}
            sums, ns = dict(self._sums), dict(self._ns)
        if not series:
            series[()] = [0] * (len(self.buckets) + 1)
            sums[()], ns[()] = 0.0, 0
        for key in sorted(series):
            labels = dict(key)
            cum = 0
            for b, c in zip(self.buckets, series[key]):
                cum += c
                out.append(f"{self.name}_bucket"
                           f"{_fmt_labels({**labels, 'le': b})} {cum}")
            cum += series[key][-1]
            out.append(f"{self.name}_bucket"
                       f"{_fmt_labels({**labels, 'le': '+Inf'})} {cum}")
            suffix = _fmt_labels(labels)
            out.append(f"{self.name}_sum{suffix} {sums[key]}")
            out.append(f"{self.name}_count{suffix} {ns[key]}")
        return "\n".join(out)


class Registry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", **kw) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_, **kw))

    def _get(self, name, factory):
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = factory()
            return self._metrics[name]

    def render(self) -> str:
        return "\n".join(m.render() for _, m in sorted(self._metrics.items())) + "\n"

    def collect(self) -> Dict[str, float]:
        """Structured snapshot: metric name (with label suffix for labeled
        series; `_sum`/`_count` for histograms) → value.  The typed
        counterpart of `render()` for programmatic consumers."""
        out: Dict[str, float] = {}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, Histogram):
                with m._lock:
                    sums, ns = dict(m._sums), dict(m._ns)
                if not sums:
                    sums[()], ns[()] = 0.0, 0
                for key in sorted(sums):
                    suffix = _fmt_labels(dict(key))
                    out[f"{name}_sum{suffix}"] = sums[key]
                    out[f"{name}_count{suffix}"] = float(ns[key])
                continue
            with m._lock:
                vals = dict(m._vals)
            for key, v in sorted(vals.items()):
                out[name + _fmt_labels(dict(key))] = v
            if not vals:
                out[name] = 0.0
        return out


default_registry = Registry()
records_consumed = default_registry.counter(
    "iotml_records_consumed_total", "stream records decoded")
records_trained = default_registry.counter(
    "iotml_records_trained_total", "records through the train step")
records_scored = default_registry.counter(
    "iotml_records_scored_total", "records through the scorer")
train_step_seconds = default_registry.histogram(
    "iotml_train_step_seconds", "train-step latency")
reconstruction_mse = default_registry.gauge(
    "iotml_reconstruction_mse", "last mean reconstruction error")
# continuous-learning loop (train/live.py ContinuousTrainer +
# serve/live.py LiveScorer): the round-4 services reported these only as
# stdout JSON for the bench harness — the operator's dashboards chart
# them from here
live_train_rounds = default_registry.counter(
    "live_train_rounds_total", "continuous-trainer rounds completed")
live_train_loss = default_registry.gauge(
    "live_train_loss", "continuous-trainer last round loss")
live_model_updates = default_registry.counter(
    "live_model_updates_total", "scorer weight hot-swaps applied")
live_detection_precision = default_registry.gauge(
    "live_detection_precision",
    "live verdict precision vs stream labels (cumulative)")
live_detection_recall = default_registry.gauge(
    "live_detection_recall",
    "live verdict recall vs stream labels (cumulative)")
# stream-plane hot-path telemetry (ISSUE 2): batch/commit shape of the
# consume path and the failure counters the serve loop's redelivery
# story turns on — alongside the per-record trace spans (obs.tracing)
fetch_batch_size = default_registry.histogram(
    "iotml_fetch_batch_size", "records returned per non-empty consumer poll",
    buckets=(1, 8, 32, 128, 512, 1024, 2048, 4096))
commit_seconds = default_registry.histogram(
    "iotml_commit_seconds", "consumer offset-commit latency")
scorer_rewinds = default_registry.counter(
    "iotml_scorer_rewinds_total",
    "scorer rewind-to-committed redeliveries after a broker failover")
consumer_autoresets = default_registry.counter(
    "iotml_consumer_autoresets_total",
    "consumer cursors auto-reset to earliest after retention trimmed "
    "past them (OffsetOutOfRange), by topic")
replica_sync_rounds = default_registry.counter(
    "iotml_replica_sync_rounds_total", "follower replication rounds")
replica_copied = default_registry.counter(
    "iotml_replica_copied_total", "messages copied leader -> follower")
replica_sync_errors = default_registry.counter(
    "iotml_replica_sync_errors_total",
    "replication rounds that failed (leader dying / unreachable)")
# replication + failover observability (ISSUE 4): the loss window and
# the fencing epoch as LIVE gauges, so dashboards see a promotion and
# the at-risk record count without polling replica.lag() themselves
replica_lag = default_registry.gauge(
    "iotml_replica_lag_records",
    "per-topic records the leader has that the follower does not "
    "(the loss window if the leader died now)")
failover_epoch = default_registry.gauge(
    "iotml_failover_epoch",
    "current leadership fencing epoch (bumped at every promotion)")
# supervision (iotml.supervise): the kubelet-equivalent's own telemetry
supervisor_unit_up = default_registry.gauge(
    "iotml_supervisor_unit_up",
    "1 while a supervised unit is live, 0 while down/degraded")
supervisor_restarts = default_registry.counter(
    "iotml_supervisor_restarts_total",
    "restarts issued per supervised unit")
supervisor_wedged = default_registry.counter(
    "iotml_supervisor_wedged_total",
    "wedge detections (live thread, stale heartbeat/stage) per unit")
supervisor_degraded = default_registry.gauge(
    "iotml_supervisor_degraded",
    "1 when the restart-storm budget is exhausted and the supervisor "
    "gave the unit up")
supervisor_failovers = default_registry.counter(
    "iotml_supervisor_failovers_total",
    "on_death failover hooks fired (leader promotions)")
# partitioned data plane (iotml.cluster): routing health — a rising
# bounce rate means clients chronically chase a moving partition map;
# failover counters pair with the supervise gauges above
cluster_not_leader_bounces = default_registry.counter(
    "iotml_cluster_not_leader_total",
    "produce/fetch requests bounced with NOT_LEADER_FOR_PARTITION "
    "(stale client metadata; refreshed and re-routed)")
cluster_metadata_refreshes = default_registry.counter(
    "iotml_cluster_metadata_refreshes_total",
    "cluster metadata refreshes performed by routing clients")
cluster_shard_failovers = default_registry.counter(
    "iotml_cluster_shard_failovers_total",
    "per-shard leader failovers (one shard moved, not the world; "
    "label shard= says WHICH — the TSDB query surface can tell a "
    "flapping shard from spread-out churn)")
cluster_shard_epoch = default_registry.gauge(
    "iotml_cluster_shard_epoch",
    "current leadership epoch per shard (a bump = a promotion; the "
    "federated scrape carries the per-shard label into the TSDB)")
cluster_coordinator_moves = default_registry.counter(
    "iotml_cluster_coordinator_moves_total",
    "group-coordinator re-discoveries after NOT_COORDINATOR or a "
    "coordinator broker death")
# model lifecycle (iotml.mlops): the continuous-delivery loop's own
# telemetry — which model every process is running (version gauges by
# component role), how far behind the log the serving model's training
# data is, and where checkpoint wall-time goes (the "no training stall"
# claim is only a claim until phase=snapshot is measured on the train
# thread and serialize/fsync are measured OFF it)
model_version = default_registry.gauge(
    "iotml_model_version",
    "registry version currently loaded, by component "
    "(trainer = last published, scorer = serving)")
model_offsets_lag = default_registry.gauge(
    "iotml_model_offsets_lag",
    "records between the current model's stamped train offsets and the "
    "log end (staleness of the serving model's knowledge)")
checkpoint_seconds = default_registry.histogram(
    "iotml_checkpoint_seconds",
    "checkpoint wall-time by phase: snapshot (train thread, device->"
    "host), serialize + fsync (background writer thread)")
checkpoint_dropped = default_registry.counter(
    "iotml_checkpoint_dropped_total",
    "pending snapshots evicted drop-oldest from the bounded writer "
    "queue (a slow disk sheds checkpoints, never stalls training)")
registry_publishes = default_registry.counter(
    "iotml_registry_publishes_total",
    "model versions committed to the registry (manifest written)")
registry_torn_recovered = default_registry.counter(
    "iotml_registry_torn_recovered_total",
    "torn/uncommitted version dirs swept by registry recovery")
registry_pruned = default_registry.counter(
    "iotml_registry_pruned_total",
    "committed versions removed by retention (keep-newest-N; channel "
    "targets are never pruned)")
model_swaps = default_registry.counter(
    "iotml_model_swaps_total",
    "scorer hot-swaps applied by registry watchers (no restart, no "
    "dropped records)")
rollouts = default_registry.counter(
    "iotml_rollouts_total",
    "A/B rollout gate decisions, by outcome (promoted | rolled_back)")
# true online learning (iotml.online): the per-window incremental
# learner's own telemetry — update cadence, what the drift detectors
# saw, which adaptation the policy chose, and whether adaptation
# actually converged (the state machine's STABLE re-entry).  The LR
# gauge makes a boost visible while it is active; the drift-stat gauge
# is the Page-Hinkley statistic an operator alarms on BEFORE the
# threshold trips.
online_updates = default_registry.counter(
    "iotml_online_updates_total",
    "incremental (per-window) SGD updates applied by the online learner")
online_drifts = default_registry.counter(
    "iotml_online_drifts_total",
    "drift episodes detected on the reconstruction-error signal, by "
    "detector (ph | adwin | level)")
online_adaptations = default_registry.counter(
    "iotml_online_adaptations_total",
    "drift-triggered adaptations applied, by action "
    "(boost | refit | reset)")
online_converged = default_registry.counter(
    "iotml_online_converged_total",
    "adaptation episodes that converged (smoothed error back inside "
    "the stable band; the monitor re-anchored its baseline)")
online_lr = default_registry.gauge(
    "iotml_online_learning_rate",
    "the online learner's current effective learning rate (boosted "
    "while a drift adaptation is active)")
online_drift_stat = default_registry.gauge(
    "iotml_online_drift_stat",
    "current Page-Hinkley statistic over the normalized smoothed "
    "error (drift fires when it crosses the configured threshold)")
# adversarial fleet conditions (iotml.gen.scenarios): agents that
# respected an MQTT backpressure signal defer records into their own
# bounded buffer instead of letting the broker drop-oldest
fleet_deferred = default_registry.counter(
    "iotml_fleet_deferred_total",
    "fleet-agent publishes deferred under MQTT backpressure (drained "
    "on later ticks — deferred, never dropped)")
# dead-letter queue (streamproc.dlq): poisoned frames routed, by source
dlq_total = default_registry.counter(
    "iotml_dlq_total",
    "undecodable records routed to a dead-letter topic, by source topic")
dlq_route_errors = default_registry.counter(
    "iotml_dlq_route_errors_total",
    "dead letters that could not be routed (degraded to a plain drop)")
# fleet-scope observability v2 (ISSUE 13): event-time watermarks on the
# columnar plane.  Per-record spans cannot exist where zero Python
# records materialise, but every store frame carries the record's
# timestamp — so each consuming stage reports, batch-granularly, how
# far behind EVENT TIME its progress frontier sits.  Lag is observed
# for the batch's min AND max event time, so the histogram brackets the
# true per-record e2e latency from below and above at zero per-record
# cost.  `stage` is a closed set (consume | score | train | twin).
watermark_lag_seconds = default_registry.histogram(
    "iotml_watermark_lag_seconds",
    "event-time lag (now - record timestamp) at each stage's progress "
    "frontier, batch-granular (min and max event time per batch)",
    buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
             60.0, 300.0))
watermark_event_ms = default_registry.gauge(
    "iotml_watermark_event_time_ms",
    "newest event timestamp (ms) each stage has fully processed — the "
    "stage's event-time watermark, by stage/topic/partition")
# consumer lag made first-class (ISSUE 13 satellite): records between
# the group's cursor and the partition high-water mark, refreshed at
# batch/commit granularity from the hwm every fetch response already
# carries (wire legs) or one end_offset read (in-process legs)
consumer_lag_records = default_registry.gauge(
    "iotml_consumer_lag_records",
    "records between a consumer group's cursor and the partition "
    "high-water mark, by group/topic/partition")
# hot-loop profiling hooks (ISSUE 13): where a train/score/online step's
# wall time actually goes — waiting on data (host_wait), inside the
# jitted program (device_compute), or in host-side decode/convert/
# format (host_pipeline).  The measured host-vs-device balance ROADMAP
# item 3 (multi-chip training) starts from.
step_seconds = default_registry.histogram(
    "iotml_step_seconds",
    "hot-loop wall time by loop (train|score|online) and phase "
    "(host_wait | device_compute | host_pipeline)",
    buckets=(0.0001, 0.001, 0.005, 0.02, 0.05, 0.1, 0.5, 1.0, 5.0,
             30.0))
prefetch_occupancy = default_registry.gauge(
    "iotml_prefetch_occupancy",
    "DevicePrefetcher queue fill fraction (0 = device starving on the "
    "host pipeline, 1 = host running ahead)")
# quorum replication (iotml.replication, ISSUE 14): the in-sync-replica
# set and the quorum high-water mark as live gauges — |ISR| per
# partition (leader included), how many covered partitions run below
# their target replica count, and how far the un-replicated tail
# (leader end - quorum HWM) currently reaches.  The federation
# collector rolls these up worst-of across the fleet.
isr_size = default_registry.gauge(
    "iotml_isr_size",
    "in-sync replica count per partition, leader included (acks=all "
    "commits at min(ISR positions))")
under_replicated = default_registry.gauge(
    "iotml_under_replicated_partitions",
    "replicated partitions whose ISR is below the configured replica "
    "target (followers evicted for lag/staleness and not re-admitted)")
quorum_hwm_lag = default_registry.gauge(
    "iotml_quorum_hwm_lag_records",
    "records between the leader log end and the quorum high-water mark "
    "— the tail acks=all producers are still waiting on and consumers "
    "cannot read yet, by topic/partition")


#: the CLOSED label-key vocabulary every iotml metric must draw from.
#: Metric labels multiply series: one label drawn from an unbounded set
#: (a car id, a trace id, an offset) turns a fixed-cost scrape into an
#: unbounded allocation — the cardinality-bound test (and lint R6)
#: fails such a label before production does.
ALLOWED_LABEL_KEYS = frozenset({
    "stage", "topic", "partition", "group", "phase", "loop", "process",
    "component", "detector", "action", "fault", "source", "outcome",
    "unit", "le", "slo", "window", "shard", "route", "code",
})

#: per-metric ceiling on distinct label-value combinations.  Generous —
#: topics × partitions × stages legitimately reach dozens — but far
#: below what one runaway per-entity label produces in seconds.
MAX_LABEL_SERIES = 256

#: per-metric label DECLARATIONS: the exact label keys each labeled
#: metric may be recorded with.  ALLOWED_LABEL_KEYS bounds the
#: vocabulary; this table bounds each metric's dimensions — a record
#: site using a key missing from its row is registry drift (analysis
#: rule D2), caught before the new dimension multiplies series in
#: production.  Metrics absent from the table take no labels.
DECLARED_METRIC_LABELS = {
    "alert_transitions": ("action",),
    "canary_probes": ("outcome",),
    "chaos_injected": ("fault",),
    "checkpoint_seconds": ("phase",),
    "cluster_shard_epoch": ("shard",),
    "cluster_shard_failovers": ("shard",),
    "consumer_autoresets": ("topic",),
    "consumer_lag_records": ("group", "partition", "topic"),
    "dlq_total": ("source",),
    "gateway_promotions": ("shard",),
    "gateway_standby_lag": ("shard",),
    "isr_size": ("partition", "topic"),
    "model_offsets_lag": ("component",),
    "model_version": ("component",),
    "online_adaptations": ("action",),
    "online_drifts": ("detector",),
    "prefetch_occupancy": ("loop",),
    "quorum_hwm_lag": ("partition", "topic"),
    "replica_lag": ("topic",),
    "rest_request_seconds": ("route",),
    "rest_requests": ("route", "code"),
    "rollouts": ("outcome",),
    "slo_burn_rate": ("slo", "window"),
    "step_seconds": ("loop", "phase"),
    "supervisor_degraded": ("unit",),
    "supervisor_failovers": ("unit",),
    "supervisor_restarts": ("unit",),
    "supervisor_unit_up": ("unit",),
    "supervisor_wedged": ("unit",),
    "watermark_event_ms": ("group", "partition", "stage", "topic"),
    "watermark_lag_seconds": ("group", "partition", "stage", "topic"),
}


def cardinality_violations(registry: "Registry" = None,
                           max_series: int = MAX_LABEL_SERIES):
    """[(metric, problem)] for labels outside the closed vocabulary or
    metrics whose labeled-series count exceeds `max_series` — the
    label-cardinality bound the obs test suite pins."""
    registry = default_registry if registry is None else registry
    out = []
    with registry._lock:
        metrics = dict(registry._metrics)
    for name, m in sorted(metrics.items()):
        if isinstance(m, Histogram):
            with m._lock:
                keysets = list(m._series.keys())
        else:
            with m._lock:
                keysets = list(m._vals.keys())
        label_keys = {k for key in keysets for k, _v in key}
        bad = label_keys - ALLOWED_LABEL_KEYS
        if bad:
            out.append((name, f"label keys outside the closed "
                              f"vocabulary: {sorted(bad)}"))
        if len(keysets) > max_series:
            out.append((name, f"{len(keysets)} labeled series exceeds "
                              f"the {max_series} cardinality bound"))
    return out


def start_http_server(port: int = 9100, registry: Registry = default_registry):
    """Serve /metrics (Prometheus text format) and /healthz (per-stage
    pipeline liveness from the trace collector) on a daemon thread."""
    import http.server
    import json

    def _healthz_body() -> bytes:
        # late import: tracing imports this module for its histograms
        from . import tracing

        stages = tracing.liveness()
        doc = {
            "status": "ok",
            "tracing": tracing.ENABLED,
            # stage → seconds since its newest span: the stalled stage is
            # the one whose age grows while its upstream stays fresh
            "stages": {s: {"last_span_age_s": age}
                       for s, age in stages.items()},
        }
        # supervision + failover state (ISSUE 4): unit states from any
        # live supervisor, the replica loss window, and the fencing
        # epoch.  Late import with a guard: an unsupervised process must
        # not pay for (or crash on) the supervise package.
        try:
            from ..supervise import registry as _sup_registry

            units = _sup_registry.snapshot()
            if units:
                doc["supervisor"] = units
                doc["status"] = "degraded" if any(
                    u.get("state") == "degraded"
                    for u in units.values()) else doc["status"]
        except Exception:  # noqa: BLE001 - health endpoint stays up
            pass
        # model identity (ISSUE 7): which registry version this process
        # runs, per component role, plus the offsets staleness of that
        # model — the rollout/rollback machinery's state surfaced where
        # probes already look
        with model_version._lock:
            mv = dict(model_version._vals)
        if mv:
            with model_offsets_lag._lock:
                lag = dict(model_offsets_lag._vals)
            doc["model"] = {
                dict(k).get("component", ""): {
                    "version": int(v),
                    "offsets_lag": lag.get(k)}
                for k, v in mv.items()}
        with replica_lag._lock:
            lag_vals = dict(replica_lag._vals)
        if lag_vals:
            doc["replica_lag_records"] = {
                dict(k).get("topic", ""): v for k, v in lag_vals.items()}
        # quorum replication (ISSUE 14): ISR width per partition, the
        # under-replicated count, and the un-replicated tail — the
        # acks=all durability state where probes already look
        with isr_size._lock:
            isr_vals = dict(isr_size._vals)
        if isr_vals:
            with quorum_hwm_lag._lock:
                qlag = dict(quorum_hwm_lag._vals)
            doc["replication"] = {
                "under_replicated_partitions": int(
                    under_replicated.value()),
                "isr": {
                    (f"{dict(k).get('topic', '')}"
                     f":{dict(k).get('partition', '')}"): int(v)
                    for k, v in sorted(isr_vals.items())},
                "quorum_hwm_lag_records": {
                    (f"{dict(k).get('topic', '')}"
                     f":{dict(k).get('partition', '')}"): int(v)
                    for k, v in sorted(qlag.items())},
            }
        # event-time watermarks (ISSUE 13): per-stage event-time
        # frontier and its lag vs now — true e2e staleness on the
        # columnar paths where per-record spans cannot exist
        with watermark_event_ms._lock:
            wm_vals = dict(watermark_event_ms._vals)
        if wm_vals:
            now_ms = time.time() * 1000.0  # wallclock-ok: event
            # timestamps live in the wall domain; this is staleness
            # display, not a deadline
            doc["watermarks"] = {}
            for k, v in sorted(wm_vals.items()):
                d = dict(k)
                name = (f"{d.get('stage', '')}:{d.get('topic', '')}"
                        f":{d.get('partition', '')}")
                if d.get("group"):
                    name += f":{d['group']}"
                doc["watermarks"][name] = {
                    "event_time_ms": int(v),
                    "lag_s": round(max(now_ms - v, 0.0) / 1000.0, 3)}
        # consumer lag (ISSUE 13 satellite): group cursor vs partition
        # high-water mark, the federation rollup's input
        with consumer_lag_records._lock:
            clag_vals = dict(consumer_lag_records._vals)
        if clag_vals:
            doc["consumer_lag_records"] = {
                (f"{dict(k).get('group', '')}:{dict(k).get('topic', '')}"
                 f":{dict(k).get('partition', '')}"): v
                for k, v in sorted(clag_vals.items())}
        # SLO burn-rate alerts (ISSUE 17): firing alerts from any live
        # SloEngine in this process, surfaced where probes already
        # look.  Late import with a guard, like the supervisor block —
        # a process without the SLO engine must not pay for it.
        try:
            from . import slo as _slo

            firing = _slo.firing_alerts()
            if firing:
                doc["alerts"] = firing
                doc["status"] = "degraded"
        except Exception:  # noqa: BLE001 - health endpoint stays up
            pass
        epoch = failover_epoch.value()
        if epoch:
            doc["failover_epoch"] = epoch
        return json.dumps(doc, indent=2, sort_keys=True).encode()

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/metrics":
                from . import tracing

                tracing.flush()  # spans land in the histograms per scrape
                body = registry.render().encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path == "/healthz":
                body = _healthz_body()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    from ..supervise.registry import register_thread

    srv = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    t = register_thread(threading.Thread(
        target=srv.serve_forever, daemon=True,
        name=f"iotml-metrics-{srv.server_address[1]}"))
    t.start()
    # federation auto-join (ISSUE 13): every process that serves
    # /metrics publishes its endpoint — into the in-process registry
    # always, and into the fleet's endpoints manifest when
    # IOTML_OBS_ENDPOINTS names one — so `python -m iotml.obs fleet`
    # discovers the whole fleet without per-process wiring.
    try:
        from . import federate, tracing

        name = tracing.proc_name()
        addr = f"127.0.0.1:{srv.server_address[1]}"
        manifest = federate.manifest_path()
        if manifest:
            federate.publish_endpoint(manifest, name, addr)
        else:
            federate.register_local_endpoint(name, addr)
    except Exception:  # noqa: BLE001 - metrics serving must not die on
        pass           # a manifest hiccup (read-only fs, lock contention)
    return srv
