"""Metrics registry with Prometheus text exposition.

The reference observes everything through Prometheus + Grafana (operator
installed first thing, `01_installConfluentPlatform.sh:12-15`; simulator and
broker export families like `agent_publish_*`, `kafka_extension_*` — SURVEY
§5).  The framework-native equivalent: every component registers counters/
gauges/histograms here, and `render()` emits Prometheus text format, served
by `start_http_server` for scrape parity with the reference's dashboards.

Standard metric families the framework emits (see `default_registry`):
  iotml_records_consumed_total      stream records decoded
  iotml_records_trained_total       records through the train step
  iotml_records_scored_total        records through the scorer
  iotml_train_step_seconds          train-step latency histogram
  iotml_reconstruction_mse          last reconstruction error gauge
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._vals: Dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._vals.get(tuple(sorted(labels.items())), 0.0)

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:  # scrapes race with inc() from worker threads
            vals = dict(self._vals)
        for key, v in sorted(vals.items()):
            out.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        if not vals:
            out.append(f"{self.name} 0")
        return "\n".join(out)


class Gauge(Counter):
    def set(self, value: float, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._vals[key] = float(value)

    def render(self) -> str:
        return super().render().replace(" counter", " gauge", 1)


class Histogram:
    """Fixed-bucket histogram (Prometheus cumulative-bucket convention)."""

    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)

    def __init__(self, name: str, help_: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, value: float):
        with self._lock:
            self._sum += value
            self._n += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def time(self):
        """Context manager: observe elapsed seconds."""
        hist = self

        class _T:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self.t0)

        return _T()

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:  # consistent bucket/sum/count snapshot under load
            counts = list(self._counts)
            total_sum, total_n = self._sum, self._n
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
        cum += counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum {total_sum}")
        out.append(f"{self.name}_count {total_n}")
        return "\n".join(out)


class Registry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", **kw) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_, **kw))

    def _get(self, name, factory):
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = factory()
            return self._metrics[name]

    def render(self) -> str:
        return "\n".join(m.render() for _, m in sorted(self._metrics.items())) + "\n"

    def collect(self) -> Dict[str, float]:
        """Structured snapshot: metric name (with label suffix for labeled
        series; `_sum`/`_count` for histograms) → value.  The typed
        counterpart of `render()` for programmatic consumers."""
        out: Dict[str, float] = {}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, Histogram):
                with m._lock:
                    out[f"{name}_sum"] = m._sum
                    out[f"{name}_count"] = float(m._n)
                continue
            with m._lock:
                vals = dict(m._vals)
            for key, v in sorted(vals.items()):
                out[name + _fmt_labels(dict(key))] = v
            if not vals:
                out[name] = 0.0
        return out


default_registry = Registry()
records_consumed = default_registry.counter(
    "iotml_records_consumed_total", "stream records decoded")
records_trained = default_registry.counter(
    "iotml_records_trained_total", "records through the train step")
records_scored = default_registry.counter(
    "iotml_records_scored_total", "records through the scorer")
train_step_seconds = default_registry.histogram(
    "iotml_train_step_seconds", "train-step latency")
reconstruction_mse = default_registry.gauge(
    "iotml_reconstruction_mse", "last mean reconstruction error")
# continuous-learning loop (train/live.py ContinuousTrainer +
# serve/live.py LiveScorer): the round-4 services reported these only as
# stdout JSON for the bench harness — the operator's dashboards chart
# them from here
live_train_rounds = default_registry.counter(
    "live_train_rounds_total", "continuous-trainer rounds completed")
live_train_loss = default_registry.gauge(
    "live_train_loss", "continuous-trainer last round loss")
live_model_updates = default_registry.counter(
    "live_model_updates_total", "scorer weight hot-swaps applied")
live_detection_precision = default_registry.gauge(
    "live_detection_precision",
    "live verdict precision vs stream labels (cumulative)")
live_detection_recall = default_registry.gauge(
    "live_detection_recall",
    "live verdict recall vs stream labels (cumulative)")


def start_http_server(port: int = 9100, registry: Registry = default_registry):
    """Serve /metrics in Prometheus text format (daemon thread)."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            body = registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    srv = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv
