"""Profiler integration — the reference's TensorBoard-profiler story.

The reference commits actual TF profiler traces with its notebooks
(`python-scripts/autoencoder-anomaly-detection/logs/plugins/profile/...`,
SURVEY §5 'tracing/profiling') and calls training monitoring a roadmap item
(reference `README.md:116`).  Here the JAX profiler fills that role: traces
are written in the same TensorBoard `plugins/profile` layout, viewable with
`tensorboard --logdir` + the profile plugin, or in Perfetto.

Usage:

    from iotml.obs.profile import trace, annotate

    with trace("./logs"):                  # one captured window
        trainer.fit_compiled(batches, epochs=20)

    with annotate("decode"):               # named span inside a capture
        batches = list(iter(sensor_batches))

`bench.py` honors `IOTML_PROFILE=<dir>` to capture its warm measurement
pass without changing the bench contract.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str = "./logs") -> Iterator[None]:
    """Capture a profiler trace window into `logdir` (TensorBoard layout)."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span that shows up on the trace timeline (host + device)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def maybe_trace(logdir: Optional[str]) -> Iterator[None]:
    """`trace` when a directory is given, no-op otherwise — for call sites
    driven by an env var (e.g. bench.py's IOTML_PROFILE)."""
    if logdir:
        with trace(logdir):
            yield
    else:
        yield


def trace_files(logdir: str) -> list:
    """Paths of captured trace artifacts under a log directory."""
    out = []
    for root, _dirs, files in os.walk(logdir):
        for f in files:
            if ".trace" in f or f.endswith((".pb", ".json.gz", ".xplane.pb")):
                out.append(os.path.join(root, f))
    return sorted(out)
