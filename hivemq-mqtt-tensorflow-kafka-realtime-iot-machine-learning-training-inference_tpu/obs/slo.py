"""Burn-rate SLO engine over the log-native TSDB (ISSUE 17).

The reference alerts through Prometheus rules + Grafana; this engine is
the framework-native equivalent, evaluated by a supervised unit against
the ``_IOTML_TSDB`` history:

- **declarative rules** (YAML-ish dicts in config): each names an
  objective and an indicator — a ``latency`` indicator over a native
  Histogram family (good = observations under the threshold bucket) or
  a ``ratio`` indicator over two counters (bad / total);
- **multi-window multi-burn-rate alerting** (the SRE-workbook shape):
  the *fast* pair (5 m short + 1 h long, burn >= 14.4) catches an
  outage in minutes, the *slow* pair (30 m short + 6 h long,
  burn >= 6) catches a simmering budget leak; BOTH windows of a pair
  must burn — a short spike alone (fast-short only) never pages;
- **alert transitions append to the compacted ``_IOTML_ALERTS``
  topic** (key = SLO name: the latest state per alert replays from the
  log like every other materialised view), surface in ``/healthz``,
  and export ``iotml_slo_burn_rate{slo=,window=}`` +
  ``iotml_alerts_firing``.

Burn rate = (observed error rate over the window) / (error budget),
error budget = 1 - objective.  Burn 1.0 = exactly on budget; 14.4 on a
99.9 % SLO = the 30-day budget gone in ~2 days.  Counter increases are
reset-corrected by the TSDB layer, so a supervised restart mid-window
reads as a reset, not as negative burn.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from . import metrics as _metrics
from . import tsdb as _tsdb

#: the compacted alert-state changelog (key = SLO name).  One writer
#: family: the obs package (lint R12 surface, like _IOTML_TSDB).
ALERTS_TOPIC = "_IOTML_ALERTS"

slo_burn_rate = _metrics.default_registry.gauge(
    "iotml_slo_burn_rate",
    "current burn rate per SLO and window pair (1.0 = exactly on "
    "budget; the fast pair pages at 14.4, the slow pair at 6)")
alerts_firing = _metrics.default_registry.gauge(
    "iotml_alerts_firing", "SLO burn-rate alerts currently firing")
slo_evals = _metrics.default_registry.counter(
    "iotml_slo_evaluations_total",
    "SLO rule evaluation passes performed by the engine")
alert_transitions = _metrics.default_registry.counter(
    "iotml_alert_transitions_total",
    "alert state transitions appended to _IOTML_ALERTS, by action "
    "(fire | resolve)")

#: (name, short_ms, long_ms, burn threshold) — the SRE-workbook pairs.
#: A rule's ``window_scale`` multiplies the durations so a drill can
#: compress 5 m/1 h into seconds without changing the alert logic.
DEFAULT_WINDOWS = (
    ("fast", 300_000, 3_600_000, 14.4),
    ("slow", 1_800_000, 21_600_000, 6.0),
)


@dataclass
class SloRule:
    """One declarative SLO: objective + indicator + window pairs."""

    name: str
    objective: float                       # e.g. 0.99
    indicator: dict                        # see from_dict
    windows: Tuple[tuple, ...] = DEFAULT_WINDOWS
    window_scale: float = 1.0

    @classmethod
    def from_dict(cls, doc: dict) -> "SloRule":
        """Validate the YAML-ish rule dict::

            {"name": "e2e-latency", "objective": 0.99,
             "indicator": {"kind": "latency",
                           "metric": "iotml_canary_e2e_seconds",
                           "threshold_s": 0.25,
                           "matchers": {"process": "canary"}},
             "window_scale": 1.0}            # optional

        ``kind: latency`` reads a native Histogram family (good =
        observations <= threshold_s); ``kind: ratio`` reads two
        counters: {"bad": name, "total": name, "matchers": {...}}."""
        name = str(doc.get("name", "")).strip()
        if not name:
            raise ValueError(f"SLO rule without a name: {doc!r}")
        objective = float(doc.get("objective", 0.0))
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"SLO {name!r}: objective must be in (0, 1), got "
                f"{objective}")
        ind = dict(doc.get("indicator") or {})
        kind = ind.get("kind")
        if kind == "latency":
            if not ind.get("metric"):
                raise ValueError(f"SLO {name!r}: latency indicator "
                                 f"needs a histogram 'metric'")
            float(ind.get("threshold_s", 0.0))
        elif kind == "ratio":
            if not ind.get("bad") or not ind.get("total"):
                raise ValueError(f"SLO {name!r}: ratio indicator needs "
                                 f"'bad' and 'total' counter names")
        else:
            raise ValueError(f"SLO {name!r}: indicator kind must be "
                             f"'latency' or 'ratio', got {kind!r}")
        windows = doc.get("windows")
        if windows is not None:
            windows = tuple(
                (str(w[0]), int(w[1]), int(w[2]), float(w[3]))
                for w in windows)
        return cls(name=name, objective=objective, indicator=ind,
                   windows=windows or DEFAULT_WINDOWS,
                   window_scale=float(doc.get("window_scale", 1.0)))

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective


def _matchers_of(ind_doc: dict, side: str = "") -> List[_tsdb.Matcher]:
    """Equality matchers from the indicator doc; a ratio indicator may
    overlay per-side ones (``bad_matchers`` / ``total_matchers``) on the
    shared ``matchers`` — e.g. bad = outcome="lost" vs total =
    outcome="sent" over the SAME counter family."""
    merged = dict(ind_doc.get("matchers") or {})
    if side:
        merged.update(ind_doc.get(f"{side}_matchers") or {})
    return [_tsdb.Matcher(k, "=", str(v))
            for k, v in sorted(merged.items())]


def _sum_increase(series: Dict[str, dict], name: str, matchers,
                  window_ms: int, at_ms: int) -> float:
    return sum(r["value"] for r in _tsdb.increase(
        series, name, matchers, window_ms=window_ms, at_ms=at_ms))


def _error_rate(rule: SloRule, series: Dict[str, dict],
                window_ms: int, at_ms: int) -> Optional[float]:
    """Observed error fraction over the window, None when the window
    carries no signal (no traffic = no burn, not 100% burn)."""
    ind = rule.indicator
    if ind["kind"] == "ratio":
        total = _sum_increase(series, ind["total"],
                              _matchers_of(ind, "total"),
                              window_ms, at_ms)
        if total <= 0:
            return None
        bad = _sum_increase(series, ind["bad"],
                            _matchers_of(ind, "bad"),
                            window_ms, at_ms)
        return min(max(bad / total, 0.0), 1.0)
    matchers = _matchers_of(ind)
    # latency: good = reset-corrected increase of the cumulative
    # bucket covering the threshold, total = the +Inf bucket
    family = ind["metric"]
    bname = family + "_bucket"
    threshold = float(ind.get("threshold_s", 0.0))
    groups: Dict[tuple, Dict[float, float]] = {}
    for s in _tsdb.select(series, bname, matchers):
        le = s["l"].get("le")
        try:
            edge = float(le)
        except (TypeError, ValueError):
            continue
        inc = _tsdb.increase(
            {_tsdb.series_id(s["n"], s["l"]): s}, bname,
            window_ms=window_ms, at_ms=at_ms)
        if not inc:
            continue
        key = tuple(sorted((k, v) for k, v in s["l"].items()
                           if k != "le"))
        groups.setdefault(key, {})[edge] = \
            groups.get(key, {}).get(edge, 0.0) + inc[0]["value"]
    good = total = 0.0
    for buckets in groups.values():
        edges = sorted(buckets)
        if not edges:
            continue
        total += buckets[edges[-1]]  # +Inf (sorts last)
        covering = [e for e in edges if e >= threshold]
        if covering:
            good += buckets[covering[0]]
    if total <= 0:
        return None
    return min(max(1.0 - good / total, 0.0), 1.0)


@dataclass
class AlertState:
    slo: str
    firing: bool = False
    window: str = ""               # which pair fired ("fast" | "slow")
    burn: Dict[str, float] = field(default_factory=dict)
    since_ms: int = 0
    message: str = ""


#: process-global firing snapshot for /healthz (metrics.start_http_server
#: late-imports this; a process without an SLO engine sees {})
_firing_lock = threading.Lock()
_firing: Dict[str, dict] = {}


def firing_alerts() -> Dict[str, dict]:
    with _firing_lock:
        return dict(_firing)


def _publish_firing(states: Dict[str, AlertState]) -> None:
    with _firing_lock:
        _firing.clear()
        for name, st in states.items():
            if st.firing:
                _firing[name] = {"window": st.window,
                                 "burn": dict(st.burn),
                                 "since_ms": st.since_ms,
                                 "message": st.message}


class SloEngine:
    """Evaluate rules against the TSDB on a cadence; fire/resolve
    alerts; append transitions to the compacted ``_IOTML_ALERTS``
    topic.  Run the ``loop`` body as a supervised unit (the engine is a
    pipeline citizen: it restarts like one, and its own counters reset
    like one — which the TSDB's rate() must read as a reset)."""

    def __init__(self, broker, rules: Iterable[dict],
                 interval_s: float = 2.0, partition: int = 0,
                 lookback_ms: Optional[int] = None):
        self.broker = broker
        self.rules = [r if isinstance(r, SloRule) else
                      SloRule.from_dict(r) for r in rules]
        self.interval_s = interval_s
        self.partition = partition
        # replay horizon: the longest scaled window, plus slack for the
        # chunk the horizon lands inside
        if lookback_ms is None and self.rules:
            lookback_ms = int(max(
                w[2] * r.window_scale
                for r in self.rules for w in r.windows)
                + 2 * _tsdb.DEFAULT_CHUNK_MS)
        self.lookback_ms = lookback_ms or 3_600_000
        self.states: Dict[str, AlertState] = {
            r.name: AlertState(slo=r.name) for r in self.rules}
        # incremental TSDB reader: a cadenced evaluator must not replay
        # the whole (growing) topic per pass — the tail decodes only
        # new records, bounded to the indicator families + lookback
        self._tail = _tsdb.TsdbTail(
            broker, partition=partition,
            names=self._indicator_families(), lookback_ms=self.lookback_ms)
        broker.create_topic(ALERTS_TOPIC, cleanup_policy="compact")

    def _indicator_families(self) -> set:
        """The metric families the rules actually read — the tail skips
        everything else at decode time."""
        names = set()
        for r in self.rules:
            ind = r.indicator
            if ind["kind"] == "latency":
                names.add(ind["metric"] + "_bucket")
            else:
                names.add(ind["bad"])
                names.add(ind["total"])
        return names

    # ---------------------------------------------------------- evaluate
    def evaluate(self, series: Optional[Dict[str, dict]] = None,
                 now_ms: Optional[int] = None) -> List[dict]:
        """One evaluation pass; returns the transition docs appended to
        _IOTML_ALERTS (empty when no alert changed state)."""
        if now_ms is None:
            now_ms = int(time.time() * 1000)  # wallclock-ok: sample
            # timestamps live in the wall/event-time domain
        if series is None:
            series = self._tail.collect(now_ms)
        slo_evals.inc()
        transitions: List[dict] = []
        for rule in self.rules:
            st = self.states[rule.name]
            burns: Dict[str, float] = {}
            fired_pair = ""
            for wname, short_ms, long_ms, threshold in rule.windows:
                pair_burn = None
                pair_ok = True
                for leg, wms in (("short", short_ms), ("long", long_ms)):
                    wms = int(wms * rule.window_scale)
                    err = _error_rate(rule, series, wms, now_ms)
                    burn = (err / rule.error_budget) \
                        if err is not None else 0.0
                    if leg == "short":
                        pair_burn = burn
                    if err is None or burn < threshold:
                        pair_ok = False
                burns[wname] = pair_burn or 0.0
                if pair_ok and not fired_pair:
                    fired_pair = wname
            st.burn = burns
            for wname, burn in burns.items():
                slo_burn_rate.set(burn, slo=rule.name, window=wname)
            if fired_pair and not st.firing:
                st.firing = True
                st.window = fired_pair
                st.since_ms = now_ms
                st.message = (
                    f"SLO {rule.name}: {fired_pair} burn-rate pair over "
                    f"threshold (burn={burns[fired_pair]:.1f}, "
                    f"objective={rule.objective})")
                transitions.append(self._transition(rule, st, "fire",
                                                    now_ms))
            elif not fired_pair and st.firing:
                st.firing = False
                st.message = f"SLO {rule.name}: burn back under threshold"
                transitions.append(self._transition(rule, st, "resolve",
                                                    now_ms))
                st.window = ""
        alerts_firing.set(sum(1 for s in self.states.values()
                              if s.firing))
        _publish_firing(self.states)
        if transitions:
            self._append(transitions)
        return transitions

    def _transition(self, rule: SloRule, st: AlertState, action: str,
                    now_ms: int) -> dict:
        alert_transitions.inc(action=action)
        return {"slo": rule.name, "action": action, "ts_ms": now_ms,
                "window": st.window, "burn": dict(st.burn),
                "objective": rule.objective, "firing": st.firing,
                "message": st.message}

    def _append(self, transitions: List[dict]) -> None:
        entries = [(t["slo"].encode(),
                    json.dumps(t, sort_keys=True).encode(), t["ts_ms"])
                   for t in transitions]
        produce_many = getattr(self.broker, "produce_many", None)
        try:
            if produce_many is not None:
                produce_many(ALERTS_TOPIC, entries,
                             partition=self.partition)
            else:
                for k, v, _ts in entries:
                    self.broker.produce(ALERTS_TOPIC, v, key=k,
                                        partition=self.partition)
        except (ConnectionError, OSError):
            pass  # broker down: /healthz + gauges still carry the alert

    # ------------------------------------------------------- unit body
    def loop(self, unit) -> None:
        """SupervisedUnit body: evaluate on the cadence, heartbeat per
        pass (``sup.add_loop("slo-engine", engine.loop)``)."""
        while not unit.should_stop():
            try:
                self.evaluate()
            except (ConnectionError, OSError):
                pass  # broker hiccup: next pass re-reads
            unit.heartbeat()
            time.sleep(self.interval_s)


def read_alerts(broker, partition: int = 0) -> Dict[str, dict]:
    """Latest alert state per SLO, replayed from the compacted
    _IOTML_ALERTS changelog (the dashboard/CLI cold-start read)."""
    if ALERTS_TOPIC not in broker.topics():
        return {}
    out: Dict[str, dict] = {}
    off = broker.begin_offset(ALERTS_TOPIC, partition)
    end = broker.end_offset(ALERTS_TOPIC, partition)
    while off < end:
        batch = broker.fetch(ALERTS_TOPIC, partition, off, 4096)
        if not batch:
            break
        for m in batch:
            off = m.offset + 1
            if m.key is None:
                continue
            if m.value is None:
                out.pop(m.key.decode(), None)
                continue
            try:
                out[m.key.decode()] = json.loads(m.value)
            except ValueError:
                continue
    return out
