"""Training scalar logging: TensorBoard + JSONL.

The reference wires TensorBoard callbacks into fit and commits the resulting
event files (SURVEY §5 'tracing/profiling').  Here: a `ScalarLogger` that
writes TensorBoard event files via tensorboardX when present (it is in this
image) and always mirrors to a plain JSONL file (grep-able, no reader dep),
plus a `JaxProfiler` wrapper over `jax.profiler` trace sessions — the
XLA-level equivalent of the reference's committed TF profiler traces.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class ScalarLogger:
    def __init__(self, log_dir: str, use_tensorboard: bool = True):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._jsonl = open(os.path.join(log_dir, "scalars.jsonl"), "a")
        self._tb = None
        if use_tensorboard:
            try:
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(log_dir)
            except Exception:
                self._tb = None

    def scalar(self, tag: str, value: float, step: int):
        self._jsonl.write(json.dumps(
            {"tag": tag, "value": float(value), "step": int(step),
             "wall_time": time.time()}) + "\n")
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.add_scalar(tag, float(value), int(step))

    def history(self, history: dict, prefix: str = "train"):
        """Log a Trainer.fit history dict (per-epoch scalars)."""
        for i, loss in enumerate(history.get("loss", [])):
            self.scalar(f"{prefix}/loss", loss, i)
        for i, acc in enumerate(history.get("accuracy", [])):
            self.scalar(f"{prefix}/accuracy", acc, i)
        for i, s in enumerate(history.get("seconds", [])):
            self.scalar(f"{prefix}/epoch_seconds", s, i)

    def close(self):
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


class JaxProfiler:
    """jax.profiler trace session → TensorBoard-loadable trace directory.

    Thin class wrapper over `obs.profile.trace` (which also offers
    `annotate` spans and `maybe_trace` for env-driven capture)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._cm = None

    def __enter__(self):
        from .profile import trace

        self._cm = trace(self.log_dir)
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        self._cm.__exit__(*exc)
