"""Record-level trace context + per-stage telemetry for the pipeline.

The reference observes its pipeline only from the *outside* — Prometheus
scraping broker and simulator gauges (SURVEY §5) — so nobody can answer
the question that matters for a no-data-lake streaming trainer: how long
does one sensor reading take device → MQTT → broker → bridge → KSQL →
consumer → train-step/score, and which stage ate the budget?  tf.data's
pipeline analysis (PAPERS.md) makes the same point: stage-level
telemetry is what turns "it's slow" into "it's input-bound at the
decode stage".

Design:

- A `TraceContext` is injected where a record is born (MQTT publish /
  devsim produce), carried through the pipeline via *record headers*
  (`Message.headers`, key ``iotml_trace``) so the Avro payload is
  untouched, and closed at the train step or the scorer.
- Time domain is **monotonic** (PR 1's R1 rule): spans are durations
  from the injection instant, never wall-clock differences.  One wall
  clock read at injection timestamps the trace for the span log.
- Stage marks record spans into a **lock-free collector**: a per-thread
  `deque` (GIL-atomic append, bounded drop-oldest) registered once per
  thread; nothing on the record path takes a lock — verified by the
  lockcheck plugin and lint rule R6.
- Exporters run at *drain* time (`flush()`, the /metrics scrape, the
  /healthz probe, atexit): spans land in the Prometheus histograms
  ``iotml_stage_seconds{stage=...}`` and
  ``iotml_e2e_ingest_to_*_seconds``, and — when a path is configured —
  in a JSONL span log the ``python -m iotml.obs trace`` CLI summarizes.

Off by default, zero-ish cost: every instrumentation site guards on the
module flag (`tracing.ENABLED`) and allocates nothing when it is False.
Enable with ``IOTML_TRACE=1``; sample with ``IOTML_TRACE_SAMPLE=0.01``;
log spans to ``IOTML_TRACE_PATH=/tmp/spans.jsonl``.  These are process
toggles, not pipeline config — registered in `iotml.config`'s
``non_config`` set.

Header wire format (for transports that carry bytes, not objects):
``iotml1;<trace_id hex16>;<t0 unix ns>;<elapsed ns>`` — `encode()` /
`decode()` round-trip it.  In-process brokers carry the live context
object itself; the Kafka wire protocol's MessageSet v1 has no header
slot, so traces end at a TCP broker boundary (graceful degradation,
like the native-engine fallback).
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import metrics as _metrics

#: module flag every hot-path site guards on.  Mutated only via
#: configure(); reading a module attribute is the whole disabled cost.
ENABLED = False

#: probability a newborn record is traced (1.0 = every record).
_SAMPLE = 1.0

#: JSONL span-log path (None = histograms only).
_PATH: Optional[str] = None

#: header key the context rides under in Message.headers.
HEADER_KEY = "iotml_trace"

_WIRE_PREFIX = "iotml1"


def proc_name() -> str:
    """This process's identity in cross-process span logs: IOTML_PROC
    when the operator names the role (scorer/trainer/broker-0/...),
    else pid-derived.  Several fleet processes append to ONE span log
    (O_APPEND line writes); the proc field is what lets the trace CLI
    reconstruct which process ran which stage."""
    return os.environ.get("IOTML_PROC") or f"pid{os.getpid()}"

#: per-thread span buffer bound — overload drops oldest, counted below.
_BUFFER_BOUND = 65536

# ------------------------------------------------------------- exporters
stage_seconds = _metrics.default_registry.histogram(
    "iotml_stage_seconds", "per-stage pipeline latency (label: stage)",
    buckets=(0.00001, 0.0001, 0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))
e2e_ingest_to_score_seconds = _metrics.default_registry.histogram(
    "iotml_e2e_ingest_to_score_seconds",
    "end-to-end latency, record ingest to scorer close")
e2e_ingest_to_train_seconds = _metrics.default_registry.histogram(
    "iotml_e2e_ingest_to_train_seconds",
    "end-to-end latency, record ingest to train-step close")
spans_dropped = _metrics.default_registry.counter(
    "iotml_trace_spans_dropped_total",
    "spans dropped by the bounded per-thread collector")
log_write_errors = _metrics.default_registry.counter(
    "iotml_trace_log_write_errors_total",
    "span-log appends that failed (unwritable path, full disk)")


# ------------------------------------------------------------- collector
class _Buf:
    """One thread's span buffer + its local overload-drop count.  The
    drop count is a plain int mutated only by the owning thread (folded
    into the shared counter at drain) so the record path touches no
    shared lock even when the buffer is saturated."""

    __slots__ = ("q", "drops", "thread")

    def __init__(self, thread: threading.Thread):
        self.q: collections.deque = collections.deque(maxlen=_BUFFER_BOUND)
        self.drops = 0
        self.thread = thread


class _Collector:
    """Per-thread bounded deques; append is GIL-atomic (no lock on the
    record path), the registry of buffers is locked only at thread
    registration and drain — never while a span is recorded."""

    def __init__(self):
        self._tls = threading.local()
        self._buffers: List[_Buf] = []
        self._reg_lock = threading.Lock()

    def buffer(self) -> _Buf:
        buf = getattr(self._tls, "buf", None)
        if buf is None:
            buf = _Buf(threading.current_thread())
            self._tls.buf = buf
            with self._reg_lock:
                self._buffers.append(buf)
        return buf

    def record(self, entry: tuple) -> None:
        buf = self.buffer()
        if len(buf.q) == buf.q.maxlen:
            buf.drops += 1  # thread-local; folded in at drain (no lock)
        buf.q.append(entry)

    def drain(self) -> List[tuple]:
        with self._reg_lock:
            buffers = list(self._buffers)
        out: List[tuple] = []
        for buf in buffers:
            # popleft until empty: concurrent appends land at the right
            # and are picked up by this or the next drain — never lost,
            # never double-read
            while True:
                try:
                    out.append(buf.q.popleft())
                except IndexError:
                    break
        dropped = 0
        dead: List[_Buf] = []
        # drop-count folding and dead-thread pruning under the registry
        # lock: concurrent drainers (two scrapes) must not both read the
        # same buf.drops and double-count it.  Recording threads never
        # touch this lock (registration is the documented once-per-thread
        # exception); an owner increment landing exactly between the read
        # and the reset below is lost — approximate under overload, by
        # design, never a crash.
        with self._reg_lock:
            for buf in buffers:
                if buf.drops:
                    dropped += buf.drops
                    buf.drops = 0
                # prune buffers of exited threads (a churning MQTT fleet
                # is a thread per connection: without this the registry
                # grows one dead deque per reconnect, forever).  Just
                # drained empty + owner dead = nothing can land in it.
                if not buf.q and not buf.thread.is_alive():
                    dead.append(buf)
            if dead:
                self._buffers = [b for b in self._buffers
                                 if b not in dead]
        if dropped:
            spans_dropped.inc(dropped)
        return out


_collector = _Collector()

#: stage → monotonic time of the newest drained span: per-stage liveness
#: for the /healthz status section (age = now - value).
_last_seen: Dict[str, float] = {}

#: current-trace slot for synchronous fan-out propagation (the MQTT
#: broker delivers on the publisher's thread, so the bridge reads the
#: publisher's context without any header slot in the MQTT PUBLISH).
_current = threading.local()

_log_lock = threading.Lock()  # serializes span-log file appends (drain only)


class TraceContext:
    """One record's journey.  `mark(stage)` records the span since the
    previous mark; `close(closer)` marks the final stage and the e2e
    span.  All durations are monotonic-clock."""

    __slots__ = ("trace_id", "t0", "t_last", "wall0_ns", "closed")

    def __init__(self, trace_id: Optional[int] = None,
                 t0: Optional[float] = None,
                 wall0_ns: Optional[int] = None):
        self.trace_id = trace_id if trace_id is not None \
            else random.getrandbits(64)
        self.t0 = t0 if t0 is not None else time.monotonic()
        self.t_last = self.t0
        self.wall0_ns = wall0_ns if wall0_ns is not None \
            else time.time_ns()  # wallclock-ok: trace birth timestamp for the span log, not a deadline
        self.closed = False

    # ------------------------------------------------------------ spans
    def mark(self, stage: str) -> None:
        """Record the span from the previous mark to now as `stage`.

        A closed context records nothing more: an epoch re-read polls the
        same header-carried context again, and re-marking it would book
        the inter-epoch gap as pipeline latency."""
        if self.closed:
            return
        now = time.monotonic()
        # `now` rides along so liveness() can report the span's MARK
        # time, not the drain time — a stalled stage probed much later
        # must show its true age
        _collector.record(("span", self.trace_id, stage,
                           self.t_last - self.t0, now - self.t_last,
                           self.wall0_ns, now))
        self.t_last = now

    def close(self, closer: str) -> None:
        """Final stage (`train` / `score`) + the end-to-end span."""
        if self.closed:
            return
        self.mark(closer)
        self.closed = True
        _collector.record(("e2e", self.trace_id, closer,
                           self.t_last - self.t0, self.wall0_ns))

    def fork(self) -> "TraceContext":
        """Per-consumer continuation of a shared upstream context.

        The header-carried object is read by EVERY consumer group of the
        topic (a train pipeline and a serve pipeline routinely poll the
        same log).  Each reader forks at its consume boundary and closes
        only its fork — same trace id, birth instant and elapsed-so-far,
        private t_last/closed — so one pipeline's close can neither
        steal the trace from another (the first-closer-wins bug) nor
        race its marks on the shared t_last."""
        child = TraceContext(trace_id=self.trace_id, t0=self.t0,
                             wall0_ns=self.wall0_ns)
        child.t_last = self.t_last
        return child

    # ---------------------------------------------------------- headers
    def encode(self) -> bytes:
        """Byte form for transports: id, birth wall time, elapsed."""
        elapsed_ns = int((time.monotonic() - self.t0) * 1e9)
        return (f"{_WIRE_PREFIX};{self.trace_id:016x};{self.wall0_ns};"
                f"{elapsed_ns}").encode()

    @classmethod
    def decode(cls, raw: bytes) -> Optional["TraceContext"]:
        """Rebase a wire-carried context into this process's monotonic
        domain (elapsed-so-far is preserved; clock skew between hosts is
        the usual distributed-tracing caveat)."""
        try:
            prefix, tid, wall0, elapsed = raw.decode().split(";")
            if prefix != _WIRE_PREFIX:
                return None
            ctx = cls(trace_id=int(tid, 16),
                      t0=time.monotonic() - int(elapsed) / 1e9,
                      wall0_ns=int(wall0))
            ctx.t_last = time.monotonic()
            return ctx
        except (ValueError, UnicodeDecodeError):
            return None


# ------------------------------------------------------------ public API
def configure(enabled: Optional[bool] = None,
              sample: Optional[float] = None,
              path: Optional[str] = None) -> None:
    global ENABLED, _SAMPLE, _PATH
    if enabled is not None:
        ENABLED = bool(enabled)
    if sample is not None:
        _SAMPLE = min(max(float(sample), 0.0), 1.0)
    if path is not None:
        _PATH = path or None


def configure_from_env(env: Optional[Dict[str, str]] = None) -> None:
    env = os.environ if env is None else env
    raw = env.get("IOTML_TRACE")
    if raw is not None:
        configure(enabled=raw.strip().lower() in ("1", "true", "yes", "on"))
    raw = env.get("IOTML_TRACE_SAMPLE")
    if raw:
        configure(sample=float(raw))
    raw = env.get("IOTML_TRACE_PATH")
    if raw:
        configure(path=raw)


def start(stage: str) -> Optional[TraceContext]:
    """Begin a trace at a record's birth (sampling decision happens
    here); returns None when disabled or not sampled."""
    if not ENABLED:
        return None
    if _SAMPLE < 1.0 and random.random() >= _SAMPLE:
        return None
    ctx = TraceContext()
    ctx.mark(stage)
    return ctx


def current() -> Optional[TraceContext]:
    """The publisher-thread context (synchronous fan-out propagation)."""
    return getattr(_current, "ctx", None)


def set_current(ctx: Optional[TraceContext]):
    prev = getattr(_current, "ctx", None)
    _current.ctx = ctx
    return prev


def touch(stage: str) -> None:
    """Mark `stage` live WITHOUT a span — the batch-granular liveness
    beat for the columnar plane (ISSUE 13 satellite): ``poll_into``
    materialises zero records, so an untraced-record columnar consumer
    emits no consume-stage spans and the /healthz stage-age view would
    report a perfectly healthy pipeline as stalled.  A plain dict store
    under the GIL; racing a concurrent drain is benign (both write
    "recent")."""
    if ENABLED:
        _last_seen[stage] = time.monotonic()


def mark_batch(ctx: Optional[TraceContext], stage: str,
               topic: Optional[str] = None, partition: int = -1,
               first_offset: int = -1, last_offset: int = -1,
               n: int = 0) -> None:
    """One span for a whole RAW batch (ISSUE 13 wire-trace leg): marks
    `stage` on `ctx` (the timing span, like mark()) and records a batch
    annotation — topic/partition, offset range, record count — that the
    span log carries so ``python -m iotml.obs trace`` can show which
    bytes the cross-process span covered.  Batch-granular by contract:
    one call per raw batch, never per record."""
    if ctx is None or ctx.closed:
        return
    ctx.mark(stage)
    _collector.record(("batch", ctx.trace_id, stage, topic or "",
                       int(partition), int(first_offset),
                       int(last_offset), int(n), ctx.wall0_ns))


def headers_for(ctx: Optional[TraceContext]) -> Optional[Tuple]:
    """Record headers carrying `ctx` (None stays None: untraced records
    pay no header tuple)."""
    if ctx is None:
        return None
    return ((HEADER_KEY, ctx),)


def birth_headers(stage: str) -> Optional[Tuple]:
    """start() + headers_for() in one: the trace-birth idiom for
    producers that attach the context straight to the produced record.
    Call sites still guard on `tracing.ENABLED` so the disabled hot
    path makes no function call at all."""
    return headers_for(start(stage))


def from_headers(headers) -> Optional[TraceContext]:
    """Extract a context from record headers: the live object on the
    in-process path, the byte form off a transport."""
    if not headers:
        return None
    for key, value in headers:
        if key != HEADER_KEY:
            continue
        if isinstance(value, TraceContext):
            return value
        if isinstance(value, (bytes, bytearray)):
            return TraceContext.decode(bytes(value))
    return None


# ---------------------------------------------------------------- drain
def flush() -> Dict[str, int]:
    """Drain the collector into the Prometheus histograms, the liveness
    table and (when configured) the JSONL span log.  Returns counts.
    Exporting happens HERE, never on the record path — the histograms'
    internal locks are only ever taken by drainers."""
    entries = _collector.drain()
    if not entries:
        return {"spans": 0, "e2e": 0}
    n_span = n_e2e = 0
    lines: List[str] = []
    proc = proc_name()
    for e in entries:
        if e[0] == "span":
            _, tid, stage, start_s, dur_s, wall0_ns, t_mark = e
            n_span += 1
            stage_seconds.observe(dur_s, stage=stage)
            # the MARK instant, not the drain instant: liveness ages
            # must keep growing for a stalled stage even when the first
            # probe in a long while is what triggers this drain
            if t_mark > _last_seen.get(stage, float("-inf")):
                _last_seen[stage] = t_mark
            if _PATH:
                lines.append(json.dumps(
                    {"kind": "span", "trace": f"{tid:016x}", "stage": stage,
                     "start_us": int(start_s * 1e6),
                     "dur_us": int(dur_s * 1e6), "wall0_ns": wall0_ns,
                     "proc": proc}))
        elif e[0] == "batch":
            # batch annotation (mark_batch): the timing span was already
            # recorded by the mark() inside mark_batch — this line
            # carries the WHAT (topic/partition/offset range/count) for
            # the cross-process trace reconstruction
            _, tid, stage, topic, part, first, last, n, wall0_ns = e
            if _PATH:
                lines.append(json.dumps(
                    {"kind": "batch", "trace": f"{tid:016x}",
                     "stage": stage, "topic": topic, "partition": part,
                     "first_offset": first, "last_offset": last,
                     "n": n, "wall0_ns": wall0_ns, "proc": proc}))
        else:
            _, tid, closer, dur_s, wall0_ns = e
            n_e2e += 1
            if closer == "score":
                e2e_ingest_to_score_seconds.observe(dur_s)
            elif closer == "train":
                e2e_ingest_to_train_seconds.observe(dur_s)
            if _PATH:
                lines.append(json.dumps(
                    {"kind": "e2e", "trace": f"{tid:016x}", "closer": closer,
                     "dur_us": int(dur_s * 1e6), "wall0_ns": wall0_ns,
                     "proc": proc}))
    if lines and _PATH:
        try:
            with _log_lock:
                with open(_PATH, "a", encoding="utf-8") as fh:
                    fh.write("\n".join(lines) + "\n")
        except OSError:
            # an unwritable span-log path (permissions, full disk) must
            # not turn into a /metrics scrape outage or an atexit crash —
            # the histograms above already have the spans; count the loss
            # under its own family (distinct from collector overload)
            log_write_errors.inc(len(lines))
    return {"spans": n_span, "e2e": n_e2e}


def liveness() -> Dict[str, float]:
    """Stage → seconds since its newest span (drains first).  The
    /healthz status section: a stage whose age keeps growing while
    upstream stages stay fresh is the stalled one."""
    flush()
    now = time.monotonic()
    # snapshot first: a concurrent flush() (ThreadingHTTPServer: /metrics
    # scrape vs /healthz probe) may insert a first-seen stage key, and
    # iterating the live dict would raise mid-probe
    snapshot = dict(_last_seen)
    return {stage: round(now - t, 3) for stage, t in sorted(snapshot.items())}


def reset() -> None:
    """Test hook: drop collected spans, liveness and current-trace state
    (the module flag and sampling survive — configure() owns those)."""
    _collector.drain()
    _last_seen.clear()
    _current.ctx = None


configure_from_env()
atexit.register(flush)
