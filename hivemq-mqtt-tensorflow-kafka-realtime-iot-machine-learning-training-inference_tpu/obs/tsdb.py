"""Log-native TSDB — the platform's telemetry stored in its own log.

The reference ships a whole Prometheus beside the pipeline; PR 12's
federation layer scrapes the fleet but compacts only the *latest*
snapshot into ``_IOTML_METRICS`` — no history, no query surface.  This
module closes that gap by dogfooding the store plane as the metrics
backend: every federated scrape appends its samples to a compacted
``_IOTML_TSDB`` topic, and a query engine replays the segment read path
to answer instant/range queries, ``rate()`` and
``histogram_quantile()`` — the telemetry feedback loop ROADMAP item 5
(self-tuning data plane) needs.

Frame layout (ARCHITECTURE §26): one record per (series, chunk window).

- **key** = ``<series id>@<window start ms>`` where the series id is
  the metric name plus its sorted ``k=v`` label pairs — per-series
  keying, so latest-per-key compaction bounds the topic at one record
  per live series per window inside retention (StorePolicy's
  ``retention_ms`` expires whole old windows).
- **value** = JSON ``{"n": name, "l": labels, "t": [t0, dt...],
  "v": [v...]}`` with timestamps delta-encoded against the chunk's
  first sample (scrape cadences are near-constant, so deltas are
  small ints) and raw float values.

Each scrape RE-APPENDS the current window's whole chunk for every
series it touched; compaction keeps only the newest (= most complete)
copy, so the log converges to exactly one record per window without
any read-modify-write on the read path.

``rate()`` detects counter resets (a supervised restart zeroes its
process's counters): a sample below its predecessor contributes its
absolute value as the delta — a reset reads as a reset, never as a
negative rate — and each detection counts into
``iotml_tsdb_resets_total``.
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import metrics as _metrics

#: the compacted telemetry log (key = series id + chunk window).  Like
#: CAR_TWIN (lint R12) this has ONE writer family: the obs package.
TSDB_TOPIC = "_IOTML_TSDB"

#: default chunk window: one record per series per minute under
#: compaction — 60 samples at a 1 s scrape cadence per chunk
DEFAULT_CHUNK_MS = 60_000

#: instant-query lookback: how far behind ``at`` the newest sample may
#: sit and still answer the query (Prometheus's 5 m staleness bound)
DEFAULT_LOOKBACK_MS = 300_000

tsdb_appends = _metrics.default_registry.counter(
    "iotml_tsdb_appends_total",
    "sample-chunk records appended to the _IOTML_TSDB topic")
tsdb_samples = _metrics.default_registry.counter(
    "iotml_tsdb_samples_total",
    "individual samples ingested into the TSDB appender")
tsdb_resets = _metrics.default_registry.counter(
    "iotml_tsdb_resets_total",
    "counter resets detected by rate() (a restarted process's counter "
    "re-starting below its predecessor sample)")
tsdb_series_live = _metrics.default_registry.gauge(
    "iotml_tsdb_series",
    "distinct series the TSDB appender is currently chunking")


# ------------------------------------------------------------- series id
def series_id(name: str, labels: Optional[dict]) -> str:
    """Canonical series identity: name + sorted ``k=v`` pairs.  The
    chunk key prefix, and the dedup identity everywhere."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


# ------------------------------------------------------------- appender
class TsdbAppender:
    """Accumulate scrape samples into per-(series, window) chunks and
    append them to the compacted ``_IOTML_TSDB`` topic.

    Thread-safe; holds only the CURRENT window's chunk per series in
    memory (prior windows are already fully on the log — the last
    append of a window carried every sample it will ever have)."""

    def __init__(self, broker, chunk_ms: int = DEFAULT_CHUNK_MS,
                 retention_ms: Optional[int] = None, partition: int = 0):
        self.broker = broker
        self.chunk_ms = int(chunk_ms)
        self.partition = partition
        self._chunks: Dict[str, dict] = {}
        self._lock = threading.Lock()
        kw = {"cleanup_policy": "compact"}
        if retention_ms is not None:
            # explicit override; otherwise the broker's StorePolicy
            # (IOTML_STORE_* env) governs retention like any topic
            kw["retention_ms"] = int(retention_ms)
        broker.create_topic(TSDB_TOPIC, **kw)

    def append(self, samples: Iterable[tuple],
               ts_ms: Optional[int] = None,
               process: Optional[str] = None) -> int:
        """Ingest one scrape's ``(name, labels, value)`` samples stamped
        at ``ts_ms`` and append the touched chunks; returns the number
        of chunk records produced.  ``process`` is merged into every
        sample's labels (the federation relabel, applied at WRITE time
        so the stored series carry their origin)."""
        if ts_ms is None:
            ts_ms = int(time.time() * 1000)  # wallclock-ok: sample
            # timestamps live in the wall/event-time domain
        window = (ts_ms // self.chunk_ms) * self.chunk_ms
        touched: Dict[str, dict] = {}
        n_samples = 0
        with self._lock:
            for name, labels, value in samples:
                labels = dict(labels or {})
                if process is not None:
                    labels["process"] = process
                sid = series_id(name, labels)
                chunk = self._chunks.get(sid)
                if chunk is None or chunk["w"] != window:
                    # window rollover: the previous window's final copy
                    # is already on the log from its last append
                    chunk = self._chunks[sid] = {
                        "w": window, "n": name, "l": labels,
                        "t": [], "v": []}
                prev_abs = chunk["t"][0] + sum(chunk["t"][1:]) \
                    if chunk["t"] else None
                if prev_abs is not None and ts_ms == prev_abs:
                    # same stamp for this series (two samples in one
                    # scrape pass): last write wins
                    chunk["v"][-1] = float(value)
                elif prev_abs is not None and ts_ms < prev_abs:
                    continue  # out-of-order within a chunk: drop
                else:
                    chunk["t"].append(
                        ts_ms if prev_abs is None else ts_ms - prev_abs)
                    chunk["v"].append(float(value))
                touched[f"{sid}@{chunk['w']}"] = chunk
                n_samples += 1
            # prune series that stopped reporting: anything still
            # parked on a window older than the previous one is dead
            # weight (its final chunk is durable on the log)
            stale = [sid for sid, c in self._chunks.items()
                     if c["w"] < window - self.chunk_ms]
            for sid in stale:
                del self._chunks[sid]
            tsdb_series_live.set(len(self._chunks))
            entries = [
                (key.encode(),
                 json.dumps({"n": c["n"], "l": c["l"],
                             "t": c["t"], "v": c["v"]},
                            sort_keys=True).encode(),
                 ts_ms)
                for key, c in sorted(touched.items())]
        if not entries:
            return 0
        produce_many = getattr(self.broker, "produce_many", None)
        if produce_many is not None:
            produce_many(TSDB_TOPIC, entries, partition=self.partition)
        else:
            for k, v, _ts in entries:
                self.broker.produce(TSDB_TOPIC, v, key=k,
                                    partition=self.partition)
        tsdb_appends.inc(len(entries))
        tsdb_samples.inc(n_samples)
        return len(entries)


# ------------------------------------------------------------- read path
def read_series(broker, start_ms: Optional[int] = None,
                end_ms: Optional[int] = None,
                partition: int = 0) -> Dict[str, dict]:
    """Replay the compacted TSDB topic into
    ``{series id: {"n": name, "l": labels, "samples": [(ts, v)...]}}``
    (samples ascending, deduped — a window re-appended by successive
    scrapes keeps only its newest copy, compaction or not)."""
    out: Dict[str, dict] = {}
    if TSDB_TOPIC not in broker.topics():
        return out
    chunks: Dict[str, dict] = {}  # chunk key → latest doc (log order)
    off = broker.begin_offset(TSDB_TOPIC, partition)
    end = broker.end_offset(TSDB_TOPIC, partition)
    while off < end:
        batch = broker.fetch(TSDB_TOPIC, partition, off, 4096)
        if not batch:
            break
        for m in batch:
            off = m.offset + 1
            if m.key is None:
                continue
            if m.value is None:
                chunks.pop(m.key.decode(), None)  # tombstoned series
                continue
            try:
                chunks[m.key.decode()] = json.loads(m.value)
            except ValueError:
                continue
    return _materialize(chunks, start_ms=start_ms, end_ms=end_ms)


def _materialize(chunks: Dict[str, dict],
                 start_ms: Optional[int] = None,
                 end_ms: Optional[int] = None) -> Dict[str, dict]:
    """Latest chunk docs (key -> doc) into the query-engine series
    shape, decoding the timestamp deltas and applying the time bounds."""
    out: Dict[str, dict] = {}
    for key, doc in chunks.items():
        sid, _, wstr = key.rpartition("@")
        try:
            window = int(wstr)
        except ValueError:
            continue
        if end_ms is not None and window > end_ms:
            continue
        series = out.setdefault(sid, {"n": doc.get("n", ""),
                                      "l": doc.get("l", {}),
                                      "samples": []})
        ts = 0
        for i, (dt, v) in enumerate(zip(doc.get("t", ()),
                                        doc.get("v", ()))):
            ts = dt if i == 0 else ts + dt
            if start_ms is not None and ts < start_ms:
                continue
            if end_ms is not None and ts > end_ms:
                continue
            series["samples"].append((ts, float(v)))
    for series in out.values():
        series["samples"].sort()
    return {sid: s for sid, s in out.items() if s["samples"]}


class TsdbTail:
    """Incremental follower over the TSDB topic for hot-loop readers
    (the SLO engine evaluates every few hundred ms).

    ``read_series`` replays the WHOLE topic per call — fine for a CLI
    query, quadratic for a cadenced evaluator on a growing log.  The
    tail keeps a cursor and a latest-doc-per-chunk-key cache instead:
    the first ``collect`` pays one full replay, every later one decodes
    only the records appended since.  The cache stays bounded by
    dropping chunks whose newest sample fell behind the lookback
    horizon, and (optionally) by a closed set of metric family
    ``names`` — an SLO engine needs its indicators' few families, not
    the fleet's whole registry."""

    def __init__(self, broker, partition: int = 0,
                 names: Optional[Iterable[str]] = None,
                 lookback_ms: Optional[int] = None):
        self.broker = broker
        self.partition = partition
        self.names = frozenset(names) if names is not None else None
        self.lookback_ms = lookback_ms
        self._off: Optional[int] = None
        #: chunk key -> (doc, newest absolute sample ts)
        self._chunks: Dict[str, tuple] = {}

    def collect(self, now_ms: Optional[int] = None) -> Dict[str, dict]:
        """Drain new TSDB records into the cache; return the series
        dict over the lookback horizon (``read_series`` shape)."""
        if now_ms is None:
            now_ms = int(time.time() * 1000)  # wallclock-ok: sample
            # timestamps live in the wall/event-time domain
        if TSDB_TOPIC not in self.broker.topics():
            return {}
        begin = self.broker.begin_offset(TSDB_TOPIC, self.partition)
        if self._off is None or self._off < begin:
            self._off = begin  # first read, or retention expired past us
        end = self.broker.end_offset(TSDB_TOPIC, self.partition)
        while self._off < end:
            batch = self.broker.fetch(TSDB_TOPIC, self.partition,
                                      self._off, 4096)
            if not batch:
                break
            for m in batch:
                self._off = m.offset + 1
                if m.key is None:
                    continue
                key = m.key.decode()
                if m.value is None:
                    self._chunks.pop(key, None)  # tombstoned series
                    continue
                try:
                    doc = json.loads(m.value)
                except ValueError:
                    continue
                if self.names is not None \
                        and doc.get("n") not in self.names:
                    continue
                ts = doc.get("t") or ()
                self._chunks[key] = (doc, ts[0] + sum(ts[1:]) if ts
                                     else 0)
        start_ms = None
        if self.lookback_ms is not None:
            start_ms = now_ms - self.lookback_ms
            dead = [k for k, (_d, last) in self._chunks.items()
                    if last < start_ms]
            for k in dead:
                del self._chunks[k]
        return _materialize({k: d for k, (d, _last)
                             in self._chunks.items()},
                            start_ms=start_ms)


# ------------------------------------------------------------- matchers
class Matcher:
    """One label matcher: ``=``, ``!=``, ``=~``, ``!~`` (anchored
    regex, Prometheus semantics)."""

    __slots__ = ("key", "op", "value", "_re")

    def __init__(self, key: str, op: str, value: str):
        if op not in ("=", "!=", "=~", "!~"):
            raise ValueError(f"unknown matcher op {op!r}")
        self.key, self.op, self.value = key, op, value
        self._re = re.compile(value + r"\Z") if op in ("=~", "!~") \
            else None

    def match(self, labels: dict) -> bool:
        got = str(labels.get(self.key, ""))
        if self.op == "=":
            return got == self.value
        if self.op == "!=":
            return got != self.value
        hit = self._re.match(got) is not None
        return hit if self.op == "=~" else not hit

    def __repr__(self):
        return f"{self.key}{self.op}\"{self.value}\""


_SELECTOR_RE = re.compile(
    r"\s*(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)\s*"
    r"(?:\{(?P<labels>[^}]*)\})?\s*"
    r"(?:\[(?P<window>[0-9]+(?:\.[0-9]+)?[smhd])\])?\s*\Z")
_MATCHER_RE = re.compile(
    r'\s*(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)\s*(?P<op>=~|!~|!=|=)\s*'
    r'"(?P<val>(?:[^"\\]|\\.)*)"\s*(?:,|\Z)')
_DUR = {"s": 1_000, "m": 60_000, "h": 3_600_000, "d": 86_400_000}


def parse_duration_ms(text: str) -> int:
    m = re.match(r"([0-9]+(?:\.[0-9]+)?)([smhd])\Z", text.strip())
    if not m:
        raise ValueError(f"bad duration {text!r} (want e.g. 30s, 5m, 1h)")
    return int(float(m.group(1)) * _DUR[m.group(2)])


def _unescape(text: str) -> str:
    return (text.replace("\\\\", "\x00").replace('\\"', '"')
            .replace("\\n", "\n").replace("\x00", "\\"))


def parse_selector(text: str) -> Tuple[str, List[Matcher], Optional[int]]:
    """``name{k="v",k2=~"re"}[5m]`` → (name, matchers, window_ms)."""
    m = _SELECTOR_RE.match(text)
    if not m:
        raise ValueError(f"bad selector {text!r}")
    matchers: List[Matcher] = []
    lab = m.group("labels")
    if lab:
        pos = 0
        while pos < len(lab.strip()):
            mm = _MATCHER_RE.match(lab, pos)
            if not mm:
                raise ValueError(f"bad matcher in {text!r} at {lab[pos:]!r}")
            matchers.append(Matcher(mm.group("key"), mm.group("op"),
                                    _unescape(mm.group("val"))))
            pos = mm.end()
    window = m.group("window")
    return (m.group("name"), matchers,
            parse_duration_ms(window) if window else None)


def select(series: Dict[str, dict], name: str,
           matchers: Sequence[Matcher] = ()) -> List[dict]:
    """Series whose metric name equals ``name`` and whose labels pass
    every matcher."""
    out = []
    for s in series.values():
        if s["n"] != name:
            continue
        if all(m.match(s["l"]) for m in matchers):
            out.append(s)
    return sorted(out, key=lambda s: sorted(s["l"].items()))


# --------------------------------------------------------------- queries
def instant(series: Dict[str, dict], name: str,
            matchers: Sequence[Matcher] = (),
            at_ms: Optional[int] = None,
            lookback_ms: int = DEFAULT_LOOKBACK_MS) -> List[dict]:
    """Newest sample per matching series at (or before) ``at_ms``,
    within the staleness lookback: ``[{labels, ts_ms, value}]``."""
    out = []
    for s in select(series, name, matchers):
        best = None
        for ts, v in s["samples"]:
            if at_ms is not None and ts > at_ms:
                break
            best = (ts, v)
        if best is None:
            continue
        if at_ms is not None and best[0] < at_ms - lookback_ms:
            continue
        out.append({"labels": s["l"], "ts_ms": best[0],
                    "value": best[1]})
    return out


def range_query(series: Dict[str, dict], name: str,
                matchers: Sequence[Matcher] = (),
                start_ms: int = 0, end_ms: int = 0,
                step_ms: int = 15_000,
                lookback_ms: int = DEFAULT_LOOKBACK_MS) -> List[dict]:
    """Evaluate the instant query at every step across [start, end]:
    ``[{labels, values: [(ts_ms, value)...]}]`` (staleness-bounded
    last-observed carry, Prometheus range semantics)."""
    step_ms = max(int(step_ms), 1)
    out = []
    for s in select(series, name, matchers):
        pts = []
        i = 0
        samples = s["samples"]
        last = None
        t = start_ms
        while t <= end_ms:
            while i < len(samples) and samples[i][0] <= t:
                last = samples[i]
                i += 1
            if last is not None and last[0] >= t - lookback_ms:
                pts.append((t, last[1]))
            t += step_ms
        if pts:
            out.append({"labels": s["l"], "values": pts})
    return out


def _reset_corrected_increase(samples: List[tuple]) -> Tuple[float, int]:
    """Total counter increase over ascending samples with reset
    correction: a drop means the process restarted and the counter
    re-started from (near) zero, so the post-reset absolute value IS
    the delta.  Returns (increase, resets_detected)."""
    inc = 0.0
    resets = 0
    for (t0, v0), (t1, v1) in zip(samples, samples[1:]):
        if v1 >= v0:
            inc += v1 - v0
        else:
            resets += 1
            inc += v1
    return inc, resets


def rate(series: Dict[str, dict], name: str,
         matchers: Sequence[Matcher] = (),
         window_ms: int = 300_000,
         at_ms: Optional[int] = None) -> List[dict]:
    """Per-second rate of a counter over the trailing window, with
    counter-reset detection (never negative): ``[{labels, value,
    resets}]``.  Detected resets count into iotml_tsdb_resets_total."""
    out = []
    for s in select(series, name, matchers):
        hi = at_ms if at_ms is not None \
            else (s["samples"][-1][0] if s["samples"] else 0)
        lo = hi - window_ms
        win = [(t, v) for t, v in s["samples"] if lo <= t <= hi]
        if len(win) < 2:
            continue
        inc, resets = _reset_corrected_increase(win)
        if resets:
            tsdb_resets.inc(resets)
        span_s = (win[-1][0] - win[0][0]) / 1000.0
        if span_s <= 0:
            continue
        out.append({"labels": s["l"], "value": inc / span_s,
                    "resets": resets})
    return out


def increase(series: Dict[str, dict], name: str,
             matchers: Sequence[Matcher] = (),
             window_ms: int = 300_000,
             at_ms: Optional[int] = None) -> List[dict]:
    """Reset-corrected total increase over the trailing window —
    ``rate() * span`` without the division; what burn-rate ratios
    consume (``[{labels, value, resets}]``)."""
    out = []
    for s in select(series, name, matchers):
        hi = at_ms if at_ms is not None \
            else (s["samples"][-1][0] if s["samples"] else 0)
        lo = hi - window_ms
        win = [(t, v) for t, v in s["samples"] if lo <= t <= hi]
        if not win:
            continue
        if len(win) == 1:
            # one sample inside the window: the increase since the
            # window opened is unknowable; treat as zero (conservative)
            out.append({"labels": s["l"], "value": 0.0, "resets": 0})
            continue
        inc, resets = _reset_corrected_increase(win)
        if resets:
            tsdb_resets.inc(resets)
        out.append({"labels": s["l"], "value": inc, "resets": resets})
    return out


def histogram_quantile(series: Dict[str, dict], q: float, family: str,
                       matchers: Sequence[Matcher] = (),
                       at_ms: Optional[int] = None,
                       window_ms: Optional[int] = None) -> List[dict]:
    """Prometheus-style quantile interpolation from a native Histogram's
    cumulative ``<family>_bucket{le=...}`` series.

    ``window_ms`` set: quantile of the OBSERVATIONS INSIDE the window
    (bucket counts as reset-corrected increases — the burn-rate /
    drill shape).  Unset: quantile of the all-time cumulative counts
    at ``at_ms``.  Grouped by the non-``le`` label sets:
    ``[{labels, value}]``; linear interpolation inside the winning
    bucket, so the answer is exact to bucket width."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    bname = family if family.endswith("_bucket") else family + "_bucket"
    groups: Dict[tuple, List[Tuple[float, float]]] = {}
    for s in select(series, bname, matchers):
        le = s["l"].get("le")
        if le is None:
            continue
        try:
            edge = float(le)
        except ValueError:
            continue
        if window_ms is not None:
            res = increase({series_id(s["n"], s["l"]): s}, bname,
                           window_ms=window_ms, at_ms=at_ms)
            if not res:
                continue
            count = res[0]["value"]
        else:
            snap = instant({series_id(s["n"], s["l"]): s}, bname,
                           at_ms=at_ms)
            if not snap:
                continue
            count = snap[0]["value"]
        key = tuple(sorted((k, v) for k, v in s["l"].items()
                           if k != "le"))
        groups.setdefault(key, []).append((edge, count))
    out = []
    for key, buckets in sorted(groups.items()):
        buckets.sort()
        if not buckets:
            continue
        total = buckets[-1][1]  # +Inf bucket is the observation count
        if total <= 0:
            continue
        rank = q * total
        value = None
        prev_edge, prev_count = 0.0, 0.0
        for edge, count in buckets:
            if count >= rank:
                if edge == float("inf"):
                    # quantile lands in the overflow bucket: the best
                    # honest answer is the highest finite edge
                    value = prev_edge
                else:
                    span = count - prev_count
                    frac = (rank - prev_count) / span if span > 0 else 0.0
                    value = prev_edge + (edge - prev_edge) * frac
                break
            prev_edge, prev_count = edge, count
        if value is not None:
            out.append({"labels": dict(key), "value": value})
    return out


# --------------------------------------------------------- expression API
_FUNC_RE = re.compile(
    r"\s*(?P<fn>rate|increase)\s*\(\s*(?P<sel>[^()]+)\s*\)\s*\Z")
_QUANTILE_RE = re.compile(
    r"\s*histogram_quantile\s*\(\s*(?P<q>[0-9.]+)\s*,"
    r"\s*(?P<sel>[^()]+)\s*\)\s*\Z")


def query(series: Dict[str, dict], expr: str,
          at_ms: Optional[int] = None,
          start_ms: Optional[int] = None, end_ms: Optional[int] = None,
          step_ms: int = 15_000) -> List[dict]:
    """The one expression entry point the REST surface and the CLI
    share.  Supported forms::

        metric{label="v",other=~"regex"}
        rate(metric_total{...}[5m])
        increase(metric_total{...}[5m])
        histogram_quantile(0.95, metric_seconds{...})
        histogram_quantile(0.95, metric_seconds{...}[5m])

    Instant evaluation unless BOTH start_ms and end_ms are given, in
    which case the plain-selector form evaluates as a range query and
    the function forms evaluate at every step."""
    ranged = start_ms is not None and end_ms is not None
    qm = _QUANTILE_RE.match(expr)
    if qm:
        name, matchers, window = parse_selector(qm.group("sel"))
        if name.endswith("_bucket"):
            name = name[:-len("_bucket")]
        qv = float(qm.group("q"))
        if not ranged:
            return histogram_quantile(series, qv, name, matchers,
                                      at_ms=at_ms, window_ms=window)
        return _stepped(lambda t: histogram_quantile(
            series, qv, name, matchers, at_ms=t, window_ms=window),
            start_ms, end_ms, step_ms)
    fm = _FUNC_RE.match(expr)
    if fm:
        name, matchers, window = parse_selector(fm.group("sel"))
        if window is None:
            raise ValueError(
                f"{fm.group('fn')}() needs a [window], e.g. "
                f"{fm.group('fn')}({name}[5m])")
        fn = rate if fm.group("fn") == "rate" else increase
        if not ranged:
            return fn(series, name, matchers, window_ms=window,
                      at_ms=at_ms)
        return _stepped(lambda t: fn(series, name, matchers,
                                     window_ms=window, at_ms=t),
                        start_ms, end_ms, step_ms)
    name, matchers, window = parse_selector(expr)
    if window is not None:
        raise ValueError("a bare selector takes no [window] — use "
                         "rate()/increase(), or query_range")
    if not ranged:
        return instant(series, name, matchers, at_ms=at_ms)
    return range_query(series, name, matchers, start_ms=start_ms,
                       end_ms=end_ms, step_ms=step_ms)


def _stepped(evaluate, start_ms: int, end_ms: int,
             step_ms: int) -> List[dict]:
    """Evaluate an instant function at every step; regroup per label
    set into range-shaped ``[{labels, values}]``."""
    step_ms = max(int(step_ms), 1)
    acc: Dict[tuple, List[tuple]] = {}
    labels_of: Dict[tuple, dict] = {}
    t = start_ms
    while t <= end_ms:
        for r in evaluate(t):
            key = tuple(sorted(r["labels"].items()))
            labels_of[key] = r["labels"]
            acc.setdefault(key, []).append((t, r["value"]))
        t += step_ms
    return [{"labels": labels_of[k], "values": v}
            for k, v in sorted(acc.items())]
