"""Event-time watermarks for the columnar data plane (ISSUE 13).

PR 2's per-record trace spans measure pipeline latency by riding each
record — which is exactly what the zero-copy columnar plane (PRs 10/11)
makes impossible: ``poll_into``/``FrameDecoder`` materialise ZERO Python
records, and wire/native transports drop record headers by design.  But
every store frame already carries the record's timestamp in its fixed
head, so the decoder reports per-batch event-time min/max as a free
by-product of the walk it does anyway.  Each consuming stage then
publishes, batch-granularly:

- ``iotml_watermark_lag_seconds{stage,topic,partition}`` — histogram of
  (now - event time) at the stage's progress frontier.  Observed for
  the batch's min AND max event time, so the distribution brackets the
  true per-record e2e latency from both sides at zero per-record cost.
- ``iotml_watermark_event_time_ms{stage,topic,partition}`` — the
  watermark itself: the newest event timestamp the stage has fully
  processed (the /healthz staleness view and the federation rollup's
  worst-of input).

Stage vocabulary is CLOSED (lint R6 / the cardinality-bound test):
``consume`` (the consumer frontier, observed inside ``poll``/
``poll_into``), ``score`` / ``train`` / ``twin`` (observed by the
scorer, the trainers, and the twin service when a drain completes — so
a ``score`` observation really means "every record up to this event
time has been scored", the ingest→score semantics PR 2's spans carried
per record).

The wall clock is the correct domain here — record timestamps are wall
timestamps stamped at ingest — so lag compares wall-to-wall (the usual
distributed-watermark clock-skew caveat applies across hosts).  Process
toggle: ``IOTML_WATERMARK=0`` disables even the batch-granular cost
(registered in config's ``non_config`` set).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

from . import metrics as _metrics

#: module flag every publishing site guards on (cheap module read;
#: watermarks are batch-granular so they default ON, unlike tracing)
ENABLED = True

#: closed stage vocabulary — the cardinality test pins label values to
#: this set, and helpers below reject anything outside it loudly
STAGES = frozenset({"consume", "score", "train", "twin"})


def configure(enabled: Optional[bool] = None) -> None:
    global ENABLED
    if enabled is not None:
        ENABLED = bool(enabled)


def configure_from_env(env: Optional[Dict[str, str]] = None) -> None:
    env = os.environ if env is None else env
    raw = env.get("IOTML_WATERMARK")
    if raw is not None:
        configure(enabled=raw.strip().lower() not in
                  ("0", "false", "no", "off"))


def observe(stage: str, topic: str, partition: int,
            ts_min_ms: int, ts_max_ms: int,
            group: str = "",
            now_ms: Optional[float] = None) -> None:
    """Record one consumed batch's event-time bounds for `stage`.

    ``ts_min_ms``/``ts_max_ms`` are the decoder-reported bounds (-1 =
    nothing consumed: a no-op).  ``group`` is the consumer group: two
    consumers of the same partition (a trainer and a scorer in one
    process) are different frontiers, and without the label the gauge
    would flap between them.  Two histogram observations + one gauge
    set per batch — the whole cost, independent of batch size."""
    if not ENABLED or ts_max_ms is None or ts_max_ms < 0:
        return
    if stage not in STAGES:
        raise ValueError(f"watermark stage {stage!r} outside the closed "
                         f"set {sorted(STAGES)}")
    if now_ms is None:
        now_ms = time.time() * 1000.0  # wallclock-ok: event timestamps
        # are wall-domain; this is a latency measurement, not a deadline
    # the watermark gauge is MONOTONE: "newest event time fully
    # processed" must never regress when a later batch happens to end
    # on an older event timestamp (store-and-forward re-deliveries).
    # Benign read-then-set race between drainers: both write forward.
    labels = dict(stage=stage, topic=topic, partition=partition,
                  group=group)
    if ts_max_ms > _metrics.watermark_event_ms.value(**labels):
        _metrics.watermark_event_ms.set(ts_max_ms, **labels)
    h = _metrics.watermark_lag_seconds
    h.observe(max(now_ms - ts_max_ms, 0.0) / 1000.0,
              stage=stage, topic=topic, partition=partition, group=group)
    if ts_min_ms is not None and 0 <= ts_min_ms < ts_max_ms:
        h.observe(max(now_ms - ts_min_ms, 0.0) / 1000.0,
                  stage=stage, topic=topic, partition=partition,
                  group=group)


def observe_taken(stage: str,
                  taken: Dict[Tuple[str, int], Tuple[int, int]],
                  group: str = "") -> None:
    """Publish a processing stage's completion watermark from the
    event-time ranges a ``StreamConsumer.take_event_time()`` call
    returned — the scorer/trainer/twin idiom: take at the drain/commit
    boundary, where "consumed" has become "processed"."""
    if not ENABLED or not taken:
        return
    now_ms = time.time() * 1000.0  # wallclock-ok: see observe()
    for (topic, partition), (ts_min, ts_max) in taken.items():
        observe(stage, topic, partition, ts_min, ts_max, group=group,
                now_ms=now_ms)


configure_from_env()
