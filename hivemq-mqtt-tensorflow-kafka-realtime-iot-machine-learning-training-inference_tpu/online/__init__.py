"""iotml.online — true online learning with drift-triggered adaptation.

The reference is explicit that it does micro-batch streaming ingestion,
*not* online learning (reference README.md:130-140).  This package goes
past it:

- ``OnlineLearner``: per-record/small-window SGD folded into the
  consume loop — every polled window is one fixed-shape jitted update,
  reusing ``ContinuousTrainer``'s cursor/commit discipline so
  offsets-as-checkpoint still holds;
- ``PageHinkley`` / ``AdaptiveWindow`` (ADWIN-style) streaming drift
  detectors over the reconstruction-error signal, composed by
  ``DriftMonitor`` into a STABLE → ADAPTING → STABLE state machine;
- drift-triggered adaptation (``AdaptationPolicy``): learning-rate
  boost, detector-window reset, or replay-buffer re-fit — each adapted
  model published through the ``iotml.mlops`` ``ModelRegistry`` so the
  scorer fleet hot-swaps it via the existing ``RegistryWatcher``, with
  the A/B rollback gate protecting against a bad adaptation.

Proof lives in ``iotml.online.drill`` (the live drift-adapt-swap
drill), the ``drift-storm`` chaos scenario, and ``bench_online``'s
online-vs-micro-batch comparison.  Lint rule R13 keeps model updates
flowing through the registry — no in-place ``set_params`` on a serving
scorer outside the mlops/online machinery.
"""

from .detectors import (ADAPTING, STABLE, AdaptiveWindow, DriftMonitor,
                        PageHinkley)
from .learner import AdaptationPolicy, OnlineLearner

__all__ = ["AdaptiveWindow", "AdaptationPolicy", "ADAPTING",
           "DriftMonitor", "OnlineLearner", "PageHinkley", "STABLE"]
