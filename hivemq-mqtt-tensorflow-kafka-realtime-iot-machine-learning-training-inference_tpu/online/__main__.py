"""``python -m iotml.online`` — online-learning CLI.

    python -m iotml.online drill [--seed S] [--records N] [--json]
                                 [--slo-detect-records N]
    python -m iotml.online run --topic T [--registry DIR] [--window N]
                               [--detector ph|adwin|both]
                               [--max-seconds S]
    python -m iotml.online list

``drill`` runs the LIVE drift-adapt-swap drill (seeded regional drift
→ detect → adapt → publish → fleet hot-swap → AUC recovery → rollback
gate rejects a wrecked adaptation) and exits with the invariant
verdict.  CI (online.yml) and deploy/smoke.sh run exactly this.
``run`` attaches an OnlineLearner to a live broker (the platform CLI's
stream leg) and trains until stopped.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.online",
        description="true online learning: incremental updates, drift "
                    "detection, drift-triggered adaptation")
    sub = ap.add_subparsers(dest="cmd")
    dp = sub.add_parser("drill", help="run the live drift-adapt-swap "
                                      "drill; exit status = verdict")
    dp.add_argument("--drill", default="drift-adapt-swap",
                    help="drill name (see `list`)")
    dp.add_argument("--seed", type=int, default=7)
    dp.add_argument("--records", type=int, default=0,
                    help="records to pump (0 = the drill's default)")
    dp.add_argument("--slo-detect-records", type=int, default=1500,
                    help="max records between drift onset and detection")
    dp.add_argument("--json", action="store_true")
    rp = sub.add_parser("run", help="attach an online learner to a "
                                    "live broker")
    rp.add_argument("--servers", default="127.0.0.1:9092",
                    help="bootstrap host:port list (kafka wire)")
    rp.add_argument("--topic", default="SENSOR_DATA_S_AVRO")
    rp.add_argument("--group", default="cardata-online")
    rp.add_argument("--registry", default="",
                    help="model-registry root (default: "
                         "IOTML_MLOPS_REGISTRY_DIR)")
    rp.add_argument("--window", type=int, default=0,
                    help="records per incremental update "
                         "(0 = config online.window)")
    rp.add_argument("--detector", default="",
                    choices=("", "ph", "adwin", "both"))
    rp.add_argument("--max-seconds", type=float, default=0.0,
                    help="stop after this long (0 = run forever)")
    sub.add_parser("list", help="list available drills")
    args = ap.parse_args(argv)

    from .drill import DRILLS

    if args.cmd == "list":
        for name, fn in sorted(DRILLS.items()):
            doc = (fn.__doc__ or "").strip().splitlines()[0]
            print(f"{name:<18} {doc}")
        return 0
    if args.cmd == "run":
        return _run(args)
    if args.cmd != "drill":
        ap.print_help()
        return 2
    if args.drill not in DRILLS:
        print(f"unknown drill {args.drill!r}; have: {sorted(DRILLS)}",
              file=sys.stderr)
        return 2
    kw = {"seed": args.seed,
          "slo_detect_records": args.slo_detect_records}
    if args.records:
        kw["records"] = args.records
    report = DRILLS[args.drill](**kw)
    if args.json:
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True,
                         default=str))
    else:
        print("\n".join(report.lines()))
    return 0 if report.ok else 1


def _run(args) -> int:
    from ..config import load_config
    from ..mlops import ModelRegistry
    from ..online.detectors import DriftMonitor
    from ..online.learner import AdaptationPolicy, OnlineLearner
    from ..stream.kafka_wire import KafkaWireBroker

    cfg, _ = load_config([])
    oc = cfg.online
    registry_root = args.registry or cfg.mlops.registry_dir
    registry = ModelRegistry(registry_root) if registry_root else None
    broker = KafkaWireBroker(args.servers)
    monitor = DriftMonitor(
        detector=args.detector or oc.detector,
        ph_delta=oc.ph_delta, ph_threshold=oc.ph_threshold,
        adwin_delta=oc.adwin_delta)
    policy = AdaptationPolicy(
        action=oc.adapt, lr_boost=oc.lr_boost,
        boost_updates=oc.boost_updates, refit_epochs=oc.refit_epochs)
    learner = OnlineLearner(
        broker, args.topic, registry=registry, group=args.group,
        window=args.window or oc.window, monitor=monitor,
        policy=policy, buffer_batches=oc.buffer_batches,
        publish_every=oc.publish_every)

    def on_update(d):
        print(json.dumps({"updates": d["updates"], "loss": d["loss"],
                          "lr": d["lr"],
                          "drifts": d["monitor"]["drifts"],
                          "state": d["monitor"]["state"]}), flush=True)

    try:
        learner.run(max_seconds=args.max_seconds or None,
                    on_update=on_update)
    except KeyboardInterrupt:
        pass
    finally:
        learner.close()
    print(json.dumps(learner.describe(), default=str), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
