"""Streaming drift detectors — pure, seeded-testable units.

The online learner's drift signal is the reconstruction-error stream:
an autoencoder trained on one sensor distribution reconstructs a
shifted distribution badly, so a sustained error increase IS the drift
(the converse — a model improving on a stationary stream — only ever
moves the signal down, which neither detector fires on).

Two classic detectors, both O(1)-ish per update and free of wall
clocks (determinism discipline of iotml.chaos):

- ``PageHinkley``: the one-sided Page-Hinkley test — cumulative
  deviation above the running mean minus a drift allowance ``delta``;
  fires when the deviation exceeds ``threshold``.  Cheap and fast on
  abrupt (step) drift.
- ``AdaptiveWindow``: an ADWIN-style adaptive window (Bifet & Gavaldà
  2007): exponential bucket compression keeps O(log n) state, and the
  window drops its oldest buckets whenever two sub-windows disagree in
  mean beyond a Hoeffding-like cut ``epsilon(delta)``.  Catches slow
  ramps Page-Hinkley's allowance absorbs, and its post-cut window is
  exactly the "recent distribution" a window-reset adaptation wants.

``DriftMonitor`` composes both over the smoothed error signal and owns
the adaptation state machine (STABLE → ADAPTING → STABLE) plus the
baseline band convergence is judged against.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple


class PageHinkley:
    """One-sided (increase-detecting) Page-Hinkley test.

    Args:
      delta: drift allowance per observation — deviations below it
        never accumulate (robustness to noise).
      threshold: the PH statistic level that signals drift (lambda in
        the literature).  Scale both to the signal's units.
      burn_in: observations before the test may fire (the running mean
        is meaningless on the first few points).
    """

    def __init__(self, delta: float = 0.005, threshold: float = 0.5,
                 burn_in: int = 10):
        self.delta = float(delta)
        self.threshold = float(threshold)
        self.burn_in = int(burn_in)
        self.reset()

    def reset(self) -> None:
        self.n = 0
        self.mean = 0.0
        self._cum = 0.0
        self._cum_min = 0.0
        self.stat = 0.0

    def update(self, x: float) -> bool:
        """Feed one observation; True when the test fires.  The caller
        owns the reset — a fired test keeps firing until reset()."""
        self.n += 1
        self.mean += (x - self.mean) / self.n
        self._cum += x - self.mean - self.delta
        self._cum_min = min(self._cum_min, self._cum)
        self.stat = self._cum - self._cum_min
        return self.n > self.burn_in and self.stat > self.threshold


class AdaptiveWindow:
    """ADWIN-style adaptive sliding window over a bounded-state sketch.

    State is rows of exponentially-sized buckets (row i buckets cover
    ``2**i`` observations, at most ``max_buckets`` per row), so a
    million-observation window costs ~log2(n) * max_buckets tuples.
    Every ``check_every`` updates the window is scanned at bucket
    boundaries: if some split has |mean(old) − mean(recent)| above the
    Hoeffding-like cut, the old side is dropped — the window *adapts*
    to hold only the post-change distribution.
    """

    def __init__(self, delta: float = 0.002, max_buckets: int = 5,
                 min_window: int = 16, check_every: int = 4):
        self.delta = float(delta)
        self.max_buckets = int(max_buckets)
        self.min_window = int(min_window)
        self.check_every = max(1, int(check_every))
        self.reset()

    def reset(self) -> None:
        # rows[i] = list of (sum, sumsq, count) buckets, count == 2**i
        # each; rows[0] is the newest (per-observation) row.  Within a
        # row, index 0 is the OLDEST bucket.
        self._rows: List[List[Tuple[float, float, int]]] = [[]]
        self.total = 0.0
        self.total_sq = 0.0
        self.width = 0
        self._since_check = 0
        self.last_cut: Optional[int] = None  # width dropped by last cut

    # ----------------------------------------------------------- update
    @property
    def mean(self) -> float:
        return self.total / self.width if self.width else 0.0

    @property
    def variance(self) -> float:
        if self.width < 2:
            return 0.0
        return max(0.0, self.total_sq / self.width - self.mean ** 2)

    def update(self, x: float) -> bool:
        """Feed one observation; True when the window cut (drift)."""
        x = float(x)
        self._rows[0].append((x, x * x, 1))
        self.total += x
        self.total_sq += x * x
        self.width += 1
        self._compress()
        self._since_check += 1
        if self._since_check < self.check_every \
                or self.width < self.min_window:
            return False
        self._since_check = 0
        return self._cut()

    def _compress(self) -> None:
        """Merge row overflow upward: 2 oldest buckets of row i become
        1 bucket of row i+1 (classic ADWIN bucket maintenance)."""
        i = 0
        while i < len(self._rows):
            row = self._rows[i]
            if len(row) <= self.max_buckets:
                break
            if i + 1 == len(self._rows):
                self._rows.append([])
            a, b = row.pop(0), row.pop(0)
            self._rows[i + 1].append((a[0] + b[0], a[1] + b[1],
                                      a[2] + b[2]))
            i += 1

    def _buckets_old_first(self) -> List[Tuple[float, float, int]]:
        """Every bucket, oldest → newest (rows store coarse=old last)."""
        out: List[Tuple[float, float, int]] = []
        for row in reversed(self._rows):
            out.extend(row)
        return out

    def _cut(self) -> bool:
        """Scan split points oldest-first; drop the old side of the
        first split whose mean gap beats the variance-adaptive
        epsilon_cut (the ADWIN2 bound — scale-aware, so raw error
        signals work without pre-normalization)."""
        buckets = self._buckets_old_first()
        if len(buckets) < 2:
            return False
        n, tot = self.width, self.total
        var = self.variance
        dp = math.log(2.0 * math.log(max(n, 3)) / self.delta)
        n0 = 0.0
        s0 = 0.0
        cut_at = None
        for i in range(len(buckets) - 1):
            s0 += buckets[i][0]
            n0 += buckets[i][2]
            n1 = n - n0
            if n0 < 2 or n1 < 2:
                continue
            m = 1.0 / (1.0 / n0 + 1.0 / n1)  # harmonic mean of sizes
            eps = math.sqrt((2.0 / m) * var * dp) + (2.0 / (3.0 * m)) * dp
            if abs(s0 / n0 - (tot - s0) / n1) > eps:
                cut_at = i
        if cut_at is None:
            return False
        dropped = buckets[: cut_at + 1]
        kept = buckets[cut_at + 1:]
        self.last_cut = int(sum(c for _s, _q, c in dropped))
        # rebuild rows from the kept buckets (oldest-first input)
        self._rows = [[]]
        self.total = 0.0
        self.total_sq = 0.0
        self.width = 0
        for s, q, c in kept:
            row = max(0, (c - 1).bit_length() if c > 1 else 0)
            while len(self._rows) <= row:
                self._rows.append([])
            self._rows[row].append((s, q, c))
            self.total += s
            self.total_sq += q
            self.width += c
        return True


#: DriftMonitor states
STABLE = "stable"
ADAPTING = "adapting"


class DriftMonitor:
    """Both detectors over the (EWMA-smoothed) error signal + the
    adaptation state machine.

    The raw signal is reconstruction error per update window; the
    monitor feeds detectors the signal NORMALIZED by its own stable
    baseline (so detector thresholds are scale-free: "error rose to
    1.5× its stable level" means the same at any absolute error).

    States:
      STABLE   — tracking the baseline; detectors armed.
      ADAPTING — a drift fired; the learner is adapting.  Detectors
        are quiet (an adaptation transient must not re-trigger), and
        the monitor watches for convergence: the smoothed error back
        inside ``converge_ratio`` × baseline, at which point the
        baseline re-anchors to the NEW normal and the state returns to
        STABLE.
    """

    def __init__(self, detector: str = "both",
                 ph_delta: float = 0.15, ph_threshold: float = 2.5,
                 adwin_delta: float = 0.002,
                 level_ratio: float = 1.25, level_windows: int = 4,
                 ewma_alpha: float = 0.3, baseline_alpha: float = 0.05,
                 converge_ratio: float = 1.5, burn_in: int = 12,
                 max_adapting_updates: int = 400):
        if detector not in ("ph", "adwin", "both"):
            raise ValueError(f"detector must be ph|adwin|both, "
                             f"got {detector!r}")
        self.detector = detector
        self.ph = PageHinkley(delta=ph_delta, threshold=ph_threshold,
                              burn_in=burn_in)
        self.adwin = AdaptiveWindow(delta=adwin_delta)
        #: the LEVEL rule, the monitor's own safety net on top of the
        #: change detectors: an ONLINE learner self-heals a drift at
        #: its base learning rate, so Page-Hinkley's running mean can
        #: catch a slowly-fading excursion before the statistic trips
        #: (measured: a +36% step peaked at PH 2.18 against threshold
        #: 2.5 and decayed).  Sustained smoothed error >= level_ratio
        #: x baseline for level_windows consecutive windows IS drift,
        #: however the change statistics wander.  level_ratio=0
        #: disables (pure-detector unit tests).
        self.level_ratio = float(level_ratio)
        self.level_windows = int(level_windows)
        self._level_run = 0
        self.ewma_alpha = float(ewma_alpha)
        self.baseline_alpha = float(baseline_alpha)
        self.converge_ratio = float(converge_ratio)
        #: fraction of the drift excursion that must heal before an
        #: episode converges: with only the ratio test, a mild drift
        #: (+30%, under converge_ratio) would "converge" on its first
        #: ADAPTING update and cancel its own boost — convergence must
        #: mean the error CAME BACK, not that it never rose far.  Half,
        #: not most: a drifted MIXTURE's reachable floor sits above the
        #: pre-drift floor (more cohorts = harder modeling task), and a
        #: target under that floor would pin the episode at its
        #: timeout instead of at the model's actual recovery
        self.heal_frac = 0.5
        self.burn_in = int(burn_in)
        #: hard bound on the ADAPTING dwell: convergence is a quality
        #: judgement, and a model that CANNOT recover (e.g. drift
        #: beyond its capacity) must not disarm detection forever
        self.max_adapting_updates = int(max_adapting_updates)
        self.state = STABLE
        self.n = 0
        self.ewma: Optional[float] = None
        self.baseline: Optional[float] = None
        self.drifts = 0
        self.converged = 0
        self._adapting_for = 0
        self._episode_peak = 0.0
        self.last_signal: Optional[str] = None

    # ------------------------------------------------------------ feed
    def severity(self) -> float:
        """Current smoothed error over the stable baseline (>= 1 at
        drift time; the policy's mild-vs-severe discriminator)."""
        if not self.baseline or self.ewma is None:
            return 1.0
        return max(1.0, self.ewma / self.baseline)

    def _normalized(self, x: float) -> float:
        return x / self.baseline if self.baseline else 1.0

    def update(self, err: float) -> Optional[str]:
        """Feed one error observation (one learner update window).
        Returns "ph" | "adwin" when a NEW drift fires (once per
        episode), else None."""
        self.n += 1
        self.ewma = err if self.ewma is None else \
            self.ewma + self.ewma_alpha * (err - self.ewma)
        if self.state == ADAPTING:
            self._adapting_for += 1
            base = self.baseline or self.ewma
            self._episode_peak = max(self._episode_peak, self.ewma)
            # healed = back inside the stable band AND most of the
            # excursion gone (the min of the two targets binds: the
            # ratio test for big drifts, the heal fraction for mild
            # ones — see heal_frac)
            target = min(self.converge_ratio * base,
                         base + self.heal_frac
                         * max(self._episode_peak - base, 0.0))
            done = self.ewma <= max(target, base)
            if done or self._adapting_for >= self.max_adapting_updates:
                if done:
                    self.converged += 1
                self._stabilize()
            return None
        if self.n <= self.burn_in or self.baseline is None:
            # establish the baseline before arming: the first windows
            # of a cold-started model are their own transient
            self.baseline = self.ewma if self.baseline is None else \
                self.baseline + self.baseline_alpha * (self.ewma
                                                       - self.baseline)
            self.adwin.update(self._normalized(self.ewma))
            return None
        # detectors see the SMOOTHED signal normalized by the stable
        # baseline: smoothing keeps single-window noise from walking
        # Page-Hinkley over its threshold, normalization makes the
        # thresholds scale-free.  The baseline follows the signal DOWN
        # (a continuously-training model keeps improving, and judging
        # drift against a stale high baseline would mute detection) but
        # never UP while stable — an error increase must be measured
        # against the pre-drift normal, not a mean the drift itself has
        # already dragged up; the baseline re-anchors upward only on
        # post-adaptation convergence.
        if self.ewma < self.baseline:
            self.baseline += self.baseline_alpha * (self.ewma
                                                    - self.baseline)
        x = self._normalized(self.ewma)
        fired = None
        if self.detector in ("ph", "both") and self.ph.update(x):
            fired = "ph"
        # ADWIN is two-sided (any mean change cuts the window) but only
        # an INCREASE is drift here — a continuously-training model's
        # error declining is the system working.  Gate its fire on the
        # signal sitting meaningfully above the stable band: the
        # baseline ratchets on the smoothed MINIMUM, so normalized
        # noise rides slightly above 1.0 by construction, and a gate
        # halfway to the level rule's threshold clears it.
        adwin_gate = (1.0 + self.level_ratio) / 2.0 \
            if self.level_ratio > 0 else 1.1
        if self.detector in ("adwin", "both") and self.adwin.update(x) \
                and x > adwin_gate and fired is None:
            fired = "adwin"
        if self.level_ratio > 0:
            self._level_run = self._level_run + 1 \
                if x >= self.level_ratio else 0
            if self._level_run >= self.level_windows and fired is None:
                fired = "level"
        if fired is not None:
            self.drifts += 1
            self.last_signal = fired
            self.state = ADAPTING
            self._adapting_for = 0
            self._episode_peak = self.ewma
        return fired

    def begin_episode(self, signal: str) -> None:
        """Externally-signalled drift: enter the ADAPTING episode
        exactly as an internal fire would, handing the convergence /
        re-anchor machinery the episode.  The mesh learner's PER-CHIP
        detectors (ISSUE 15) come through here — each chip watches its
        own shard's loss, but the data-parallel model is ONE model, so
        any chip's drift is the fleet's drift and a no-op while already
        adapting keeps N chips tripping on one drift to ONE episode."""
        if self.state == ADAPTING:
            return
        self.drifts += 1
        self.last_signal = signal
        self.state = ADAPTING
        self._adapting_for = 0
        self._episode_peak = self.ewma if self.ewma is not None else 0.0

    # ----------------------------------------------------- transitions
    def _stabilize(self) -> None:
        """Adaptation over: re-anchor the baseline to the new normal
        and re-arm the detectors on fresh windows."""
        self.state = STABLE
        self.baseline = self.ewma
        self.reset_windows()

    def reset_windows(self) -> None:
        """The "window reset" adaptation primitive: both detectors
        forget pre-drift history (Page-Hinkley's cumulative deviation
        and ADWIN's old sub-window are meaningless across a regime
        change)."""
        self.ph.reset()
        self.adwin.reset()
        self._level_run = 0

    def describe(self) -> dict:
        return {"state": self.state, "n": self.n, "drifts": self.drifts,
                "converged": self.converged,
                "baseline": self.baseline, "ewma": self.ewma,
                "ph_stat": self.ph.stat, "adwin_width": self.adwin.width,
                "last_signal": self.last_signal}
