"""The drift-adapt-swap drill: online learning proven live, end to end.

One seeded run drives the whole ISSUE-9 story against real components
(broker, registry, watcher, scorer) in a deterministic interleave:

1. a model is pre-trained on the pre-drift fleet and published (v1 —
   "the deployed micro-batch model");
2. the ``OnlineLearner`` warm-starts from v1 and consumes the live
   stream (per-window incremental updates, baseline established);
3. the regional-cohort drift arrives; the learner must DETECT it
   within an SLO record budget, ADAPT (lr boost / window reset /
   refit), CONVERGE, and publish the adapted model through the
   registry;
4. the scorer fleet must hot-swap to the adapted version via the
   existing ``RegistryWatcher`` with zero lost / double-scored
   records, and live detection AUC (the r04 protocol over the
   scorer's own error histograms) must recover toward its pre-drift
   level;
5. finally a deliberately WRECKED "adaptation" is published as a
   candidate and deployed — the ``iotml.mlops`` A/B gate must roll it
   back: the rollback gate protects the fleet from a bad adaptation.

Run via ``python -m iotml.online drill`` (exit status = verdict; CI
and deploy/smoke.sh run exactly this).
"""

from __future__ import annotations

import shutil
import tempfile
from typing import List

import numpy as np

from ..chaos.runner import (Invariant, _check_commits_monotonic,
                            _record_commits)
from ..supervise.drill import DrillReport

IN_TOPIC = "SENSOR_DATA_S_AVRO"
PRED_TOPIC = "model-predictions"
GROUP = "online-drill"
CARS = 50


def _phase_auc(scorer, before: dict):
    """r04 histogram AUC of the rows scored since ``before`` (the
    err_hist snapshot protocol: cumulative hists diff into a window)."""
    from ..serve.scorer import hist_auc

    return hist_auc(scorer.err_hist["true"] - before["true"],
                    scorer.err_hist["false"] - before["false"])


def _snap(scorer) -> dict:
    return {k: v.copy() for k, v in scorer.err_hist.items()}


def drill_drift_adapt_swap(seed: int = 7, records: int = 12_000,
                           slo_detect_records: int = 1500,
                           auc_margin: float = 0.08) -> DrillReport:
    """Detect a seeded regional drift, adapt, publish, hot-swap the
    fleet, recover detection quality — then prove the rollback gate
    rejects a wrecked adaptation.  Deterministic single-thread drive;
    record-based SLOs."""
    import jax

    from ..data.dataset import SensorBatches
    from ..gen.scenarios import AdversarialFleet, condition
    from ..gen.simulator import FleetScenario
    from ..mlops import (ABRollout, ModelRegistry, RegistryWatcher,
                         RolloutGate)
    from ..mlops.checkpoint import (params_from_h5_bytes,
                                    params_to_h5_bytes)
    from ..models.autoencoder import CAR_AUTOENCODER
    from ..online.learner import OnlineLearner
    from ..serve.scorer import StreamScorer
    from ..stream.broker import Broker
    from ..stream.consumer import StreamConsumer
    from ..stream.producer import OutputSequence
    from ..train.loop import Trainer

    ticks = max(40, records // CARS)
    t_pretrain = (3 * ticks) // 10
    t_live = (3 * ticks) // 10
    t_post = ticks - t_pretrain - t_live
    window = 50

    broker = Broker()
    commit_log: List[tuple] = []
    _record_commits(broker, commit_log, "stream")
    # The AUC legs need anomaly mass the PARITY feature set can see:
    # failure mode 2 (battery fault) lives in voltage/current, both
    # zeroed by the reference's own normalize_fn TODOs — a fleet whose
    # failing cars all drew mode 2 has label noise, not signal.
    # Deterministically walk seeds from the requested one until the
    # drawn fleet has enough VISIBLE (vibration/tire) failure cars;
    # same seed -> same walk -> same fleet.
    cond = condition("regional-drift", drift_tick=t_pretrain + t_live)
    fleet = None
    for s in range(seed, seed + 32):
        cand_fleet = AdversarialFleet(
            FleetScenario(num_cars=CARS, failure_rate=0.12, seed=s),
            cond)
        failing = cand_fleet.gen.failing
        if int(((failing == 0) | (failing == 1)).sum()) >= 4:
            fleet = cand_fleet
            break
    fleet = fleet or cand_fleet
    root = tempfile.mkdtemp(prefix="iotml_online_drill_")
    reg = ModelRegistry(root)

    # ---- phase A: pre-train "the deployed model", publish v1
    fleet.publish_stream(broker, IN_TOPIC, n_ticks=t_pretrain)
    pre = Trainer(CAR_AUTOENCODER)
    pre_batches = SensorBatches(
        StreamConsumer(broker, [f"{IN_TOPIC}:0:0"], group="pretrain"),
        batch_size=100, only_normal=True, cache=True)
    pre.fit_compiled(pre_batches, epochs=10)
    mark = broker.end_offset(IN_TOPIC, 0)
    v1 = reg.publish(
        {"model.h5": params_to_h5_bytes(jax.device_get(pre.state.params))},
        offsets=[(IN_TOPIC, 0, mark)]).version
    reg.promote(v1)

    # ---- the online learner (warm start from v1) + the scorer fleet
    learner = OnlineLearner(broker, IN_TOPIC, registry=reg,
                            group=GROUP, window=window, publish_every=20)
    scons = StreamConsumer.from_committed(
        broker, IN_TOPIC, [0], group=f"{GROUP}-scorer", eof=True)
    scons.seek(IN_TOPIC, 0, mark)  # score the LIVE phases only
    scorer = StreamScorer(
        CAR_AUTOENCODER, None,
        SensorBatches(scons, batch_size=100, keep_labels=True),
        OutputSequence(broker, PRED_TOPIC, partition=0), threshold=5.0)
    watcher = RegistryWatcher(reg, scorers=[scorer])
    watcher.poll_once()
    swap_log: List[int] = []
    _orig = scorer.set_params

    def _recording(params, version=None):
        _orig(params, version=version)
        swap_log.append(version)

    scorer.set_params = _recording

    def drive():
        while learner.process_available(max_updates=5):
            learner.write_published()
            watcher.poll_once()
            scorer.score_available(max_rows=2000)
        scorer.score_available()

    # ---- phase B: live pre-drift — baseline + pre AUC.  The online
    # model improves through the first half of the phase (warm start
    # is not convergence), so the pre-drift quality reference is the
    # SECOND half only — the steady state the drift then breaks.
    fleet.publish_stream(broker, IN_TOPIC, n_ticks=t_live // 2)
    drive()
    h0 = _snap(scorer)
    fleet.publish_stream(broker, IN_TOPIC,
                         n_ticks=t_live - t_live // 2)
    drive()
    h_pre = _snap(scorer)
    auc_pre = _phase_auc(scorer, h0)
    updates_at_drift = learner.updates
    fp_adaptations = list(learner.adaptations)

    # ---- phase C: drift + detection + adaptation.  Three windows:
    # the drift front (the "during" dip), the adaptation transient
    # (deliberately unmeasured — rows scored by half-adapted models
    # belong to neither side), and the recovery window the invariant
    # judges.
    fleet.publish_stream(broker, IN_TOPIC, n_ticks=t_post // 3)
    drive()
    h_during = _snap(scorer)
    auc_during = _phase_auc(scorer, h_pre)
    fleet.publish_stream(broker, IN_TOPIC, n_ticks=t_post // 3)
    drive()
    h_transient = _snap(scorer)

    # ---- phase D: post-adaptation recovery window
    fleet.publish_stream(broker, IN_TOPIC,
                         n_ticks=t_post - 2 * (t_post // 3))
    drive()
    auc_post = _phase_auc(scorer, h_transient)
    learner.write_published()
    watcher.poll_once()
    detections = [a for a in learner.adaptations
                  if a[0] > updates_at_drift]
    detect_records = (detections[0][0] - updates_at_drift) * window \
        if detections else None
    latest = reg.latest()
    manifest = reg.manifest(latest)

    # ---- phase E: the rollback gate rejects a WRECKED adaptation
    good = latest
    params = params_from_h5_bytes(reg.load_bytes(good, "model.h5"))
    noise = np.random.RandomState(seed)
    bad = jax.tree_util.tree_map(
        lambda a: np.asarray(a)
        + noise.normal(0, 1.0, np.shape(a)).astype(np.float32), params)
    cand = reg.publish({"model.h5": params_to_h5_bytes(bad)},
                       metrics={"online": 1.0, "degraded": 1.0}).version
    gate = RolloutGate(min_records=300, epsilon=0.02)
    ab = ABRollout(broker, IN_TOPIC, reg, baseline=good, candidate=cand,
                   gate=gate, threshold=5.0, deploy_candidate=True,
                   from_start=True, group_prefix="online-gate")
    for _ in range(512):
        if ab.step(max_rows=5_000) == 0:
            break
    serving_final = reg.channel("serving")

    published = broker.end_offset(IN_TOPIC, 0)
    live_records = published - mark
    committed = {p: broker.committed(GROUP, IN_TOPIC, p) for p in [0]}
    manifest_offsets = {p: off for _t, p, off in manifest.offsets}
    invariants = [
        Invariant(
            "no_false_positive_drift",
            not fp_adaptations,
            "no drift fired on the stationary pre-drift stream"
            if not fp_adaptations else
            f"detector fired BEFORE the drift: {fp_adaptations}"),
        Invariant(
            "drift_detected_within_slo",
            detect_records is not None
            and detect_records <= slo_detect_records,
            f"drift detected {detect_records} records after onset "
            f"(slo {slo_detect_records})" if detect_records is not None
            else "the drift was never detected"),
        Invariant(
            "adaptation_converged",
            learner.monitor.converged >= 1,
            f"{learner.monitor.converged} adaptation episode(s) "
            f"converged; monitor state {learner.monitor.state!r}"),
        Invariant(
            "adapted_model_published",
            latest > v1 and manifest.metrics.get("online") == 1.0,
            f"registry at v{latest} (> deployed v{v1}), stamped as an "
            f"online checkpoint with cursors {manifest_offsets}"),
        Invariant(
            "fleet_hot_swapped",
            scorer.model_version == latest and latest in swap_log,
            f"scorer serving v{scorer.model_version} == registry tip "
            f"v{latest} after {len(swap_log)} hot-swaps"),
        Invariant(
            "auc_recovered",
            auc_pre is not None and auc_post is not None
            and (auc_post >= auc_pre - auc_margin
                 or (auc_during is not None
                     and auc_post >= auc_during
                     + max(0.03, 0.3 * (auc_pre - auc_during)))),
            f"live AUC {auc_pre and round(auc_pre, 3)} pre -> "
            f"{auc_during and round(auc_during, 3)} during-drift -> "
            f"{auc_post and round(auc_post, 3)} recovered "
            f"(within {auc_margin} of pre, or a >=30%-of-dip heal — "
            f"a drifted COHORT MIX can have a lower quality ceiling "
            f"than the pristine fleet; the quantitative online-vs-"
            f"micro-batch trajectory is bench_online's)"),
        Invariant(
            "zero_lost_zero_double_scored",
            scorer.scored == live_records
            and broker.end_offset(PRED_TOPIC, 0) == scorer.scored,
            f"{scorer.scored} rows scored == {live_records} live "
            f"records; predictions topic contiguous at "
            f"{broker.end_offset(PRED_TOPIC, 0)}"),
        Invariant(
            "commit_trails_manifest",
            all((committed.get(p) or 0) <= manifest_offsets.get(p, 0)
                for p in committed),
            f"committed {committed} <= newest durable manifest "
            f"{manifest_offsets} (offsets-as-checkpoint held)"),
        Invariant(
            "bad_adaptation_rolled_back",
            ab.decision == "rollback" and serving_final == good,
            f"gate verdict {ab.decision!r}; serving back at v"
            f"{serving_final} == last good v{good}"),
        _check_commits_monotonic(commit_log),
    ]
    shutil.rmtree(root, ignore_errors=True)
    return DrillReport(
        drill="drift-adapt-swap", seed=seed, records=records,
        published=published, scored=scorer.scored,
        restarts={},
        slos={"detect_records": detect_records,
              "auc_pre": auc_pre, "auc_during": auc_during,
              "auc_post": auc_post},
        invariants=invariants, injected={})


DRILLS = {
    "drift-adapt-swap": drill_drift_adapt_swap,
}
