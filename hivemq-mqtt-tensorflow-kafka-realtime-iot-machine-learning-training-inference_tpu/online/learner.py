"""True online learning: per-window SGD folded into the consume loop.

The reference is explicit that it does micro-batch streaming ingestion,
*not* online learning (reference README.md:130-140): its train job
re-fits a 10k-record slice and redeploys.  ``OnlineLearner`` goes past
it — every polled window (default 100 records, one fixed-shape jitted
step) updates the model in place, a ``DriftMonitor`` watches the
step's own loss signal for distribution drift, and a drift triggers an
adaptation (learning-rate boost, detector-window reset, or a replay-
buffer re-fit) whose result is published through the PR 7
``ModelRegistry`` so the scorer fleet hot-swaps it live.

Discipline shared with ``ContinuousTrainer`` (train/live.py):

- ONE persistent committed-offsets cursor; offsets-as-checkpoint still
  holds: snapshots ride the ``AsyncCheckpointer`` with the exact
  cursors they were trained through, and the group commit trails
  manifest durability (``commit_manifest_offsets``), so a crashed
  learner resumes model + stream position as one consistent unit.
- Model updates reach scorers ONLY through the registry (lint R13): an
  in-place ``set_params`` on a serving scorer would bypass versioning,
  the rollback gate, and the swap metrics.

The drift signal is the train step's own pre-update loss — the step
computes it anyway, so detection costs zero extra device dispatches
and incremental updates stay within the throughput SLO
(``bench_online`` pins >= 80% of micro-batch train throughput).
"""

from __future__ import annotations

import dataclasses
import collections
import time
from typing import Callable, Optional

import numpy as np

from ..chaos import faults as chaos
from ..data.dataset import SensorBatches
from ..obs import metrics as obs_metrics
from ..obs import watermark
from ..stream.consumer import StreamConsumer
from ..train.live import commit_manifest_offsets
from ..train.loop import (Trainer, adam_injectable_cached,
                          scanned_window_steps_cached)
from .detectors import ADAPTING, DriftMonitor


def _padded_shard_counts(mask: np.ndarray, n_shards: int) -> list:
    """Per-chip valid-row counts under `ShardedTrainer.put_batch`'s
    padding: rows pad up to a multiple of the data axis, shards are
    contiguous row blocks, padding rows carry mask 0."""
    b = len(mask)
    r = b if b % n_shards == 0 else b + (n_shards - b % n_shards)
    m = np.zeros((r,), np.float32)
    m[:b] = mask
    return [int(c) for c in m.reshape(n_shards, -1).sum(axis=1)]


@dataclasses.dataclass
class AdaptationPolicy:
    """Which adaptation a drift episode triggers.

    ``action``:
      boost — multiply the learning rate by ``lr_boost`` for the next
        ``boost_updates`` windows, then restore it.  Cheap; right for
        mild shifts the optimizer can chase.
      refit — replay the bounded recent-batch buffer for
        ``refit_epochs`` extra passes (a mini retrain biased to the
        post-drift distribution), THEN boost.  Right for severe
        shifts where per-window steps alone converge too slowly.
      reset — detector-window reset only (the monitor always resets
        its windows on drift; this action adds nothing else — the
        "trust the optimizer" null adaptation).
      auto — pick by measured severity: ``refit`` when the smoothed
        error rose past ``severe_ratio`` × baseline, else ``boost``.
    """

    action: str = "auto"
    lr_boost: float = 5.0
    boost_updates: int = 80
    refit_epochs: int = 2
    severe_ratio: float = 4.0

    def choose(self, severity: float, buffer_len: int) -> str:
        if self.action != "auto":
            return self.action
        if severity >= self.severe_ratio and buffer_len:
            return "refit"
        return "boost"


class OnlineLearner:
    """Per-record/small-window incremental trainer with drift-triggered
    adaptation, publishing through the model registry.

    Args:
      broker/topic/group: the stream leg, ContinuousTrainer-shaped.
      registry | checkpointer: where adapted models publish.  Pass a
        registry and an ``AsyncCheckpointer`` is built; pass a
        checkpointer to control its policy (queue depth, cadence,
        retention) — but a checkpointer is PER-TRAINER: its one commit
        hook encodes this group's cursor discipline, so trainers share
        a ModelRegistry, never a writer (enforced).  ``None`` both
        runs detect-only (tests).
      window: records per incremental update (one fixed [window, F]
        jitted step — the "small-window" in per-record/small-window;
        window=1 is true per-record SGD at per-dispatch cost).
      monitor/policy: drift detection + adaptation knobs.
      publish_every: windows between steady-state publishes (drift
        adaptations publish immediately, and again on convergence).
    """

    def __init__(self, broker, topic: str, registry=None,
                 checkpointer=None, model=None,
                 group: str = "cardata-online", window: int = 100,
                 learning_rate: float = 1e-3,
                 monitor: Optional[DriftMonitor] = None,
                 policy: Optional[AdaptationPolicy] = None,
                 normalizer=None, only_normal: bool = True,
                 publish_every: int = 20, buffer_batches: int = 32,
                 warm_start: bool = True, keep_versions: int = 0,
                 fuse: int = 8, mesh=None, device_normalize: bool = False,
                 chip_monitors: Optional[list] = None):
        if model is None:
            from ..models.autoencoder import CAR_AUTOENCODER

            model = CAR_AUTOENCODER
        self.broker = broker
        self.topic = topic
        self.group = group
        self.model = model
        self.window = int(window)
        #: catch-up fusion cap: when the stream runs ahead, up to this
        #: many windows run as ONE scanned device program (per-window
        #: losses still feed the detector) — the dispatch amortization
        #: that keeps incremental updates inside the throughput SLO.
        #: Group sizes bucket to powers of two so jit compiles at most
        #: log2(fuse)+1 variants.  1 disables fusion (pure per-window).
        self.fuse = max(1, int(fuse))
        self.base_lr = float(learning_rate)
        self.monitor = monitor or DriftMonitor()
        self.policy = policy or AdaptationPolicy()
        self.publish_every = int(publish_every)
        # injectable-LR Adam: the boost mutates opt_state.hyperparams —
        # same compiled step before, during and after a boost
        self._tx = adam_injectable_cached(learning_rate)
        self.trainer = Trainer(model, learning_rate=learning_rate,
                               tx=self._tx)
        # mesh mode (ISSUE 15): the window step runs SHARDED over the
        # data axis (rows → chips, gradient all-reduce over the mesh)
        # and each chip gets its OWN DriftMonitor fed from its shard's
        # per-row pre-update losses — a cohort drift that only one
        # chip's rows carry trips that chip's detector even when the
        # fleet-mean signal stays calm.  Coordination is ONE model +
        # ONE registry: any chip's drift begins a single global episode
        # (monitor.begin_episode) whose adaptation/publication rides
        # the exact machinery below.  device_normalize ships raw
        # columns and folds the affine map into the sharded step.
        self.mesh = mesh
        self._sharded = None
        self.chip_monitors: list = []
        self.last_chip_losses = None
        self._chip_signal: Optional[tuple] = None
        if mesh is not None:
            from ..core.normalize import CAR_NORMALIZER
            from ..parallel.data_parallel import ShardedTrainer
            from ..parallel.streaming import data_axis_devices

            n_dev = len(data_axis_devices(mesh))
            self._sharded = ShardedTrainer(
                model, mesh, tx=self._tx,
                normalizer=(normalizer or CAR_NORMALIZER)
                if device_normalize else None,
                row_loss=True)
            self.chip_monitors = list(chip_monitors) if chip_monitors \
                else [DriftMonitor() for _ in range(n_dev)]
            if len(self.chip_monitors) != n_dev:
                raise ValueError(f"{len(self.chip_monitors)} chip "
                                 f"monitors for a {n_dev}-device mesh")
        elif chip_monitors:
            raise ValueError("chip_monitors need a mesh")
        if device_normalize:
            if mesh is None:
                raise ValueError("device_normalize needs a mesh (the "
                                 "affine fold lives in the sharded step)")
            from ..core.normalize import RAW_COLUMNS

            normalizer = RAW_COLUMNS  # batcher ships raw columns
        self.checkpointer = checkpointer
        self.registry = registry
        if registry is not None and checkpointer is None:
            from ..mlops.checkpoint import AsyncCheckpointer

            self.checkpointer = AsyncCheckpointer(
                registry, keep_versions=keep_versions)
        if self.checkpointer is not None:
            self.registry = self.checkpointer.registry
            if self.checkpointer.commit_fn is not None:
                # the checkpointer has ONE commit hook, and it encodes
                # one trainer's (group, cursor) discipline — silently
                # stealing it would stall the other trainer's committed
                # cursor AND commit this group's offsets for records it
                # never trained.  A writer is per-trainer; share the
                # REGISTRY, not the checkpointer.
                raise ValueError(
                    "checkpointer is already wired to another "
                    "trainer's commit hook; each trainer owns its own "
                    "AsyncCheckpointer (they may share one "
                    "ModelRegistry)")
            # the shared crash-consistency hook: group commit trails
            # manifest durability, forward-only (train/live.py)
            self.checkpointer.commit_fn = lambda m: \
                commit_manifest_offsets(self.broker, self.group, m)
        broker.create_topic(topic)  # idempotent; a learner may boot
        # before the first producer provisions the stream
        parts = list(range(broker.topic(topic).partitions))
        self._parts = parts
        self.consumer = StreamConsumer.from_committed(broker, topic, parts,
                                                      group=group)
        # registry warm start — identical contract to ContinuousTrainer:
        # resume the lineage TIP's weights and apply its stamped cursors
        # forward-only (committed may trail the manifest, never lead it)
        self.restored_version: Optional[int] = None
        if self.registry is not None and warm_start:
            from ..mlops.checkpoint import restore_trainer

            m = restore_trainer(self.trainer, self.registry)
            if m is not None:
                self.restored_version = m.version
                for t, p, off in m.offsets:
                    cur = broker.committed(group, t, p) or 0
                    if off > cur:
                        self.consumer.seek(t, p, off)
        batch_kw = {} if normalizer is None else dict(normalizer=normalizer)
        # take-budgeted drains — ContinuousTrainer's cursor discipline:
        # each iteration emits at most `fuse` windows and the batcher's
        # poll budgeting (_need_rows) never over-polls past what it
        # will emit, so consumer.positions() at a drain boundary IS the
        # trained frontier.  Without the budget a suspended iterator
        # buffers up to poll_chunk rows past the trained frontier, and
        # a checkpoint stamped from positions() would, on crash-resume,
        # silently skip every polled-but-untrained record.
        self.batches = SensorBatches(self.consumer, batch_size=self.window,
                                     only_normal=only_normal,
                                     take=self.fuse,
                                     poll_chunk=max(self.window, 4096),
                                     **batch_kw)
        #: bounded replay buffer of recent (x, mask) windows — what a
        #: "refit" adaptation re-fits on (biased to the newest data by
        #: construction: drop-oldest)
        self.buffer: collections.deque = collections.deque(
            maxlen=max(1, int(buffer_batches)))
        self.updates = 0
        self.records_trained = 0
        self.last_loss: Optional[float] = None
        self.adaptations: list = []  # [(update_idx, signal, action)]
        self.published_versions: list = []
        self._boost_left = 0
        self._since_publish = 0
        # publish requests raised inside a group are applied at the
        # GROUP boundary: a mid-group snapshot would stamp the drain's
        # end offsets against a partially-trained state
        self._publish_pending = False
        self._publish_force = False
        obs_metrics.online_lr.set(self.base_lr)

    # -------------------------------------------------------------- lr
    @property
    def current_lr(self) -> float:
        st = self.trainer.state
        if st is None:
            return self.base_lr
        return float(st.opt_state.hyperparams["learning_rate"])

    def set_lr(self, lr: float) -> None:
        """Runtime LR mutation — an opt_state edit, no recompile."""
        import jax.numpy as jnp

        st = self.trainer.state
        if st is None:
            self.base_lr = float(lr)
            return
        hp = dict(st.opt_state.hyperparams)
        hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
        self.trainer.state = st.replace(
            opt_state=st.opt_state._replace(hyperparams=hp))
        if self._sharded is not None and self._sharded.state is not None:
            # the sharded step trains from _sharded.state: the edit must
            # land there too (the scalar re-places as replicated on the
            # next dispatch — no recompile, same contract)
            self._sharded.state = self.trainer.state
        obs_metrics.online_lr.set(float(lr))

    # ----------------------------------------------------------- update
    def _update(self, b) -> float:
        """One incremental step on one window; returns the pre-update
        loss (the drift signal)."""
        if self._sharded is not None:
            return self._update_mesh(b)
        self.trainer._ensure_state(b.x)
        with obs_metrics.train_step_seconds.time(), \
                obs_metrics.step_seconds.time(loop="online",
                                              phase="device_compute"):
            self.trainer.state, m = self.trainer._step(
                self.trainer.state, b.x, b.x, b.mask)
        loss = float(m["loss"])
        self.updates += 1
        self.records_trained += b.n_valid
        self.last_loss = loss
        obs_metrics.online_updates.inc()
        obs_metrics.records_trained.inc(b.n_valid)
        self.buffer.append((b.x, b.mask))
        return loss

    def _update_mesh(self, b) -> float:
        """The sharded window step: rows shard over chips, the global
        loss is the drift signal as ever, and each chip's shard-mean
        pre-update loss additionally feeds its own monitor — a chip
        whose detector fires stages a coordinated episode that
        `_after_update` opens on the global monitor."""
        from ..parallel.streaming import shard_mean_losses

        if self._sharded.state is None:
            # adopt the (possibly warm-started) host state once
            self.trainer._ensure_state(b.x)
            self._sharded.init(b.x, from_state=self.trainer.state)
        with obs_metrics.train_step_seconds.time(), \
                obs_metrics.step_seconds.time(loop="online",
                                              phase="device_compute"):
            m = self._sharded.step(b.x, b.x, b.mask)
        loss = float(m["loss"])
        # mirror the CURRENT state: snapshot/publish and current_lr read
        # self.trainer.state, and it must never be a stale donated buffer
        self.trainer.state = self._sharded.state
        counts = _padded_shard_counts(b.mask, len(self.chip_monitors))
        chip = shard_mean_losses(m["row_loss"], counts)
        self.last_chip_losses = chip
        for i, (mon, cl, cnt) in enumerate(
                zip(self.chip_monitors, chip, counts)):
            if cnt <= 0:
                continue  # all-padding shard: no signal to judge
            sig = mon.update(float(cl))
            if sig is not None:
                self._chip_signal = (i, sig)
        self.updates += 1
        self.records_trained += b.n_valid
        self.last_loss = loss
        obs_metrics.online_updates.inc()
        obs_metrics.records_trained.inc(b.n_valid)
        self.buffer.append((b.x, b.mask))
        return loss

    def _update_group(self, bs) -> list:
        """K windows as ONE scanned dispatch (catch-up fusion): K
        sequential updates, per-window losses back for the detector."""
        self.trainer._ensure_state(bs[0].x)
        xs = np.stack([b.x for b in bs])
        masks = np.stack([b.mask for b in bs])
        scan = scanned_window_steps_cached(
            self.model, self._tx, tx_key=("online-adam", self.base_lr))
        with obs_metrics.train_step_seconds.time(), \
                obs_metrics.step_seconds.time(loop="online",
                                              phase="device_compute"):
            self.trainer.state, losses = scan(self.trainer.state, xs,
                                              masks)
        losses = [float(v) for v in np.asarray(losses)]
        n_valid = sum(b.n_valid for b in bs)
        self.updates += len(bs)
        self.records_trained += n_valid
        self.last_loss = losses[-1]
        obs_metrics.online_updates.inc(len(bs))
        obs_metrics.records_trained.inc(n_valid)
        for b in bs:
            self.buffer.append((b.x, b.mask))
        return losses

    def _take_group(self, limit: int) -> list:
        """One budgeted drain: at most ``limit`` windows, polled under
        the batcher's take/_need_rows cap so the consumer cursor never
        runs ahead of what this group will train (the offsets-as-
        checkpoint edge).  The iterator is run to completion — no
        suspended state, every drain leaves positions() == the trained
        frontier (modulo label-filtered rows, which are consumed by
        design exactly as in ContinuousTrainer)."""
        self.batches.take = max(1, limit)
        group = []
        with obs_metrics.step_seconds.time(loop="online",
                                           phase="host_pipeline"):
            for b in iter(self.batches):
                chaos.point("online.update")
                if b.n_valid:
                    group.append(b)
        return group

    def _after_update(self, loss: float) -> None:
        """The per-window control body: feed the monitor, adapt on
        drift, publish on cadence / episode end."""
        was_adapting = self.monitor.state == ADAPTING
        conv_before = self.monitor.converged
        signal = self.monitor.update(loss)
        obs_metrics.online_drift_stat.set(self.monitor.ph.stat)
        chip_signal, self._chip_signal = self._chip_signal, None
        if signal is not None:
            self._adapt(signal)
        elif chip_signal is not None and not was_adapting \
                and self.monitor.state != ADAPTING:
            # per-chip coordination (mesh mode): a chip-local drift the
            # fleet mean diluted — open ONE global episode (the model
            # is one model) and adapt at the tripping chip's severity
            i, sig = chip_signal
            tag = f"chip{i}-{sig}"
            self.monitor.begin_episode(tag)
            self._adapt(tag, severity=self.chip_monitors[i].severity())
        elif was_adapting and self.monitor.state != ADAPTING:
            # adaptation episode ended (converged or timed out):
            # restore the base LR and publish the adapted model — THIS
            # is the version the drift story promised the fleet
            if self.monitor.converged > conv_before:
                obs_metrics.online_converged.inc()
            self._boost_left = 0
            self.set_lr(self.base_lr)
            self._request_publish(force=True)
        elif self._boost_left > 0:
            self._boost_left -= 1
            if self._boost_left == 0:
                self.set_lr(self.base_lr)
        self._since_publish += 1
        if self._since_publish >= self.publish_every:
            self._request_publish()

    def _request_publish(self, force: bool = False) -> None:
        """Queue a publish for the next GROUP boundary: snapshots stamp
        consumer positions, and mid-group those describe rows the state
        has not trained through yet."""
        self._publish_pending = True
        self._publish_force = self._publish_force or force

    def process_available(self, max_updates: Optional[int] = None) -> int:
        """Consume and train on everything currently in the stream;
        returns windows processed.  A deep backlog is chewed in fused
        groups (power-of-two sizes up to ``fuse``); at the stream head
        the group degenerates to single windows — minimum latency live,
        amortized dispatch in catch-up.  Adaptation actions land
        between dispatches (a drift detected inside a fused group
        boosts/refits before the NEXT group, one group late at worst)
        and publishes land at group boundaries, where the consumer
        cursor and the trained state agree."""
        n = 0
        while True:
            want = self.fuse if max_updates is None \
                else min(self.fuse, max_updates - n)
            group = self._take_group(want)
            if not group:
                break
            while group:
                # largest power-of-two chunk: bounded compile variants.
                # Mesh mode dispatches per window — the sharded step
                # already amortizes over chips, and per-chip detectors
                # want window-granular shard losses
                k = 1 if self._sharded is not None \
                    else 1 << (len(group).bit_length() - 1)
                chunk, group = group[:k], group[k:]
                losses = [self._update(chunk[0])] if k == 1 \
                    else self._update_group(chunk)
                for loss in losses:
                    self._after_update(loss)
                n += k
            # group boundary: consumed == trained — publish the
            # ingest→train watermark from the folded event-time ranges
            watermark.observe_taken("train",
                                    self.consumer.take_event_time(),
                                    group=self.group)
            if self._publish_pending:
                force, self._publish_pending = self._publish_force, False
                self._publish_force = False
                self._publish(force=force)
            if max_updates is not None and n >= max_updates:
                break
        return n

    # ------------------------------------------------------- adaptation
    def _adapt(self, signal: str, severity: Optional[float] = None) -> None:
        if severity is None:
            severity = self.monitor.severity()
        action = self.policy.choose(severity, len(self.buffer))
        self.adaptations.append((self.updates, signal, action))
        obs_metrics.online_drifts.inc(detector=signal)
        obs_metrics.online_adaptations.inc(action=action)
        # window reset is unconditional: pre-drift detector state is
        # meaningless across a regime change (monitor.update already
        # moved to ADAPTING; reset re-arms its post-episode windows)
        self.monitor.reset_windows()
        if action == "refit":
            self._refit()
        if action in ("boost", "refit"):
            self.set_lr(self.base_lr * self.policy.lr_boost)
            self._boost_left = self.policy.boost_updates
        # ship the first adapted state at the group boundary: the
        # fleet should not score a drifted distribution on pre-drift
        # weights for a whole publish_every cadence
        self._request_publish(force=True)

    def _refit(self) -> None:
        """Replay-buffer mini-retrain: extra passes over the recent
        windows (drop-oldest buffer ⇒ post-drift biased)."""
        last = None
        for _ in range(self.policy.refit_epochs):
            for x, mask in list(self.buffer):
                if self._sharded is not None:
                    last = self._sharded.step(x, x, mask)
                    self.trainer.state = self._sharded.state
                else:
                    self.trainer.state, last = self.trainer._step(
                        self.trainer.state, x, x, mask)
                self.records_trained += int(mask.sum())
        if last is not None:
            self.last_loss = float(last["loss"])

    # ------------------------------------------------------- publishing
    def _publish(self, force: bool = False) -> None:
        self._since_publish = 0
        if self.checkpointer is None:
            return
        if not self.checkpointer.would_accept(force):
            self.checkpointer.coalesced += 1
            return
        cursors = self.consumer.positions()
        ends = {(t, p): self.broker.end_offset(t, p)
                for t, p, _off in cursors}
        self.checkpointer.snapshot(
            self.trainer.state, cursors,
            metrics={"loss": self.last_loss
                     if self.last_loss is not None else float("nan"),
                     "records": float(self.records_trained),
                     "drifts": float(self.monitor.drifts),
                     "online": 1.0},
            end_offsets=ends, force=force)

    def write_published(self) -> list:
        """Deterministically drain the checkpoint writer (tests/drills;
        live mode runs checkpointer.start() instead).  Returns the
        versions committed by this drain."""
        out = []
        if self.checkpointer is None:
            return out
        while True:
            v = self.checkpointer.write_once()
            if v is None:
                break
            out.append(v)
        self.published_versions.extend(out)
        return out

    # -------------------------------------------------------- lifecycle
    def run(self, stop: Optional[Callable[[], bool]] = None,
            max_seconds: Optional[float] = None,
            poll_interval_s: float = 0.05,
            on_update: Optional[Callable[[dict], None]] = None) -> int:
        """Consume-and-train until ``stop()``/``max_seconds``; returns
        windows processed.  Owns the checkpoint writer thread."""
        if self.checkpointer is not None:
            self.checkpointer.start()
        deadline = None if max_seconds is None else \
            time.monotonic() + max_seconds
        n = 0
        while (stop is None or not stop()) and \
                (deadline is None or time.monotonic() < deadline):
            got = self.process_available(max_updates=256)
            n += got
            if on_update is not None and got:
                on_update(self.describe())
            if not got:
                time.sleep(poll_interval_s)
        if self.checkpointer is not None:
            self._publish(force=True)  # newest state must not die
            self.checkpointer.flush(timeout_s=30.0)
        return n

    def close(self, timeout_s: float = 30.0) -> None:
        if self.checkpointer is not None:
            self.checkpointer.stop(flush=True, timeout_s=timeout_s)

    def describe(self) -> dict:
        out = {"updates": self.updates,
               "records_trained": self.records_trained,
               "loss": self.last_loss, "lr": self.current_lr,
               "adaptations": list(self.adaptations),
               "monitor": self.monitor.describe(),
               "published": list(self.published_versions)}
        if self.chip_monitors:
            out["chips"] = [m.describe() for m in self.chip_monitors]
        return out
