from .avro import AvroCodec  # noqa: F401
from .framing import frame, unframe, SCHEMA_ID_DEFAULT  # noqa: F401
