"""Attention ops: reference implementation + Pallas flash-attention kernel.

The reference system's only sequence model is an LSTM at look_back=1
(SURVEY §5 'long-context: nothing') — but this framework treats long per-car
sensor histories as first-class: fleets emit unbounded streams, and anomaly
models that look at hours of context need sequence lengths the LSTM path
never contemplated.  The attention stack here:

- `attention_reference`: straight jnp softmax attention — the oracle for
  every other path, and the XLA-fused fallback on CPU.
- `flash_attention`: blocked online-softmax attention as a Pallas TPU
  kernel — O(T) memory instead of O(T²), MXU-shaped [128×128] tiles, the
  single-chip hot op of the transformer model family.
- `blockwise_update`: one online-softmax accumulation step, shared between
  the flash kernel's inner loop (conceptually) and the ring-attention
  cross-chip loop (`parallel.ring_attention`), which is the same math with
  the KV blocks arriving over ICI instead of from VMEM.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _tpu_compiler_params(pltpu, **kw):
    """Pallas-TPU compiler params across JAX versions: the class is
    `CompilerParams` on newer JAX and `TPUCompilerParams` on 0.4.x."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kw)

NEG_INF = -1e30


def _causal_tiles(nq: int, nk: int, block_q: int, block_k: int,
                  order: str) -> tuple:
    """Enumerate the LIVE causal tiles as (i_map, j_map) int32 arrays.

    The dense grid pays DMA + a grid step for every (i, j) tile and
    `pl.when`s away the strictly-future half — measured at ≈½ a computed
    tile each (ARCHITECTURE.md roofline lever 2).  Feeding these maps
    through scalar prefetch makes the grid exactly the lower triangle:
    skipped tiles stop existing instead of being masked.

    order="row": row-major (i outer) — forward and dQ, whose scratch
    accumulates along j within one q row.  order="col": column-major
    (j outer) — dK/dV, whose scratch accumulates along i within one kv
    column.  Columns entirely in the future of every query keep one dead
    diagonal tile so their dk/dv output block is still zero-written.

    Cost bound: the maps hold ~nq·nk/2 int32 pairs (vectorized numpy —
    no Python loop), shipped through scalar prefetch.  At the benched
    long-context shape (T=65,536, 1024² tiles) that is 2,080 tiles =
    16 KB; callers picking tiny blocks at huge T pay O((T/block)²)
    map memory, which `_flash_forward` caps (falls back to the dense
    grid past _TRI_TILE_CAP) so the prefetch stream can never outgrow
    SMEM-class storage."""
    if order == "row":
        jmax = np.minimum(nk - 1, (np.arange(nq, dtype=np.int64) * block_q
                                   + block_q - 1) // block_k)
        counts = jmax + 1
        im = np.repeat(np.arange(nq, dtype=np.int64), counts)
        # j runs 0..jmax within each row: global arange minus the row start
        starts = np.repeat(np.cumsum(counts) - counts, counts)
        jm = np.arange(counts.sum(), dtype=np.int64) - starts
    else:
        imin = np.minimum(nq - 1, (np.arange(nk, dtype=np.int64) * block_k)
                          // block_q)
        counts = nq - imin
        jm = np.repeat(np.arange(nk, dtype=np.int64), counts)
        starts = np.repeat(np.cumsum(counts) - counts, counts)
        im = np.arange(counts.sum(), dtype=np.int64) - starts + \
            np.repeat(imin, counts)
    return (im.astype(np.int32), jm.astype(np.int32))


#: triangular-grid cap: above this many live tiles the scalar-prefetch
#: maps (2 × 4 B × tiles, × 3 kernels) would outgrow SMEM-class storage —
#: fall back to the dense grid, which has O(1) grid metadata.  65,536
#: tiles = 512 KB of maps; every practical (T, block) pairing for this
#: framework sits far below it (65,536 tokens at 1024² → 2,080 tiles;
#: 128² blocks stay under the cap to T = 46k).
_TRI_TILE_CAP = 65_536


def _tri_tile_count(nq: int, nk: int, block_q: int, block_k: int) -> int:
    """Live-tile count of the causal triangle (row order; col is equal)."""
    jmax = np.minimum(nk - 1, (np.arange(nq, dtype=np.int64) * block_q
                               + block_q - 1) // block_k)
    return int((jmax + 1).sum())


def attention_reference(q, k, v, causal: bool = True,
                        q_offset: int = 0, k_offset: int = 0):
    """Plain softmax attention. q,k,v: [B, T, H, D] → [B, Tq, H, D].

    q_offset/k_offset give the global positions of local blocks so the
    causal mask stays correct under sequence sharding.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])[:, None]
        kpos = k_offset + jnp.arange(k.shape[1])[None, :]
        mask = qpos >= kpos
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def blockwise_update(o, m, l, q, k_blk, v_blk, scale,
                     mask: Optional[jnp.ndarray] = None):
    """One online-softmax accumulation against a KV block.

    o: [B, Tq, H, D] running (unnormalized) output
    m: [B, H, Tq] running rowmax, l: [B, H, Tq] running denominator
    mask: [Tq, Tk] boolean (True = attend), already global-position-aware.
    Returns updated (o, m, l).  Final output is o / l[..., None].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows (m_new == NEG_INF): exp(NEG_INF - NEG_INF)=1
    # would pollute l; clamp the correction to 0 there.
    alive = m_new > NEG_INF / 2
    corr = jnp.where(alive, jnp.exp(m - m_new), 0.0)
    p = jnp.where(alive[..., None], jnp.exp(s - m_new[..., None]), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * jnp.transpose(corr, (0, 2, 1))[..., None] + \
        jnp.einsum("bhqk,bkhd->bqhd", p, v_blk)
    return o_new, m_new, l_new


def finalize_blockwise(o, l):
    """Normalize accumulated output; fully-masked rows come out zero."""
    denom = jnp.transpose(jnp.where(l == 0.0, 1.0, l), (0, 2, 1))[..., None]
    return o / denom


# --------------------------------------------------------------------- pallas
def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s, *,
                  scale: float, causal: bool, block_q: int, block_k: int):
    """Flash attention kernel.  Grid: (batch*heads, q_blocks, kv_blocks) —
    the kv dimension iterates sequentially on-core, so K/V stream through
    VMEM one [block_k, D] tile at a time (O(T) VMEM, long-context safe) and
    the online-softmax state lives in scratch that persists across the kv
    iterations of one q block."""
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    def compute():
        # EVERY matmul rides the MXU at the INPUT dtype (bf16 inputs →
        # bf16 systolic passes at ~4× the f32 rate, f32 ACCUMULATION
        # always).  QK's bf16 products are exact (inputs are bf16); the
        # scale is applied to the f32 scores afterwards.  P is computed in
        # f32 (softmax stability) then cast to the input dtype for P·V —
        # the standard flash-attention trade: an f32 P·V matmul runs at ¼
        # the MXU rate and capped this kernel's whole-step MFU at ~33%
        # (see ARCHITECTURE.md roofline); the bf16 P rounding (~3 decimal
        # digits) is below the bf16 output's own quantization.
        s = jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qi = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + i * block_q
            kj = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + j * block_k
            s = jnp.where(qi >= kj, s, NEG_INF)
        m = m_s[:]
        l = l_s[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alive = m_new > NEG_INF / 2
        corr = jnp.where(alive, jnp.exp(m - m_new), 0.0)
        p = jnp.where(alive, jnp.exp(s - m_new), 0.0)
        m_s[:] = m_new
        l_s[:] = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # whole KV block strictly in the future of this q block → skip
        @pl.when(j * block_k <= i * block_q + (block_q - 1))
        def _():
            compute()
    else:
        compute()

    @pl.when(j == nk - 1)
    def _emit():
        l = l_s[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc[:] / safe_l).astype(o_ref.dtype)
        # log-sum-exp per query row (needed by the custom-VJP backward)
        lse_ref[:] = jnp.where(l == 0.0, NEG_INF, m_s[:] + jnp.log(safe_l))


def _flash_kernel_tri(im_ref, jm_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc, m_s, l_s, *, scale: float, block_q: int,
                      block_k: int, nk: int):
    """Causal flash forward on the TRIANGULAR grid: the grid's second
    axis walks only the live lower-triangle tiles (row-major), with the
    (i, j) tile coordinates arriving via scalar prefetch.  Strictly-future
    tiles no longer exist, so they pay neither their K/V DMA nor a grid
    step (the dense grid's `pl.when` skip still paid both — measured at
    ≈½ a computed tile, ARCHITECTURE.md roofline lever 2)."""
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    i = im_ref[t]
    j = jm_ref[t]

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    # same math + dtype policy as _flash_kernel (see its comment): bf16
    # systolic passes, f32 accumulation, f32 softmax, P cast for P·V
    s = jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale

    # the elementwise causal mask runs on EVERY tile even though only
    # diagonal-straddling tiles need it: branch-specializing it behind a
    # lax.cond was MEASURED SLOWER (54.2% vs 57.7% MFU same-session —
    # the cond defeats Mosaic's fusion/pipelining and, in the backward,
    # the duplicated branch temporaries blow the 16 MB scoped-VMEM
    # budget at 1024^2 tiles).  Roofline lever 3 stays on the table via
    # cheaper masks, not control flow.
    qi = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0) + i * block_q
    kj = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1) + j * block_k
    s = jnp.where(qi >= kj, s, NEG_INF)
    m = m_s[:]
    l = l_s[:]
    m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
    alive = m_new > NEG_INF / 2
    corr = jnp.where(alive, jnp.exp(m - m_new), 0.0)
    p = jnp.where(alive, jnp.exp(s - m_new), 0.0)
    m_s[:] = m_new
    l_s[:] = l * corr + jnp.sum(p, axis=1, keepdims=True)
    acc[:] = acc[:] * corr + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    # last live tile of this q row = the diagonal block
    jmax = jnp.minimum(nk - 1, (i * block_q + block_q - 1) // block_k)

    @pl.when(j == jmax)
    def _emit():
        lf = l_s[:]
        safe_l = jnp.where(lf == 0.0, 1.0, lf)
        o_ref[:] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[:] = jnp.where(lf == 0.0, NEG_INF,
                               m_s[:] + jnp.log(safe_l))


def _flash_forward(q, k, v, causal: bool, block_q: int,
                   block_k: int, interpret: bool):
    """Run the Pallas kernel; returns (out [B,T,H,D], lse [B,H,T])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    Tq = ((T + block_q - 1) // block_q) * block_q
    Tk = ((T + block_k - 1) // block_k) * block_k
    if not causal and Tk != T:
        # padded keys are only excluded by the causal mask; non-causal
        # callers must supply block-multiple sequence lengths
        raise ValueError(f"non-causal flash attention needs T % {block_k} == 0")
    if Tq != T:
        pad = [(0, 0), (0, Tq - T), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
    if Tk != T:
        pad = [(0, 0), (0, Tk - T), (0, 0), (0, 0)]
        # pad keys so padded positions never win the max: values 0, and the
        # causal mask (global positions) excludes them for every real query
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    # layout: fold batch & heads into the grid's first axis, T-major blocks
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)

    out_shape = [
        jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        jax.ShapeDtypeStruct((B * H, Tq, 1), jnp.float32),
    ]
    scratch_shapes = [
        pltpu.VMEM((block_q, D), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
    ]
    tri = causal and _tri_tile_count(Tq // block_q, Tk // block_k,
                                     block_q, block_k) <= _TRI_TILE_CAP
    if tri:
        # triangular grid: only live tiles exist (see _flash_kernel_tri)
        im, jm = _causal_tiles(Tq // block_q, Tk // block_k,
                               block_q, block_k, "row")
        kernel = functools.partial(_flash_kernel_tri, scale=scale,
                                   block_q=block_q, block_k=block_k,
                                   nk=Tk // block_k)
        out, lse = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, len(im)),
                in_specs=[
                    pl.BlockSpec((None, block_q, D),
                                 lambda b, t, im, jm: (b, im[t], 0)),
                    pl.BlockSpec((None, block_k, D),
                                 lambda b, t, im, jm: (b, jm[t], 0)),
                    pl.BlockSpec((None, block_k, D),
                                 lambda b, t, im, jm: (b, jm[t], 0)),
                ],
                out_specs=[
                    pl.BlockSpec((None, block_q, D),
                                 lambda b, t, im, jm: (b, im[t], 0)),
                    pl.BlockSpec((None, block_q, 1),
                                 lambda b, t, im, jm: (b, im[t], 0)),
                ],
                scratch_shapes=scratch_shapes,
            ),
            out_shape=out_shape,
            compiler_params=_tpu_compiler_params(pltpu, 
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(jnp.asarray(im), jnp.asarray(jm), qf, kf, vf)
    else:
        kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                                   block_q=block_q, block_k=block_k)
        out, lse = pl.pallas_call(
            kernel,
            grid=(B * H, Tq // block_q, Tk // block_k),
            in_specs=[
                pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=out_shape,
            scratch_shapes=scratch_shapes,
            compiler_params=_tpu_compiler_params(pltpu, 
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=interpret,
        )(qf, kf, vf)
    out = out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)[:, :T]
    lse = lse.reshape(B, H, Tq)[:, :, :T]
    return out, lse


def _bwd_common(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *,
                scale, causal, block_q, block_k, t_real, i, j):
    """Shared recompute for both backward kernels: returns (p, ds) f32.

    Matmul dtype policy mirrors the forward: score/dP matmuls run at the
    input dtype (exact products for bf16, MXU bf16 rate, f32 accumulate);
    p/ds stay f32 — they are exp-of-f32 quantities the gradient
    tolerances pin.  The mask runs on every tile: branch-specializing it
    (lax.cond on straddle/tail tiles) was measured slower AND blew the
    scoped-VMEM budget at 1024^2 tiles — see the forward kernel's note."""
    s = jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    qi = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0) + i * block_q
    kj = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1) + j * block_k
    mask = kj < t_real
    if causal:
        mask = mask & (qi >= kj)
    p = jnp.where(mask, jnp.exp(s - lse_ref[:]), 0.0)
    dp = jax.lax.dot_general(do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[:]) * scale
    return p, ds


def _flash_bwd_dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale,
                          causal, block_q, block_k, t_real):
    """dK/dV: grid (BH, kv_blocks, q_blocks) — for one kv block, stream
    the q blocks through VMEM accumulating dk/dv in scratch; p never
    touches HBM (the jnp fallback's bandwidth wall)."""
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    i = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        p, ds = _bwd_common(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            scale=scale, causal=causal, block_q=block_q,
                            block_k=block_k, t_real=t_real, i=i, j=j)
        # p/ds cast to the input dtype: bf16 MXU passes with f32
        # accumulation (see the forward's dtype-policy note + the
        # ARCHITECTURE.md roofline — f32 operand matmuls were the MFU cap)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # q blocks strictly before this kv block contribute nothing
        @pl.when(i * block_q + (block_q - 1) >= j * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(i == nq - 1)
    def _emit():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                         dq_ref, dq_acc, *, scale, causal, block_q,
                         block_k, t_real):
    """dQ: grid (BH, q_blocks, kv_blocks) — one q block accumulates over
    its (causally relevant) kv blocks."""
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        _, ds = _bwd_common(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            scale=scale, causal=causal, block_q=block_q,
                            block_k=block_k, t_real=t_real, i=i, j=j)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(j * block_k <= i * block_q + (block_q - 1))
        def _():
            compute()
    else:
        compute()

    @pl.when(j == nk - 1)
    def _emit():
        dq_ref[:] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel_tri(im_ref, jm_ref, q_ref, do_ref, lse_ref,
                              delta_ref, k_ref, v_ref, dk_ref, dv_ref,
                              dk_acc, dv_acc, *, scale, block_q, block_k,
                              t_real, nq):
    """dK/dV on the triangular grid: column-major live tiles (the scratch
    accumulates q blocks within one kv column).  A column entirely in the
    future of every query keeps one dead diagonal tile whose mask zeroes
    p/ds, so its dk/dv block is still zero-written (see _causal_tiles)."""
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    i = im_ref[t]
    j = jm_ref[t]
    imin = jnp.minimum(nq - 1, (j * block_k) // block_q)

    @pl.when(i == imin)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    p, ds = _bwd_common(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        scale=scale, causal=True, block_q=block_q,
                        block_k=block_k, t_real=t_real, i=i, j=j)
    dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
        p.astype(do_ref.dtype), do_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
        ds.astype(q_ref.dtype), q_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _emit():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel_tri(im_ref, jm_ref, q_ref, do_ref, lse_ref,
                             delta_ref, k_ref, v_ref, dq_ref, dq_acc, *,
                             scale, block_q, block_k, t_real, nk):
    """dQ on the triangular grid: row-major live tiles (one q block
    accumulates its causally-relevant kv blocks)."""
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    i = im_ref[t]
    j = jm_ref[t]

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    _, ds = _bwd_common(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        scale=scale, causal=True, block_q=block_q,
                        block_k=block_k, t_real=t_real, i=i, j=j)
    dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
        ds.astype(k_ref.dtype), k_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    jmax = jnp.minimum(nk - 1, (i * block_q + block_q - 1) // block_k)

    @pl.when(j == jmax)
    def _emit():
        dq_ref[:] = dq_acc[:].astype(dq_ref.dtype)


# backward tile cap: 1024² measured fastest on v5e (the three [bq, bk]
# f32 temporaries fit VMEM; 2048² fails to compile) — sweep in PARITY
_BWD_CAP = 1024


def _flash_backward(q, k, v, out, lse, do, causal: bool, block_q: int,
                    block_k: int, interpret: bool):
    """Pallas flash-attention backward: the standard two-kernel split
    (dkv sweeping q per kv block; dq sweeping kv per q block — p/ds
    recomputed blockwise in VMEM, never materialized to HBM)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    # independent backward tile sizes (see _BWD_CAP)
    bq = min(block_q, _BWD_CAP)
    bk = min(block_k, _BWD_CAP)
    Tq = ((T + bq - 1) // bq) * bq
    Tk = ((T + bk - 1) // bk) * bk

    do_f = do.astype(jnp.float32)
    # rowwise D_i = sum_d dO_i·O_i (softmax-jacobian diagonal term)
    delta = jnp.einsum("bqhd,bqhd->bhq", do_f, out.astype(jnp.float32))

    def fold_q(x, pad_value=0.0):
        x = x.transpose(0, 2, 1, 3).reshape(B * H, T, D)
        return jnp.pad(x, [(0, 0), (0, Tq - T), (0, 0)],
                       constant_values=pad_value)

    qf = fold_q(q)
    dof = fold_q(do)
    kf = jnp.pad(k.transpose(0, 2, 1, 3).reshape(B * H, T, D),
                 [(0, 0), (0, Tk - T), (0, 0)])
    vf = jnp.pad(v.transpose(0, 2, 1, 3).reshape(B * H, T, D),
                 [(0, 0), (0, Tk - T), (0, 0)])
    # padded q rows: +BIG lse → p = exp(s - BIG) = 0, so they contribute
    # nothing to dk/dv and their dq rows are sliced off
    lse_f = jnp.pad(lse.reshape(B * H, T, 1),
                    [(0, 0), (0, Tq - T), (0, 0)],
                    constant_values=1e30)
    delta_f = jnp.pad(delta.reshape(B * H, T, 1),
                      [(0, 0), (0, Tq - T), (0, 0)])

    q_spec_i = pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0))
    q_spec_j = pl.BlockSpec((None, bq, D), lambda b, j, i: (b, i, 0))
    r_spec_i = pl.BlockSpec((None, bq, 1), lambda b, i, j: (b, i, 0))
    r_spec_j = pl.BlockSpec((None, bq, 1), lambda b, j, i: (b, i, 0))
    kv_spec_i = pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0))
    kv_spec_j = pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0))

    dkv_out_shape = [jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
                     jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype)]
    dkv_scratch = [pltpu.VMEM((bk, D), jnp.float32),
                   pltpu.VMEM((bk, D), jnp.float32)]
    tri = causal and _tri_tile_count(Tq // bq, Tk // bk,
                                     bq, bk) <= _TRI_TILE_CAP
    if tri:
        imc, jmc = _causal_tiles(Tq // bq, Tk // bk, bq, bk, "col")
        dkv_kernel = functools.partial(
            _flash_bwd_dkv_kernel_tri, scale=scale, block_q=bq,
            block_k=bk, t_real=T, nq=Tq // bq)
        q_tri = pl.BlockSpec((None, bq, D),
                             lambda b, t, im, jm: (b, im[t], 0))
        r_tri = pl.BlockSpec((None, bq, 1),
                             lambda b, t, im, jm: (b, im[t], 0))
        kv_tri = pl.BlockSpec((None, bk, D),
                              lambda b, t, im, jm: (b, jm[t], 0))
        dk_f, dv_f = pl.pallas_call(
            dkv_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, len(imc)),
                in_specs=[q_tri, q_tri, r_tri, r_tri, kv_tri, kv_tri],
                out_specs=[kv_tri, kv_tri],
                scratch_shapes=dkv_scratch,
            ),
            out_shape=dkv_out_shape,
            compiler_params=_tpu_compiler_params(pltpu, 
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(jnp.asarray(imc), jnp.asarray(jmc), qf, dof, lse_f, delta_f,
          kf, vf)
    else:
        dkv_kernel = functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, causal=causal, block_q=bq,
            block_k=bk, t_real=T)
        dk_f, dv_f = pl.pallas_call(
            dkv_kernel,
            grid=(B * H, Tk // bk, Tq // bq),
            in_specs=[q_spec_j, q_spec_j, r_spec_j, r_spec_j,
                      kv_spec_j, kv_spec_j],
            out_specs=[pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
                       pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0))],
            out_shape=dkv_out_shape,
            scratch_shapes=dkv_scratch,
            compiler_params=_tpu_compiler_params(pltpu, 
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=interpret,
        )(qf, dof, lse_f, delta_f, kf, vf)

    if tri:
        imr, jmr = _causal_tiles(Tq // bq, Tk // bk, bq, bk, "row")
        dq_kernel = functools.partial(
            _flash_bwd_dq_kernel_tri, scale=scale, block_q=bq,
            block_k=bk, t_real=T, nk=Tk // bk)
        dq_f = pl.pallas_call(
            dq_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, len(imr)),
                in_specs=[q_tri, q_tri, r_tri, r_tri, kv_tri, kv_tri],
                out_specs=q_tri,
                scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
            ),
            out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            compiler_params=_tpu_compiler_params(pltpu, 
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(jnp.asarray(imr), jnp.asarray(jmr), qf, dof, lse_f, delta_f,
          kf, vf)
    else:
        dq_kernel = functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal, block_q=bq,
            block_k=bk, t_real=T)
        dq_f = pl.pallas_call(
            dq_kernel,
            grid=(B * H, Tq // bq, Tk // bk),
            in_specs=[q_spec_i, q_spec_i, r_spec_i, r_spec_i,
                      kv_spec_i, kv_spec_i],
            out_specs=pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
            compiler_params=_tpu_compiler_params(pltpu, 
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=interpret,
        )(qf, dof, lse_f, delta_f, kf, vf)

    def unfold(x, Tp):
        return x.reshape(B, H, Tp, D).transpose(0, 2, 1, 3)[:, :T]

    return unfold(dq_f, Tq), unfold(dk_f, Tk), unfold(dv_f, Tk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Pallas flash attention. q,k,v: [B, T, H, D] → [B, T, H, D].

    T is padded to the block size internally (padding keys are masked out by
    the causal structure; non-causal callers must pass T multiple of the
    block).  `interpret=True` runs the same kernel on CPU for tests.

    Differentiable via custom VJP: the forward kernel emits the per-row
    log-sum-exp; the backward is the standard two-kernel Pallas split
    (dK/dV sweeping q blocks per kv block, dQ sweeping kv blocks per q
    block) with blockwise probability recompute in VMEM — O(T·block)
    memory and no HBM round trip for the probability matrices.
    """
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, do, causal, block_q, block_k,
                           interpret)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
