"""Attention ops: reference implementation + Pallas flash-attention kernel.

The reference system's only sequence model is an LSTM at look_back=1
(SURVEY §5 'long-context: nothing') — but this framework treats long per-car
sensor histories as first-class: fleets emit unbounded streams, and anomaly
models that look at hours of context need sequence lengths the LSTM path
never contemplated.  The attention stack here:

- `attention_reference`: straight jnp softmax attention — the oracle for
  every other path, and the XLA-fused fallback on CPU.
- `flash_attention`: blocked online-softmax attention as a Pallas TPU
  kernel — O(T) memory instead of O(T²), MXU-shaped [128×128] tiles, the
  single-chip hot op of the transformer model family.
- `blockwise_update`: one online-softmax accumulation step, shared between
  the flash kernel's inner loop (conceptually) and the ring-attention
  cross-chip loop (`parallel.ring_attention`), which is the same math with
  the KV blocks arriving over ICI instead of from VMEM.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attention_reference(q, k, v, causal: bool = True,
                        q_offset: int = 0, k_offset: int = 0):
    """Plain softmax attention. q,k,v: [B, T, H, D] → [B, Tq, H, D].

    q_offset/k_offset give the global positions of local blocks so the
    causal mask stays correct under sequence sharding.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])[:, None]
        kpos = k_offset + jnp.arange(k.shape[1])[None, :]
        mask = qpos >= kpos
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def blockwise_update(o, m, l, q, k_blk, v_blk, scale,
                     mask: Optional[jnp.ndarray] = None):
    """One online-softmax accumulation against a KV block.

    o: [B, Tq, H, D] running (unnormalized) output
    m: [B, H, Tq] running rowmax, l: [B, H, Tq] running denominator
    mask: [Tq, Tk] boolean (True = attend), already global-position-aware.
    Returns updated (o, m, l).  Final output is o / l[..., None].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows (m_new == NEG_INF): exp(NEG_INF - NEG_INF)=1
    # would pollute l; clamp the correction to 0 there.
    alive = m_new > NEG_INF / 2
    corr = jnp.where(alive, jnp.exp(m - m_new), 0.0)
    p = jnp.where(alive[..., None], jnp.exp(s - m_new[..., None]), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * jnp.transpose(corr, (0, 2, 1))[..., None] + \
        jnp.einsum("bhqk,bkhd->bqhd", p, v_blk)
    return o_new, m_new, l_new


def finalize_blockwise(o, l):
    """Normalize accumulated output; fully-masked rows come out zero."""
    denom = jnp.transpose(jnp.where(l == 0.0, 1.0, l), (0, 2, 1))[..., None]
    return o / denom


# --------------------------------------------------------------------- pallas
def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s, *,
                  scale: float, causal: bool, block_q: int, block_k: int):
    """Flash attention kernel.  Grid: (batch*heads, q_blocks, kv_blocks) —
    the kv dimension iterates sequentially on-core, so K/V stream through
    VMEM one [block_k, D] tile at a time (O(T) VMEM, long-context safe) and
    the online-softmax state lives in scratch that persists across the kv
    iterations of one q block."""
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    def compute():
        q = q_ref[:].astype(jnp.float32) * scale
        k_blk = k_ref[:].astype(jnp.float32)
        v_blk = v_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qi = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + i * block_q
            kj = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + j * block_k
            s = jnp.where(qi >= kj, s, NEG_INF)
        m = m_s[:]
        l = l_s[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alive = m_new > NEG_INF / 2
        corr = jnp.where(alive, jnp.exp(m - m_new), 0.0)
        p = jnp.where(alive, jnp.exp(s - m_new), 0.0)
        m_s[:] = m_new
        l_s[:] = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # whole KV block strictly in the future of this q block → skip
        @pl.when(j * block_k <= i * block_q + (block_q - 1))
        def _():
            compute()
    else:
        compute()

    @pl.when(j == nk - 1)
    def _emit():
        l = l_s[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc[:] / safe_l).astype(o_ref.dtype)
        # log-sum-exp per query row (needed by the custom-VJP backward)
        lse_ref[:] = jnp.where(l == 0.0, NEG_INF, m_s[:] + jnp.log(safe_l))


def _flash_forward(q, k, v, causal: bool, block_q: int,
                   block_k: int, interpret: bool):
    """Run the Pallas kernel; returns (out [B,T,H,D], lse [B,H,T])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    Tq = ((T + block_q - 1) // block_q) * block_q
    Tk = ((T + block_k - 1) // block_k) * block_k
    if not causal and Tk != T:
        # padded keys are only excluded by the causal mask; non-causal
        # callers must supply block-multiple sequence lengths
        raise ValueError(f"non-causal flash attention needs T % {block_k} == 0")
    if Tq != T:
        pad = [(0, 0), (0, Tq - T), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
    if Tk != T:
        pad = [(0, 0), (0, Tk - T), (0, 0), (0, 0)]
        # pad keys so padded positions never win the max: values 0, and the
        # causal mask (global positions) excludes them for every real query
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    # layout: fold batch & heads into the grid's first axis, T-major blocks
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)

    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q, Tk // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)[:, :T]
    lse = lse.reshape(B, H, Tq)[:, :, :T]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Pallas flash attention. q,k,v: [B, T, H, D] → [B, T, H, D].

    T is padded to the block size internally (padding keys are masked out by
    the causal structure; non-causal callers must pass T multiple of the
    block).  `interpret=True` runs the same kernel on CPU for tests.

    Differentiable via custom VJP: the forward kernel emits the per-row
    log-sum-exp; the backward recomputes attention probabilities blockwise
    in jnp (lax.scan over KV blocks — O(T·block) memory, XLA-fused), the
    standard flash-attention recompute strategy.
    """
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    B, T, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32)
    do = do.astype(jnp.float32)
    # rowwise D_i = sum_d dO_i·O_i  (the softmax-jacobian diagonal term)
    delta = jnp.einsum("bqhd,bqhd->bhq", do, out.astype(jnp.float32))

    nkb = (T + block_k - 1) // block_k
    Tp = nkb * block_k
    pad = [(0, 0), (0, Tp - T), (0, 0), (0, 0)]
    kp = jnp.pad(k.astype(jnp.float32), pad).reshape(B, nkb, block_k, H, D)
    vp = jnp.pad(v.astype(jnp.float32), pad).reshape(B, nkb, block_k, H, D)
    kpos_pad = jnp.arange(Tp).reshape(nkb, block_k)
    qpos = jnp.arange(T)

    def kv_block(dq_acc, blk):
        k_blk, v_blk, kpos = blk  # [B,block_k,H,D], [block_k]
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk) * scale
        mask = kpos[None, :] < T  # padding guard
        if causal:
            mask = mask & (qpos[:, None] >= kpos[None, :])
        s = jnp.where(mask[None, None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])  # [B,H,Tq,block_k]; 0 where masked
        dv = jnp.einsum("bhqk,bqhd->bkhd", p, do)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do, v_blk)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, k_blk)
        dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((B, T, H, D), jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(
        kv_block, dq0,
        (kp.transpose(1, 0, 2, 3, 4), vp.transpose(1, 0, 2, 3, 4), kpos_pad))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(B, Tp, H, D)[:, :T]
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(B, Tp, H, D)[:, :T]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
