"""Schema-compiled Avro binary codec → columnar numpy blocks.

TPU-native replacement for the reference's C++ ``tensorflow_io.kafka
.decode_avro`` op (cardata-v3.py:53-74): given a record schema, decode a
*batch* of Avro-binary messages into one numpy column per field, ready to be
stacked into a fixed-shape device batch.  Row-at-a-time Python decoding would
never feed a TPU; the design splits into

- this pure-Python/numpy codec (reference implementation + test oracle), and
- a C++ twin in ``cpp/stream`` with the same columnar output contract,
  loaded via ctypes when built (see `iotml.stream.native`).

Supported schema features are exactly what the car/KSQL schemas need:
primitives float/double/int/long/boolean/string/bytes and the nullable
2-branch union ``["null", T]`` with the Avro spec's zigzag-varint framing.
"""

from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

from ..core.schema import RecordSchema, Field

_F32 = struct.Struct("<f")
_F64 = struct.Struct("<d")


# ---------------------------------------------------------------- primitives
def zigzag_encode(n: int) -> bytes:
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def zigzag_decode(buf: bytes, pos: int) -> tuple:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


class AvroCodec:
    """Encoder/decoder for one record schema.

    ``decode_batch(messages)`` returns a dict {field_name: numpy array};
    string columns come back as object arrays.  ``encode(record)`` takes a
    dict keyed by field name (missing nullable fields encode as null).
    """

    def __init__(self, schema: RecordSchema):
        self.schema = schema
        self._fields: Sequence[Field] = schema.fields

    # ------------------------------------------------------------ encoding
    def encode(self, record: dict) -> bytes:
        out = bytearray()
        for f in self._fields:
            v = record.get(f.name)
            if f.nullable:
                if v is None:
                    out += zigzag_encode(0)  # union branch 0 = null
                    continue
                out += zigzag_encode(1)
            self._encode_prim(out, f.avro_type, v)
        return bytes(out)

    @staticmethod
    def _encode_prim(out: bytearray, t: str, v):
        if t == "float":
            out += _F32.pack(float(v))
        elif t == "double":
            out += _F64.pack(float(v))
        elif t in ("int", "long"):
            out += zigzag_encode(int(v))
        elif t == "boolean":
            out.append(1 if v else 0)
        elif t == "string":
            b = v.encode() if isinstance(v, str) else bytes(v)
            out += zigzag_encode(len(b)) + b
        elif t == "bytes":
            out += zigzag_encode(len(v)) + bytes(v)
        else:  # pragma: no cover
            raise TypeError(f"unsupported avro primitive {t}")

    # ------------------------------------------------------------ decoding
    def decode(self, message: bytes) -> dict:
        return self._decode_at(message, 0)[0]

    def _decode_at(self, buf: bytes, pos: int) -> tuple:
        """Decode one record starting at pos → (record, next_pos).  The
        position-tracking form lets container blocks hold many records."""
        rec = {}
        for f in self._fields:
            if f.nullable:
                branch, pos = zigzag_decode(buf, pos)
                if branch == 0:
                    rec[f.name] = None
                    continue
            rec[f.name], pos = self._decode_prim(buf, pos, f.avro_type)
        return rec, pos

    @staticmethod
    def _decode_prim(buf: bytes, pos: int, t: str):
        if t == "float":
            return _F32.unpack_from(buf, pos)[0], pos + 4
        if t == "double":
            return _F64.unpack_from(buf, pos)[0], pos + 8
        if t in ("int", "long"):
            return zigzag_decode(buf, pos)
        if t == "boolean":
            return bool(buf[pos]), pos + 1
        if t in ("string", "bytes"):
            n, pos = zigzag_decode(buf, pos)
            raw = buf[pos:pos + n]
            return (raw.decode() if t == "string" else raw), pos + n
        raise TypeError(f"unsupported avro primitive {t}")  # pragma: no cover

    def decode_batch(self, messages: List[bytes], null_fill=0.0) -> dict:
        """Decode many messages into columns.

        Nullable numeric fields decode nulls to ``null_fill``; nullable
        strings decode nulls to ``""`` (matching the reference's observed
        'no value' label case, cardata-v3.py:267).
        """
        n = len(messages)
        cols = {}
        for f in self._fields:
            if f.avro_type in ("string", "bytes"):
                cols[f.name] = np.empty((n,), object)
            else:
                cols[f.name] = np.zeros((n,), f.np_dtype)
        for i, msg in enumerate(messages):
            rec = self.decode(msg)
            for f in self._fields:
                v = rec[f.name]
                if v is None:
                    v = "" if f.avro_type in ("string", "bytes") else null_fill
                cols[f.name][i] = v
        return cols

    def sensor_matrix(self, cols: dict, dtype=np.float64) -> np.ndarray:
        """Stack the sensor columns (schema order, label excluded) into
        a [N, num_sensors] matrix — the decode→stack step the reference does
        in-graph (cardata-v3.py:150-168)."""
        names = [f.name for f in self.schema.sensor_fields]
        return np.stack([cols[n].astype(dtype) for n in names], axis=1)


# ------------------------------------------------------ schema evolution
#: the Confluent frame header every pre-evolution payload carries
#: (magic 0 + schema id 1, `ops.framing`)
_V1_HEADER = b"\x00\x00\x00\x00\x01"


def needs_resolution(value: bytes) -> bool:
    """True for a well-formed Confluent frame whose writer id is a
    KNOWN non-default schema (schema evolution on a live topic) —
    the cheap prefix test readers use to route a chunk through the
    resolving decode path.  Unknown ids and non-Confluent payloads
    return False: their legacy failure mode (DLQ downstream) must not
    change."""
    if len(value) < 5 or value[:5] == _V1_HEADER or value[0] != 0:
        return False
    return int.from_bytes(value[1:5], "big") in _writer_schemas()


def _writer_schemas():
    """Module-cached WRITER_SCHEMAS (needs_resolution runs per message
    in the hot decode loop — an import statement there would pay a
    sys.modules lookup per record)."""
    global _WRITER_SCHEMAS
    if _WRITER_SCHEMAS is None:
        from ..core.schema import WRITER_SCHEMAS

        _WRITER_SCHEMAS = WRITER_SCHEMAS
    return _WRITER_SCHEMAS


_WRITER_SCHEMAS = None


def resolve_record(rec: dict, reader: RecordSchema) -> dict:
    """Avro schema-resolution projection, name-based (spec §"Schema
    Resolution"): reader fields take the writer's value when the writer
    had the field; writer-only fields are dropped; reader fields the
    writer lacks take their default (null for the nullable unions this
    framework uses — a required reader field missing from the writer is
    an incompatible evolution and raises)."""
    out = {}
    for f in reader.fields:
        if f.name in rec:
            out[f.name] = rec[f.name]
        elif f.nullable:
            out[f.name] = None
        else:
            raise ValueError(
                f"incompatible schema evolution: required reader field "
                f"{f.name!r} missing from writer record")
    return out


class ResolvingCodec:
    """Schema-id-dispatching decoder for mixed-version topics.

    A live topic under rolling fleet upgrades holds v1 AND v2 framed
    payloads side by side; each message's Confluent frame names its
    WRITER schema.  This codec decodes every message with its writer's
    codec and projects the record onto the fixed READER schema (the
    ML layer's v1 view), implementing the subset of Avro schema
    resolution the nullable-union car schemas need.  Positional v1
    decode of v2 bytes — the failure mode this replaces — previously
    dead-lettered (or worse, silently mis-read) every v2 chunk.
    """

    def __init__(self, reader: RecordSchema, writers=None):
        from ..core.schema import WRITER_SCHEMAS

        self.reader = reader
        self.writers = {}
        for sid, schema in (writers or WRITER_SCHEMAS).items():
            self.writers[sid] = (schema, AvroCodec(schema))

    def decode_framed(self, message: bytes) -> dict:
        """One framed message → a record in the READER's fields."""
        from .framing import unframe

        sid, payload = unframe(message)
        entry = self.writers.get(sid)
        if entry is None:
            raise ValueError(f"unknown writer schema id {sid}")
        schema, codec = entry
        rec = codec.decode(payload)
        if schema is self.reader:
            return rec
        return resolve_record(rec, self.reader)

    def decode_batch_framed(self, messages: List[bytes],
                            null_fill=0.0) -> dict:
        """Mixed-version batch → reader-schema columns (the
        ``AvroCodec.decode_batch`` contract, resolution included)."""
        n = len(messages)
        cols = {}
        for f in self.reader.fields:
            if f.avro_type in ("string", "bytes"):
                cols[f.name] = np.empty((n,), object)
            else:
                cols[f.name] = np.zeros((n,), f.np_dtype)
        for i, msg in enumerate(messages):
            rec = self.decode_framed(msg)
            for f in self.reader.fields:
                v = rec[f.name]
                if v is None:
                    v = "" if f.avro_type in ("string", "bytes") \
                        else null_fill
                cols[f.name][i] = v
        return cols
