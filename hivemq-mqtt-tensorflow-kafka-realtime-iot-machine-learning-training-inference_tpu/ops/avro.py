"""Schema-compiled Avro binary codec → columnar numpy blocks.

TPU-native replacement for the reference's C++ ``tensorflow_io.kafka
.decode_avro`` op (cardata-v3.py:53-74): given a record schema, decode a
*batch* of Avro-binary messages into one numpy column per field, ready to be
stacked into a fixed-shape device batch.  Row-at-a-time Python decoding would
never feed a TPU; the design splits into

- this pure-Python/numpy codec (reference implementation + test oracle), and
- a C++ twin in ``cpp/stream`` with the same columnar output contract,
  loaded via ctypes when built (see `iotml.stream.native`).

Supported schema features are exactly what the car/KSQL schemas need:
primitives float/double/int/long/boolean/string/bytes and the nullable
2-branch union ``["null", T]`` with the Avro spec's zigzag-varint framing.
"""

from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

from ..core.schema import RecordSchema, Field

_F32 = struct.Struct("<f")
_F64 = struct.Struct("<d")


# ---------------------------------------------------------------- primitives
def zigzag_encode(n: int) -> bytes:
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def zigzag_decode(buf: bytes, pos: int) -> tuple:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


class AvroCodec:
    """Encoder/decoder for one record schema.

    ``decode_batch(messages)`` returns a dict {field_name: numpy array};
    string columns come back as object arrays.  ``encode(record)`` takes a
    dict keyed by field name (missing nullable fields encode as null).
    """

    def __init__(self, schema: RecordSchema):
        self.schema = schema
        self._fields: Sequence[Field] = schema.fields

    # ------------------------------------------------------------ encoding
    def encode(self, record: dict) -> bytes:
        out = bytearray()
        for f in self._fields:
            v = record.get(f.name)
            if f.nullable:
                if v is None:
                    out += zigzag_encode(0)  # union branch 0 = null
                    continue
                out += zigzag_encode(1)
            self._encode_prim(out, f.avro_type, v)
        return bytes(out)

    @staticmethod
    def _encode_prim(out: bytearray, t: str, v):
        if t == "float":
            out += _F32.pack(float(v))
        elif t == "double":
            out += _F64.pack(float(v))
        elif t in ("int", "long"):
            out += zigzag_encode(int(v))
        elif t == "boolean":
            out.append(1 if v else 0)
        elif t == "string":
            b = v.encode() if isinstance(v, str) else bytes(v)
            out += zigzag_encode(len(b)) + b
        elif t == "bytes":
            out += zigzag_encode(len(v)) + bytes(v)
        else:  # pragma: no cover
            raise TypeError(f"unsupported avro primitive {t}")

    # ------------------------------------------------------------ decoding
    def decode(self, message: bytes) -> dict:
        return self._decode_at(message, 0)[0]

    def _decode_at(self, buf: bytes, pos: int) -> tuple:
        """Decode one record starting at pos → (record, next_pos).  The
        position-tracking form lets container blocks hold many records."""
        rec = {}
        for f in self._fields:
            if f.nullable:
                branch, pos = zigzag_decode(buf, pos)
                if branch == 0:
                    rec[f.name] = None
                    continue
            rec[f.name], pos = self._decode_prim(buf, pos, f.avro_type)
        return rec, pos

    @staticmethod
    def _decode_prim(buf: bytes, pos: int, t: str):
        if t == "float":
            return _F32.unpack_from(buf, pos)[0], pos + 4
        if t == "double":
            return _F64.unpack_from(buf, pos)[0], pos + 8
        if t in ("int", "long"):
            return zigzag_decode(buf, pos)
        if t == "boolean":
            return bool(buf[pos]), pos + 1
        if t in ("string", "bytes"):
            n, pos = zigzag_decode(buf, pos)
            raw = buf[pos:pos + n]
            return (raw.decode() if t == "string" else raw), pos + n
        raise TypeError(f"unsupported avro primitive {t}")  # pragma: no cover

    def decode_batch(self, messages: List[bytes], null_fill=0.0) -> dict:
        """Decode many messages into columns.

        Nullable numeric fields decode nulls to ``null_fill``; nullable
        strings decode nulls to ``""`` (matching the reference's observed
        'no value' label case, cardata-v3.py:267).
        """
        n = len(messages)
        cols = {}
        for f in self._fields:
            if f.avro_type in ("string", "bytes"):
                cols[f.name] = np.empty((n,), object)
            else:
                cols[f.name] = np.zeros((n,), f.np_dtype)
        for i, msg in enumerate(messages):
            rec = self.decode(msg)
            for f in self._fields:
                v = rec[f.name]
                if v is None:
                    v = "" if f.avro_type in ("string", "bytes") else null_fill
                cols[f.name][i] = v
        return cols

    def sensor_matrix(self, cols: dict, dtype=np.float64) -> np.ndarray:
        """Stack the sensor columns (schema order, label excluded) into
        a [N, num_sensors] matrix — the decode→stack step the reference does
        in-graph (cardata-v3.py:150-168)."""
        names = [f.name for f in self.schema.sensor_fields]
        return np.stack([cols[n].astype(dtype) for n in names], axis=1)
