"""Avro Object Container File (OCF) writer/reader.

The reference's data-lake path sinks the `SENSOR_DATA_S_AVRO` topic to GCS
"in Avro format" via the Kafka Connect GCS connector (reference
`infrastructure/kafka-connect/gcs/README.md:21-43`) — i.e. standard `.avro`
container files any Avro tool can read: magic `Obj\\x01`, a metadata map
carrying the writer schema + codec, a 16-byte sync marker, then blocks of
`(record_count, byte_length, records..., sync)`.  This is that format
(null codec), built on the framework's own binary codec so lake files are
self-describing and interoperable with fastavro / avro-tools.
"""

from __future__ import annotations

import io
import os
from typing import Iterator, List, Tuple

from ..core.schema import RecordSchema
from .avro import AvroCodec, zigzag_decode, zigzag_encode

MAGIC = b"Obj\x01"


def _encode_bytes(b: bytes) -> bytes:
    return zigzag_encode(len(b)) + b


def _encode_meta(meta: dict) -> bytes:
    out = bytearray()
    out += zigzag_encode(len(meta))
    for k, v in meta.items():
        out += _encode_bytes(k.encode())
        out += _encode_bytes(v if isinstance(v, bytes) else v.encode())
    out += zigzag_encode(0)  # end of map
    return bytes(out)


def _decode_bytes(buf: bytes, pos: int) -> Tuple[bytes, int]:
    n, pos = zigzag_decode(buf, pos)
    return buf[pos:pos + n], pos + n


def _decode_meta(buf: bytes, pos: int) -> Tuple[dict, int]:
    meta = {}
    while True:
        count, pos = zigzag_decode(buf, pos)
        if count == 0:
            return meta, pos
        if count < 0:  # block-size variant: long byte-size follows
            _, pos = zigzag_decode(buf, pos)
            count = -count
        for _ in range(count):
            k, pos = _decode_bytes(buf, pos)
            v, pos = _decode_bytes(buf, pos)
            meta[k.decode()] = v


class ContainerWriter:
    """Write records (already binary-encoded, or dicts via a codec) to an
    OCF file. One block per `write_block` call."""

    def __init__(self, path: str, schema: RecordSchema, sync: bytes = None):
        self.path = path
        self.schema = schema
        self.codec = AvroCodec(schema)
        # deterministic per-path marker keeps tests reproducible; 16 bytes
        self.sync = sync if sync is not None else \
            __import__("hashlib").md5(path.encode()).digest()
        if len(self.sync) != 16:
            raise ValueError(
                f"sync marker must be 16 bytes, got {len(self.sync)}")
        self._fh = open(path, "wb")
        self._fh.write(MAGIC)
        self._fh.write(_encode_meta({
            "avro.schema": schema.avro_json(),
            "avro.codec": "null",
        }))
        self._fh.write(self.sync)
        self.records_written = 0

    def write_block(self, records: List) -> int:
        """records: dicts (encoded via the schema codec) or raw bytes
        (already schema-encoded payloads, e.g. unframed stream messages)."""
        if not records:
            return 0
        body = io.BytesIO()
        for r in records:
            body.write(r if isinstance(r, bytes) else self.codec.encode(r))
        blob = body.getvalue()
        self._fh.write(zigzag_encode(len(records)))
        self._fh.write(zigzag_encode(len(blob)))
        self._fh.write(blob)
        self._fh.write(self.sync)
        self.records_written += len(records)
        return len(records)

    def close(self) -> None:
        self._fh.close()

    def __enter__(self) -> "ContainerWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_container(path: str) -> Tuple[RecordSchema, List[dict]]:
    """Read a (null-codec) OCF file → (schema, records)."""
    from ..stream.registry import parse_avsc

    buf = open(path, "rb").read()
    if buf[:4] != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    meta, pos = _decode_meta(buf, 4)
    if meta.get("avro.codec", b"null") not in (b"null", "null"):
        raise ValueError(f"unsupported codec {meta['avro.codec']!r}")
    schema = parse_avsc(meta["avro.schema"].decode()
                        if isinstance(meta["avro.schema"], bytes)
                        else meta["avro.schema"])
    codec = AvroCodec(schema)
    sync = buf[pos:pos + 16]
    pos += 16
    records = []
    while pos < len(buf):
        count, pos = zigzag_decode(buf, pos)
        size, pos = zigzag_decode(buf, pos)
        block = buf[pos:pos + size]
        pos += size
        bpos = 0
        for _ in range(count):
            rec, bpos = codec._decode_at(block, bpos)
            records.append(rec)
        if buf[pos:pos + 16] != sync:
            raise ValueError(f"{path}: bad sync marker at {pos}")
        pos += 16
    return schema, records
