"""Confluent Schema-Registry wire framing + the raw frame-batch contract.

Records on the Kafka topic the ML layer consumes are not bare Avro: the
Schema Registry serializer prepends a 5-byte header — magic byte ``0`` plus
a big-endian uint32 schema id.  The reference strips it in-graph with
``tf.strings.substr(e, 5, -1)`` (cardata-v3.py:50).  We keep the format
byte-compatible so our stream engine interoperates with real Confluent
payloads.

This module is also the stream layer's half of the ONE frame contract
(lint R14): the segmented log's CRC32C frame layout
``[len|crc|attrs|offset|ts|key|value|headers]`` (store/segment.py) is
the wire→disk→host batch format — ``Broker.fetch_raw``, the wire's
RAW_FETCH and the replay API all hand back `RawFrameBatch` views of it,
and the only parsers are ``store.segment`` and the helpers here (which
delegate to it).  The C++ twin is ``cpp/frame_engine.cc``; the pure
functions below are its byte-parity oracle and the no-toolchain
fallback.
"""

from __future__ import annotations

import struct
from typing import List, NamedTuple, Optional, Tuple

MAGIC = 0
SCHEMA_ID_DEFAULT = 1
_HDR = struct.Struct(">bI")


def frame(payload: bytes, schema_id: int = SCHEMA_ID_DEFAULT) -> bytes:
    """Prepend the Confluent 5-byte header."""
    return _HDR.pack(MAGIC, schema_id) + payload


def unframe(message: bytes) -> tuple:
    """Split a framed message into (schema_id, payload).

    Raises ValueError on a non-Confluent magic byte — callers that want the
    reference's permissive substr(5) behavior should use ``strip_frame``.
    """
    magic, schema_id = _HDR.unpack_from(message)
    if magic != MAGIC:
        raise ValueError(f"bad Confluent magic byte: {magic}")
    return schema_id, message[5:]


def strip_frame(message: bytes) -> bytes:
    """Reference-equivalent framing strip: drop the first 5 bytes blindly."""
    return message[5:]


# ------------------------------------------------------ raw frame batches
class RawFrameBatch(NamedTuple):
    """A batch of records as CONTIGUOUS store-format frame bytes.

    The zero-copy carrier between fetch and decode: no per-record Python
    objects, just one buffer + the cursor it was read at.  ``data`` may
    begin with frames below ``start_offset`` (sparse-index alignment —
    the decoder skips them) and may end mid-frame (a torn tail ends the
    batch, exactly like crash recovery); the decoder reports the true
    row count and next cursor."""

    topic: str
    partition: int
    start_offset: int   # the requested cursor; frames below are skipped
    data: bytes         # store-format frames (segment.py layout)


#: stop-flag bits shared with the native decoder (frame_engine.cc)
FRAMES_STOP_TORN = 1
FRAMES_STOP_SCHEMA = 2


# ----------------------------------------------- batch-granular headers
def stamp_first_frame(buf: bytes, headers) -> bytes:
    """Attach `headers` ([(key, value)]) to the FIRST frame of a raw
    batch, re-framing only that one record — the batch-granular trace
    carrier of the wire-trace leg (ISSUE 13): one record re-encode per
    SAMPLED batch, zero cost on unstamped batches.  Headers ride the
    store frame's own headers field, so they survive RAW_PRODUCE,
    segment append, replica mirroring and RAW_FETCH verbatim.  Returns
    `buf` unchanged when it holds no complete frame."""
    from ..store import segment as seg

    for pos, end, off, key, value, ts, hdrs in seg.scan_records(buf):
        merged = tuple(hdrs or ()) + tuple(headers)
        return (buf[:pos] + seg.encode_record(off, key, value, ts, merged)
                + buf[end:])
    return buf


def first_frame_headers(buf, at_or_after: Optional[int] = None
                        ) -> Optional[tuple]:
    """Headers of the first complete frame in a raw batch (None when
    absent) — the consume-side twin of `stamp_first_frame`.  O(one
    frame), never a batch walk: batch-granular by construction.

    ``at_or_after``: answer None when the first frame's offset is below
    it.  A raw read is sparse-index ALIGNED — it may re-serve the batch
    head below the requested cursor — and without this guard every
    later slice of one stamped batch would re-extract (and re-close)
    the same trace context."""
    from ..store import segment as seg

    for _pos, _end, off, _k, _v, _ts, hdrs in seg.scan_records(buf):
        if at_or_after is not None and off < at_or_after:
            return None
        return hdrs
    return None


class CorruptFrameError(ValueError):
    """A pre-framed batch failed CRC/offset validation at frame `index`.

    The write-path rejection signal: RAW_PRODUCE batches are validated
    WHOLE before any byte may land in a segment (no torn/partial
    appends), and the broker maps this to Kafka CORRUPT_MESSAGE (2)."""

    def __init__(self, index: int, detail: str = ""):
        super().__init__(
            f"corrupt frame batch at frame {index}"
            + (f": {detail}" if detail else "")
            + " — whole batch rejected, nothing appended")
        self.index = index


def encode_frame_batch(entries) -> bytes:
    """[(offset, key, value, timestamp_ms, headers)] → contiguous frame
    bytes — how the IN-MEMORY broker (and the chaos fixtures) express a
    batch in the store's frame format.  Delegates to the store's frame
    codec: one encoder, one layout (lint R14)."""
    from ..store import segment as seg

    return b"".join(
        seg.encode_record(off, key, value, ts, headers)
        for off, key, value, ts, headers in entries)


def iter_frame_entries(buf: bytes):
    """Yield (offset, key, value, timestamp_ms, headers) for every valid
    frame in `buf` — the in-memory emulator's RAW_PRODUCE landing leg
    and the replica's in-memory mirror leg (both decode through the ONE
    parser; the durable backend appends the bytes verbatim instead)."""
    from ..store import segment as seg

    for _pos, _end, off, key, value, ts, hdrs in seg.scan_records(buf):
        yield off, key, value, ts, hdrs


# ---------------------------------------------------- write-path helpers
def _native_lib():
    """frame_engine.cc when present — None falls back to the oracle."""
    try:
        from ..stream.native import load

        return load()
    except Exception:  # noqa: BLE001 - no toolchain: pure-python path
        return None


def frame_entries(entries, base_offset: int = 0) -> bytes:
    """[(key, value, timestamp_ms[, headers])] → contiguous store frames
    stamped ``base_offset + i`` — the generic produce-side framing entry
    (bridge JSON leg, rekey pass-through, durable produce_many fusion).
    Native (`iotml_frames_encode_values`) when the engine is loaded and
    no entry carries headers; the python codec otherwise — output bytes
    identical either way (pinned by tests)."""
    entries = entries if isinstance(entries, list) else list(entries)
    lib = _native_lib()
    if lib is not None and entries and \
            not any(len(e) > 3 and e[3] for e in entries):
        import ctypes

        import numpy as np

        n = len(entries)
        values = b"".join(e[1] or b"" for e in entries)
        voff = np.zeros((n + 1,), np.int64)
        np.cumsum([len(e[1] or b"") for e in entries], out=voff[1:])
        vnull = np.asarray([1 if e[1] is None else 0 for e in entries],
                           np.uint8)
        keys = b"".join(e[0] or b"" for e in entries)
        koff = np.zeros((n + 1,), np.int64)
        np.cumsum([len(e[0] or b"") for e in entries], out=koff[1:])
        knull = np.asarray([1 if e[0] is None else 0 for e in entries],
                           np.uint8)
        ts = np.asarray([e[2] for e in entries], np.int64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        cap = len(values) + len(keys) + 64 * n + 64
        out = ctypes.create_string_buffer(cap)
        rc = lib.iotml_frames_encode_values(
            ctypes.c_char_p(values), voff.ctypes.data_as(i64p),
            ctypes.c_char_p(keys), koff.ctypes.data_as(i64p),
            knull.ctypes.data_as(u8p), vnull.ctypes.data_as(u8p),
            ts.ctypes.data_as(i64p), ctypes.c_int64(n),
            ctypes.c_int64(int(base_offset)),
            ctypes.cast(out, u8p), ctypes.c_int64(cap))
        if rc >= 0:
            return out.raw[:rc]
    return encode_frame_batch(
        (base_offset + i, e[0], e[1], e[2],
         e[3] if len(e) > 3 else None)
        for i, e in enumerate(entries))


def restamp_frame_batch(buf: bytes, base_offset: int
                        ) -> Tuple[bytes, int, int]:
    """CRC-validate a pre-framed batch WHOLE and stamp real log offsets
    (``base_offset + i``) into the frame heads, recomputing each CRC —
    the broker's RAW_PRODUCE landing step.  Returns
    ``(stamped_bytes, count, max_ts)``; raises `CorruptFrameError` on
    any torn/corrupt frame or trailing garbage (nothing may land)."""
    lib = _native_lib()
    if lib is not None:
        import ctypes

        mutable = ctypes.create_string_buffer(bytes(buf), len(buf))
        max_ts = ctypes.c_int64(-1)
        rc = lib.iotml_frames_restamp(
            ctypes.cast(mutable, ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(len(buf)), ctypes.c_int64(int(base_offset)),
            ctypes.byref(max_ts))
        if rc < 0:
            raise CorruptFrameError(-rc - 1, "CRC/length mismatch")
        return mutable.raw[:len(buf)], int(rc), int(max_ts.value)
    # oracle: strict scan through the one parser, then re-encode with the
    # stamped offsets (byte-identical to the in-place native patch)
    from ..store import segment as seg

    out = []
    consumed = 0
    max_ts = -1
    for _pos, end, _off, key, value, ts, hdrs in seg.scan_records(buf):
        out.append(seg.encode_record(base_offset + len(out), key, value,
                                     ts, hdrs))
        if ts > max_ts:
            max_ts = ts
        consumed = end
    if consumed != len(buf):
        raise CorruptFrameError(len(out), "torn/corrupt tail")
    return b"".join(out), len(out), max_ts


def validate_frame_batch(buf: bytes, start_offset: int = 0,
                         strict: bool = False) -> dict:
    """CRC + offset-monotonicity walk over a raw frame batch — the
    replica's zero-copy mirror validation.  Frames below `start_offset`
    are the sparse-index alignment (skipped); a torn TAIL ends the batch
    (strict=False) or rejects it (strict=True).  Returns a dict with
    ``count / first / last / max_ts / start_pos / end_pos / contiguous``
    where [start_pos, end_pos) is the byte range of the in-range frames
    (appendable verbatim).  Raises `CorruptFrameError` on a strict
    violation or a non-monotone offset."""
    lib = _native_lib()
    if lib is not None:
        import ctypes

        outs = [ctypes.c_int64(0) for _ in range(6)]
        rc = lib.iotml_frames_validate(
            ctypes.cast(ctypes.c_char_p(bytes(buf)),
                        ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(len(buf)), ctypes.c_int64(int(start_offset)),
            ctypes.c_int64(1 if strict else 0),
            *[ctypes.byref(o) for o in outs])
        if rc < 0:
            raise CorruptFrameError(-rc - 1, "validation failed")
        first, last, start_pos, end_pos, max_ts, contiguous = \
            (int(o.value) for o in outs)
        return dict(count=int(rc), first=first, last=last,
                    max_ts=max_ts, start_pos=start_pos, end_pos=end_pos,
                    contiguous=bool(contiguous))
    from ..store import segment as seg

    count = 0
    first = last = -1
    start_pos = 0
    end_pos = 0
    max_ts = -1
    prev = -1
    consumed = 0
    for pos, end, off, _k, _v, ts, _h in seg.scan_records(buf):
        if off <= prev:
            raise CorruptFrameError(count, "non-monotone offset")
        prev = off
        consumed = end
        if off < start_offset:
            continue
        if first < 0:
            first = off
            start_pos = pos
        last = off
        end_pos = end
        if ts > max_ts:
            max_ts = ts
        count += 1
    if strict and consumed != len(buf):
        raise CorruptFrameError(count, "torn/corrupt tail")
    return dict(count=count, first=first, last=last, max_ts=max_ts,
                start_pos=start_pos, end_pos=end_pos,
                contiguous=count == 0 or last - first + 1 == count)


def truncate_frame_batch(buf: bytes, max_offset_exclusive: int) -> bytes:
    """The prefix of a raw frame batch whose record offsets are all
    below ``max_offset_exclusive`` — the quorum read barrier's cut
    (iotml.replication): a consumer-facing raw fetch must not ship
    frames past the quorum high-water mark.  Cuts at a frame boundary
    by construction; a torn/corrupt frame ends the walk exactly like
    every other reader of the format."""
    from ..store import segment as seg

    end_pos = 0
    try:
        for _pos, end, off, _k, _v, _ts, _h in seg.scan_records(buf):
            if off >= max_offset_exclusive:
                break
            end_pos = end
    except ValueError:
        pass  # corrupt frame: keep the clean prefix below the ceiling
    return buf[:end_pos]


def decode_frames_columnar_py(
        buf: bytes, start_offset: int, schema,
        pinned_id_limit: Optional[int] = None,
        cap_rows: int = 1 << 62, label_stride: int = 16,
        key_stride: int = 64, with_keys: bool = False,
        want_ts: bool = False
) -> Tuple["np.ndarray", "np.ndarray", Optional["np.ndarray"],
           int, int, int]:
    """Pure-Python twin of ``cpp/frame_engine.cc``'s columnar decoder —
    the byte-parity ORACLE (tests) and the no-toolchain fallback.

    Walks store frames in ``buf`` via ``store.segment.scan_records`` (the
    one parser), applies the same stop conditions (torn/corrupt frame,
    Confluent schema-id mismatch, cap) and fills float32 numeric /
    fixed-stride label / key columns.  Returns
    ``(numeric [n,F] float32, labels [n,S] S-stride, keys|None,
    next_offset, flags, skipped_tombstones)``; with ``want_ts`` the
    tuple grows ``(ts_min, ts_max)`` — event-time bounds (ms) over the
    consumed frames, tombstones included, -1 when nothing consumed
    (parity with ``iotml_frames_decode_columnar_ts``).
    """
    import numpy as np

    from ..ops.avro import AvroCodec
    from ..store import segment as seg

    if pinned_id_limit is None:
        from ..stream.registry import RESERVED_ID_BASE

        pinned_id_limit = RESERVED_ID_BASE
    codec = AvroCodec(schema)
    strings = [f.name for f in schema.fields if f.avro_type == "string"]
    numerics = [f.name for f in schema.fields if f.avro_type != "string"]
    rows_num: List[list] = []
    rows_lab: List[list] = []
    rows_key: List[bytes] = []
    flags = 0
    skipped = 0
    next_offset = start_offset
    consumed = 0
    stopped = False
    ts_min = ts_max = -1

    def _fold_ts(ts):
        nonlocal ts_min, ts_max
        if ts_min < 0 or ts < ts_min:
            ts_min = ts
        if ts > ts_max:
            ts_max = ts

    for _pos, end, off, key, value, _ts, _hdrs in seg.scan_records(buf):
        if len(rows_num) >= cap_rows:
            stopped = True
            break
        if off >= start_offset and value is None:
            # tombstone: no payload to decode, consumed + counted — and
            # it still advances the event-time watermark
            skipped += 1
            next_offset = off + 1
            consumed = end
            _fold_ts(_ts)
            continue
        if off < start_offset:
            consumed = end  # sparse-index alignment: skip, still consumed
            continue
        payload = value
        if pinned_id_limit >= 0:
            if len(value) < 5 or value[0] != MAGIC or \
                    int.from_bytes(value[1:5], "big") >= pinned_id_limit:
                flags |= FRAMES_STOP_SCHEMA
                stopped = True
                break
            payload = value[5:]
        try:
            rec = codec.decode(payload)
        except Exception:
            flags |= FRAMES_STOP_TORN  # malformed Avro in a valid frame
            stopped = True
            break
        rows_num.append([
            np.float32(0.0 if rec[n] is None else rec[n])
            for n in numerics])
        rows_lab.append(["" if rec[s] is None else rec[s]
                         for s in strings])
        if with_keys:
            rows_key.append((key or b"")[:key_stride - 1])
        next_offset = off + 1
        consumed = end
        _fold_ts(_ts)
    if not stopped and consumed < len(buf):
        flags |= FRAMES_STOP_TORN  # scan parked on a torn/corrupt frame
    n = len(rows_num)
    numeric = np.zeros((n, len(numerics)), np.float32)
    labels = np.zeros((n, len(strings)), f"S{label_stride}")
    for i in range(n):
        numeric[i] = rows_num[i]
        labels[i] = [s.encode()[:label_stride - 1]
                     for s in rows_lab[i]]
    keys = None
    if with_keys:
        keys = np.asarray(rows_key, f"S{key_stride}") if rows_key \
            else np.zeros((0,), f"S{key_stride}")
    out = (numeric, labels, keys, next_offset, flags, skipped)
    return out + (ts_min, ts_max) if want_ts else out
