"""Confluent Schema-Registry wire framing + the raw frame-batch contract.

Records on the Kafka topic the ML layer consumes are not bare Avro: the
Schema Registry serializer prepends a 5-byte header — magic byte ``0`` plus
a big-endian uint32 schema id.  The reference strips it in-graph with
``tf.strings.substr(e, 5, -1)`` (cardata-v3.py:50).  We keep the format
byte-compatible so our stream engine interoperates with real Confluent
payloads.

This module is also the stream layer's half of the ONE frame contract
(lint R14): the segmented log's CRC32C frame layout
``[len|crc|attrs|offset|ts|key|value|headers]`` (store/segment.py) is
the wire→disk→host batch format — ``Broker.fetch_raw``, the wire's
RAW_FETCH and the replay API all hand back `RawFrameBatch` views of it,
and the only parsers are ``store.segment`` and the helpers here (which
delegate to it).  The C++ twin is ``cpp/frame_engine.cc``; the pure
functions below are its byte-parity oracle and the no-toolchain
fallback.
"""

from __future__ import annotations

import struct
from typing import List, NamedTuple, Optional, Tuple

MAGIC = 0
SCHEMA_ID_DEFAULT = 1
_HDR = struct.Struct(">bI")


def frame(payload: bytes, schema_id: int = SCHEMA_ID_DEFAULT) -> bytes:
    """Prepend the Confluent 5-byte header."""
    return _HDR.pack(MAGIC, schema_id) + payload


def unframe(message: bytes) -> tuple:
    """Split a framed message into (schema_id, payload).

    Raises ValueError on a non-Confluent magic byte — callers that want the
    reference's permissive substr(5) behavior should use ``strip_frame``.
    """
    magic, schema_id = _HDR.unpack_from(message)
    if magic != MAGIC:
        raise ValueError(f"bad Confluent magic byte: {magic}")
    return schema_id, message[5:]


def strip_frame(message: bytes) -> bytes:
    """Reference-equivalent framing strip: drop the first 5 bytes blindly."""
    return message[5:]


# ------------------------------------------------------ raw frame batches
class RawFrameBatch(NamedTuple):
    """A batch of records as CONTIGUOUS store-format frame bytes.

    The zero-copy carrier between fetch and decode: no per-record Python
    objects, just one buffer + the cursor it was read at.  ``data`` may
    begin with frames below ``start_offset`` (sparse-index alignment —
    the decoder skips them) and may end mid-frame (a torn tail ends the
    batch, exactly like crash recovery); the decoder reports the true
    row count and next cursor."""

    topic: str
    partition: int
    start_offset: int   # the requested cursor; frames below are skipped
    data: bytes         # store-format frames (segment.py layout)


#: stop-flag bits shared with the native decoder (frame_engine.cc)
FRAMES_STOP_TORN = 1
FRAMES_STOP_SCHEMA = 2


def encode_frame_batch(entries) -> bytes:
    """[(offset, key, value, timestamp_ms, headers)] → contiguous frame
    bytes — how the IN-MEMORY broker (and the chaos fixtures) express a
    batch in the store's frame format.  Delegates to the store's frame
    codec: one encoder, one layout (lint R14)."""
    from ..store import segment as seg

    return b"".join(
        seg.encode_record(off, key, value, ts, headers)
        for off, key, value, ts, headers in entries)


def decode_frames_columnar_py(
        buf: bytes, start_offset: int, schema,
        pinned_id_limit: Optional[int] = None,
        cap_rows: int = 1 << 62, label_stride: int = 16,
        key_stride: int = 64, with_keys: bool = False
) -> Tuple["np.ndarray", "np.ndarray", Optional["np.ndarray"],
           int, int, int]:
    """Pure-Python twin of ``cpp/frame_engine.cc``'s columnar decoder —
    the byte-parity ORACLE (tests) and the no-toolchain fallback.

    Walks store frames in ``buf`` via ``store.segment.scan_records`` (the
    one parser), applies the same stop conditions (torn/corrupt frame,
    Confluent schema-id mismatch, cap) and fills float32 numeric /
    fixed-stride label / key columns.  Returns
    ``(numeric [n,F] float32, labels [n,S] S-stride, keys|None,
    next_offset, flags, skipped_tombstones)``.
    """
    import numpy as np

    from ..ops.avro import AvroCodec
    from ..store import segment as seg

    if pinned_id_limit is None:
        from ..stream.registry import RESERVED_ID_BASE

        pinned_id_limit = RESERVED_ID_BASE
    codec = AvroCodec(schema)
    strings = [f.name for f in schema.fields if f.avro_type == "string"]
    numerics = [f.name for f in schema.fields if f.avro_type != "string"]
    rows_num: List[list] = []
    rows_lab: List[list] = []
    rows_key: List[bytes] = []
    flags = 0
    skipped = 0
    next_offset = start_offset
    consumed = 0
    stopped = False
    for _pos, end, off, key, value, _ts, _hdrs in seg.scan_records(buf):
        if len(rows_num) >= cap_rows:
            stopped = True
            break
        if off >= start_offset and value is None:
            # tombstone: no payload to decode, consumed + counted
            skipped += 1
            next_offset = off + 1
            consumed = end
            continue
        if off < start_offset:
            consumed = end  # sparse-index alignment: skip, still consumed
            continue
        payload = value
        if pinned_id_limit >= 0:
            if len(value) < 5 or value[0] != MAGIC or \
                    int.from_bytes(value[1:5], "big") >= pinned_id_limit:
                flags |= FRAMES_STOP_SCHEMA
                stopped = True
                break
            payload = value[5:]
        try:
            rec = codec.decode(payload)
        except Exception:
            flags |= FRAMES_STOP_TORN  # malformed Avro in a valid frame
            stopped = True
            break
        rows_num.append([
            np.float32(0.0 if rec[n] is None else rec[n])
            for n in numerics])
        rows_lab.append(["" if rec[s] is None else rec[s]
                         for s in strings])
        if with_keys:
            rows_key.append((key or b"")[:key_stride - 1])
        next_offset = off + 1
        consumed = end
    if not stopped and consumed < len(buf):
        flags |= FRAMES_STOP_TORN  # scan parked on a torn/corrupt frame
    n = len(rows_num)
    numeric = np.zeros((n, len(numerics)), np.float32)
    labels = np.zeros((n, len(strings)), f"S{label_stride}")
    for i in range(n):
        numeric[i] = rows_num[i]
        labels[i] = [s.encode()[:label_stride - 1]
                     for s in rows_lab[i]]
    keys = None
    if with_keys:
        keys = np.asarray(rows_key, f"S{key_stride}") if rows_key \
            else np.zeros((0,), f"S{key_stride}")
    return numeric, labels, keys, next_offset, flags, skipped
