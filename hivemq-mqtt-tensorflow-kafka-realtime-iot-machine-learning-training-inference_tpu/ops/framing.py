"""Confluent Schema-Registry wire framing.

Records on the Kafka topic the ML layer consumes are not bare Avro: the
Schema Registry serializer prepends a 5-byte header — magic byte ``0`` plus
a big-endian uint32 schema id.  The reference strips it in-graph with
``tf.strings.substr(e, 5, -1)`` (cardata-v3.py:50).  We keep the format
byte-compatible so our stream engine interoperates with real Confluent
payloads.
"""

from __future__ import annotations

import struct

MAGIC = 0
SCHEMA_ID_DEFAULT = 1
_HDR = struct.Struct(">bI")


def frame(payload: bytes, schema_id: int = SCHEMA_ID_DEFAULT) -> bytes:
    """Prepend the Confluent 5-byte header."""
    return _HDR.pack(MAGIC, schema_id) + payload


def unframe(message: bytes) -> tuple:
    """Split a framed message into (schema_id, payload).

    Raises ValueError on a non-Confluent magic byte — callers that want the
    reference's permissive substr(5) behavior should use ``strip_frame``.
    """
    magic, schema_id = _HDR.unpack_from(message)
    if magic != MAGIC:
        raise ValueError(f"bad Confluent magic byte: {magic}")
    return schema_id, message[5:]


def strip_frame(message: bytes) -> bytes:
    """Reference-equivalent framing strip: drop the first 5 bytes blindly."""
    return message[5:]
