"""Whole-training-run-in-one-kernel: Pallas fused autoencoder fit.

The reference's training job is thousands of *tiny* SGD steps — batch 100
over an 18-wide MLP (`cardata-v3.py:176-194,218`) is microseconds of MXU
work per step.  Even with the whole fit scanned into one XLA program
(`train.loop.make_scanned_fit`), each scan iteration still dispatches ~25
separate fused kernels (forward, backward, per-tensor Adam), and at ~30µs
of TPU loop overhead per kernel the job is overhead-bound, not FLOP-bound.

This module collapses the *entire fit* — every epoch, every batch: forward,
hand-derived backward, and Adam for all eight parameter tensors — into ONE
Pallas kernel.  Data (up to a few MB) and parameters live in VMEM for the
whole run; the only HBM traffic is the initial load and the final
parameter/metric write-back.  Numerics match `make_scanned_fit` (same ops,
same order, float32) to float tolerance.

Exact math replicated (see `train.loop` / `models.autoencoder`):

  h1 = tanh(x W1 + b1);  penalty = l1 * sum|h1| / B      (Keras activity reg)
  h2 = relu(h1 W2 + b2); h3 = tanh(h2 W3 + b3); out = relu(h3 W4 + b4)
  loss = sum((out-x)^2 * m) / max(sum(m)*F, 1) + penalty  (masked MSE)
  acc  = sum((out==x) * m) / max(sum(m)*F, 1)             (Keras 'accuracy')
  Adam: optax defaults b1=.9 b2=.999 eps=1e-8, bias correction at t=step+1

Supports any DenseAutoencoder geometry (18- and 30-dim variants).  Falls
back transparently to interpret mode off-TPU, so CPU tests run the same
kernel.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: parameter layout: (layer name, activation) in forward order
_LAYERS = ("encoder0", "encoder1", "decoder0", "decoder1")

#: The kernel maps the whole training slice into VMEM (no grid/BlockSpecs),
#: so callers must gate on data size: beyond this budget, use the scanned
#: fit, which streams batches from HBM.  ~16 MB VMEM per v5e core, minus
#: params/moments/activations headroom.
VMEM_DATA_BUDGET_BYTES = 8 * 2 ** 20


def _flatten_params(params) -> list:
    """params tree → [W1, b1, W2, b2, W3, b3, W4, b4] (forward order)."""
    out = []
    for name in _LAYERS:
        out.append(params[name]["kernel"])
        out.append(params[name]["bias"])
    return out


def _unflatten_params(flat) -> dict:
    return {name: {"kernel": flat[2 * i], "bias": flat[2 * i + 1]}
            for i, name in enumerate(_LAYERS)}


def _fit_kernel(xs_ref, mask_ref, t0_ref, *refs, n_tensors: int,
                steps_per_epoch: int, total_steps: int, lr: float, l1: float,
                b1: float, b2: float, eps: float):
    """One kernel = the whole fit.  refs layout:
    [p_in ×8, m_in ×8, v_in ×8, p_out ×8, m_out ×8, v_out ×8, losses, accs].
    State lives in the *output* refs (copied from inputs up front), so the
    fori_loop reads and writes VMEM only."""
    n3 = 3 * n_tensors
    ins, outs = refs[:n3], refs[n3:2 * n3]
    losses_ref, accs_ref = refs[2 * n3], refs[2 * n3 + 1]
    for i in range(n3):
        outs[i][...] = ins[i][...]
    p, m, v = outs[:n_tensors], outs[n_tensors:2 * n_tensors], \
        outs[2 * n_tensors:3 * n_tensors]

    batch = xs_ref.shape[1]
    feat = xs_ref.shape[2]
    n_epochs = total_steps // steps_per_epoch
    # Mosaic cannot prove alignment for scalar stores at a dynamic index,
    # so metrics accumulate into small loop-carried per-epoch vectors via a
    # one-hot mask (pure vector ops) and are stored once after the loop.
    # 2D iota: 1D iota is not lowerable on TPU.
    epoch_ids = jax.lax.broadcasted_iota(
        jnp.int32, (n_epochs, 1), 0).reshape(n_epochs)

    def body(i, carry):
        loss_acc, acc_acc = carry
        s = jax.lax.rem(i, steps_per_epoch)
        x = xs_ref[pl.ds(s, 1)].reshape(batch, feat)
        msk = mask_ref[pl.ds(s, 1)].reshape(batch, 1)

        w1, bi1 = p[0][...], p[1][...]
        w2, bi2 = p[2][...], p[3][...]
        w3, bi3 = p[4][...], p[5][...]
        w4, bi4 = p[6][...], p[7][...]

        # ---- forward (same op order as the flax model)
        dot = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
        z1 = dot(x, w1) + bi1
        h1 = jnp.tanh(z1)
        z2 = dot(h1, w2) + bi2
        h2 = jnp.maximum(z2, 0.0)
        z3 = dot(h2, w3) + bi3
        h3 = jnp.tanh(z3)
        z4 = dot(h3, w4) + bi4
        out = jnp.maximum(z4, 0.0)

        denom = jnp.maximum(jnp.sum(msk) * feat, 1.0)
        # mask enters the loss LINEARLY (a per-row sample weight), matching
        # train.loop._masked_mse — for 0/1 masks this is indistinguishable
        # from masking diff, but fractional weights must not get squared
        diff = out - x
        penalty = l1 * jnp.sum(jnp.abs(h1)) / batch
        loss = jnp.sum(diff * diff * msk) / denom + penalty
        acc = jnp.sum((out == x).astype(jnp.float32) * msk) / denom

        # ---- backward (hand-derived; matches jax.grad of the above)
        dz4 = (2.0 / denom) * diff * msk * (z4 > 0.0)
        dW4 = dot(h3.T, dz4)
        db4 = jnp.sum(dz4, axis=0)
        dh3 = dot(dz4, w4.T)
        dz3 = dh3 * (1.0 - h3 * h3)
        dW3 = dot(h2.T, dz3)
        db3 = jnp.sum(dz3, axis=0)
        dh2 = dot(dz3, w3.T)
        dz2 = dh2 * (z2 > 0.0)
        dW2 = dot(h1.T, dz2)
        db2 = jnp.sum(dz2, axis=0)
        dh1 = dot(dz2, w2.T) + (l1 / batch) * jnp.sign(h1)
        dz1 = dh1 * (1.0 - h1 * h1)
        dW1 = dot(x.T, dz1)
        db1 = jnp.sum(dz1, axis=0)

        grads = (dW1, db1, dW2, db2, dW3, db3, dW4, db4)

        # ---- Adam, optax bias-correction at t = global step + 1.
        # b^t as exp(t·ln b): Mosaic has no powf lowering, exp it has.
        t = (t0_ref[0] + i + 1).astype(jnp.float32)
        c1 = 1.0 - jnp.exp(t * math.log(b1))
        c2 = 1.0 - jnp.exp(t * math.log(b2))
        for k in range(n_tensors):
            g = grads[k]
            mk = b1 * m[k][...] + (1.0 - b1) * g
            vk = b2 * v[k][...] + (1.0 - b2) * g * g
            m[k][...] = mk
            v[k][...] = vk
            p[k][...] = p[k][...] - lr * (mk / c1) / (jnp.sqrt(vk / c2) + eps)

        onehot = (epoch_ids == (i // steps_per_epoch)).astype(jnp.float32)
        return loss_acc + loss * onehot, acc_acc + acc * onehot

    zeros = jnp.zeros((n_epochs,), jnp.float32)
    losses, accs = jax.lax.fori_loop(0, total_steps, body, (zeros, zeros))
    inv = jnp.float32(1.0 / steps_per_epoch)
    losses_ref[...] = losses * inv  # per-epoch mean, like make_scanned_fit
    accs_ref[...] = accs * inv


@functools.partial(jax.jit, static_argnames=("epochs", "lr", "l1", "b1",
                                             "b2", "eps", "interpret"))
def _fused_fit(flat_p, flat_m, flat_v, t0, xs, masks, epochs: int,
               lr: float, l1: float, b1: float, b2: float, eps: float,
               interpret: bool):
    steps_per_epoch = xs.shape[0]
    total = epochs * steps_per_epoch
    n_tensors = len(flat_p)
    out_shape = (
        [jax.ShapeDtypeStruct(a.shape, a.dtype)
         for a in (*flat_p, *flat_m, *flat_v)]
        + [jax.ShapeDtypeStruct((epochs,), jnp.float32),
           jax.ShapeDtypeStruct((epochs,), jnp.float32)]
    )
    kernel = functools.partial(
        _fit_kernel, n_tensors=n_tensors, steps_per_epoch=steps_per_epoch,
        total_steps=total, lr=lr, l1=l1, b1=b1, b2=b2, eps=eps)
    t0_arr = jnp.asarray(t0, jnp.int32).reshape(1)
    res = pl.pallas_call(kernel, out_shape=out_shape, interpret=interpret)(
        xs, masks, t0_arr, *flat_p, *flat_m, *flat_v)
    n3 = 3 * n_tensors
    return res[:n3], res[n3], res[n3 + 1]


def supported(state, supervised: bool) -> bool:
    """Is this TrainState the fused kernel's exact contract? (4-layer
    DenseAutoencoder params + optax.adam state, unsupervised)."""
    if supervised:
        return False
    try:
        params = state.params
        if set(params.keys()) != set(_LAYERS):
            return False
        adam_state = state.opt_state[0]
        _ = adam_state.mu, adam_state.nu, adam_state.count
    except (AttributeError, TypeError, IndexError, KeyError):
        return False
    return True


def fused_fit(state, xs, masks, epochs: int, lr: float = 1e-3,
              l1: float = 1e-7, interpret: bool = None
              ) -> Tuple[object, jnp.ndarray, jnp.ndarray]:
    """Run the whole fit in one Pallas kernel.

    state: TrainState (DenseAutoencoder params + optax.adam opt_state)
    xs: [S, B, F] float32 batches; masks: [S, B] float32
    Returns (new_state, losses [epochs], accs [epochs]) — per-epoch means,
    the same history `make_scanned_fit` reports.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    adam_state = state.opt_state[0]
    flat_p = _flatten_params(state.params)
    flat_m = _flatten_params(adam_state.mu)
    flat_v = _flatten_params(adam_state.nu)
    t0 = adam_state.count

    xs = jnp.asarray(xs, jnp.float32)
    masks = jnp.asarray(masks, jnp.float32)
    out_flat, losses, accs = _fused_fit(
        flat_p, flat_m, flat_v, t0, xs, masks, epochs=int(epochs),
        lr=float(lr), l1=float(l1), b1=0.9, b2=0.999, eps=1e-8,
        interpret=bool(interpret))
    n = len(flat_p)
    total = epochs * xs.shape[0]
    new_params = _unflatten_params(out_flat[:n])
    new_mu = _unflatten_params(out_flat[n:2 * n])
    new_nu = _unflatten_params(out_flat[2 * n:3 * n])
    new_adam = adam_state._replace(count=t0 + total,
                                   mu=new_mu, nu=new_nu)
    new_opt_state = (new_adam,) + tuple(state.opt_state[1:])
    new_state = state.replace(step=state.step + total,
                              params=new_params,
                              opt_state=new_opt_state)
    return new_state, losses, accs
