from .mesh import make_mesh, auto_mesh, batch_sharding, replicated  # noqa: F401
from .data_parallel import ShardedTrainer, shard_params, param_specs, make_sharded_eval_step  # noqa: F401
