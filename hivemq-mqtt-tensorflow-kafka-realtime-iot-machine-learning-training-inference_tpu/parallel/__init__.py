from .mesh import make_mesh, auto_mesh, batch_sharding, replicated  # noqa: F401
from .data_parallel import ShardedTrainer, shard_params, param_specs, make_sharded_eval_step  # noqa: F401
from .ring_attention import make_ring_attention, ring_attention  # noqa: F401
from .seq_parallel import make_sp_train_step  # noqa: F401
from . import distributed  # noqa: F401
from .pipeline import make_pp_train_step, pipeline_apply, pipeline_schedule  # noqa: F401
from .expert_parallel import make_ep_train_step, shard_expert_params  # noqa: F401
