"""Sharded training/eval steps over a device mesh (pjit-style).

Data parallelism is the strategy the workload requires (SURVEY §2.7): the
batch's leading dim shards over the mesh 'data' axis, parameters replicate
(or shard over 'model' for tensor parallelism), and XLA inserts the gradient
all-reduce over ICI — no hand-written collectives, by design.

Partition → shard assignment: a 10-partition topic consumed by a host feeds
batches whose rows interleave partitions; sharding the batch dim maps those
rows onto chips, which is exactly the reference's Kafka-partition/consumer-
group parallelism moved on-device.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..train.loop import TrainState, make_raw_train_step
from .mesh import batch_sharding, replicated


def param_specs(params, mesh: Mesh, model_axis: Optional[str] = "model"):
    """PartitionSpecs for a param tree: Dense kernels shard their output dim
    over the model axis when it divides evenly (tensor-parallel hook);
    everything else replicates.  With model axis size 1 this is pure DP."""
    axis_size = mesh.shape.get(model_axis, 1) if model_axis else 1

    def spec(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if (axis_size > 1 and name == "kernel" and leaf.ndim == 2
                and leaf.shape[1] % axis_size == 0):
            return P(None, model_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def put_global(x, sharding: NamedSharding):
    """Host array → (possibly multi-process) global device array.

    Single-process: plain device_put.  Multi-host (jax.distributed
    initialized): `x` is THIS PROCESS's share — the rows its consumers
    pulled from its assigned partitions — and the global array is
    assembled from every process's share (replicated specs take the full
    array from each host).  This is the host-local → global boundary of
    the whole multi-host design: data stays host-local on the DCN side,
    the mesh sees one logical array on the ICI side."""
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sharding, x)
    return jax.device_put(x, sharding)


def shard_params(params, mesh: Mesh, model_axis: Optional[str] = "model"):
    specs = param_specs(params, mesh, model_axis)
    return jax.tree.map(
        lambda x, s: put_global(x, NamedSharding(mesh, s)), params, specs)


def make_device_normalized_step(raw, normalizer):
    """Fold the affine normalization map INTO the step (ISSUE 15).

    The host pipeline ships RAW float32 columns (the batcher runs
    ``core.normalize.RAW_COLUMNS``) and the map — one fused
    ``(x * scale + shift) * mask`` XLA folds into whatever consumes it —
    runs on each device over its own shard.  The constants come from the
    SAME ``Normalizer`` the host path would use, so the two modes agree
    to float32 rounding (~1 ulp: the host twin rounds once from float64,
    the device computes in float32 — pinned by test).  Unsupervised
    streams pass y=x, so the target is normalized identically."""
    import numpy as np

    scale = np.asarray(normalizer.scale, np.float32)
    shift = np.asarray(normalizer.shift, np.float32)
    maskv = np.asarray(normalizer.mask, np.float32)

    def step(state, x, y, mask):
        xn = (x * scale + shift) * maskv
        yn = (y * scale + shift) * maskv
        return raw(state, xn, yn, mask)

    return step


class ShardedTrainer:
    """Mesh-parallel twin of `train.Trainer`: same step math, jitted with
    explicit in/out shardings so batches land sharded and the gradient
    all-reduce is compiled over the mesh.

    ``normalizer=`` folds the affine normalization onto the device (the
    host ships raw columns — see `make_device_normalized_step`);
    ``row_loss=True`` keeps the per-row pre-update loss sharded over
    'data' in the metrics (the per-chip drift signal)."""

    def __init__(self, model, mesh: Mesh, rng=None, learning_rate: float = 1e-3,
                 supervised: bool = False, tx=None, model_axis: str = "model",
                 normalizer=None, row_loss: bool = False):
        import optax

        self.model = model
        self.mesh = mesh
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.tx = tx or optax.adam(learning_rate)
        self.supervised = supervised
        self.model_axis = model_axis
        self.normalizer = normalizer
        self.row_loss = row_loss
        self.state: Optional[TrainState] = None
        self._step = None
        self._data_sharding = batch_sharding(mesh)
        # multi-host: the ONE batch shape all processes agreed on (fixed
        # at the first step; see put_batch)
        self._agreed_shape = None

    @property
    def data_sharding(self) -> NamedSharding:
        return self._data_sharding

    def init(self, sample_x, from_state: Optional[TrainState] = None):
        """Build (or adopt — warm start) the state and compile the step.

        ``from_state`` shards an existing HOST TrainState instead of a
        fresh init: the registry warm-start path (`mlops.restore_trainer`
        fills a host state, the mesh adopts it)."""
        state = from_state if from_state is not None else \
            TrainState.create(self.model, self.rng, sample_x, tx=self.tx)
        pspecs = param_specs(state.params, self.mesh, self.model_axis)
        params = shard_params(state.params, self.mesh, self.model_axis)
        opt_state = jax.tree.map(
            lambda a: put_global(a, replicated(self.mesh)), state.opt_state)
        self.state = state.replace(params=params, opt_state=opt_state)

        raw = make_raw_train_step(self.model, self.tx, self.supervised,
                                  row_loss=self.row_loss)
        if self.normalizer is not None:
            raw = make_device_normalized_step(raw, self.normalizer)
        state_shardings = TrainState(
            step=replicated(self.mesh),
            params=jax.tree.map(lambda s: NamedSharding(self.mesh, s), pspecs),
            opt_state=jax.tree.map(lambda _: replicated(self.mesh),
                                   self.state.opt_state),
            apply_fn=self.model.apply, tx=self.tx)
        metric_shardings = {"loss": replicated(self.mesh),
                            "accuracy": replicated(self.mesh)}
        if self.row_loss:
            # each device's rows stay on their chip: no collective, and
            # the host reads per-chip means from addressable shards
            metric_shardings["row_loss"] = self._data_sharding
        self._step = jax.jit(
            raw,
            in_shardings=(state_shardings, self._data_sharding,
                          self._data_sharding, self._data_sharding),
            out_shardings=(state_shardings, metric_shardings),
            donate_argnums=(0,))
        return self.state

    def put_batch(self, x, y, mask):
        """Host batch → sharded device arrays (rows split over 'data').

        Rows are zero-padded up to a multiple of the data-axis share this
        process carries (the masked loss already ignores padding), so any
        batch size works on any mesh — e.g. the reference's batch 100 on an
        8-chip slice.  Multi-host: `x` is this host's rows (from its
        assigned partitions); the global batch is their concatenation."""
        import numpy as np

        # pad to this process's share of the data axis: with P processes
        # each contributing rows, the global row count splits over the full
        # axis only when every local count is a multiple of axis/P
        d = max(1, self.mesh.shape["data"] // jax.process_count())
        b = x.shape[0]
        if b % d:
            pad = d - b % d
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
            y = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
            mask = np.concatenate([mask, np.zeros((pad,), mask.dtype)])
        if jax.process_count() > 1:
            # every process must present the same local shape or
            # make_array_from_process_local_data assembles DIFFERENT global
            # shapes per process and the compiled step hangs in its first
            # cross-host collective.  The agreement collective runs exactly
            # ONCE — at the first step, which every process reaches
            # together — and fixes the shape for the run; later deviations
            # (a ragged tail one host hit) fail LOCALLY with a clear error
            # instead of desynchronizing a per-shape collective.
            if self._agreed_shape is None:
                from jax.experimental import multihost_utils

                shapes = multihost_utils.process_allgather(
                    np.asarray(x.shape, np.int64))
                if not (shapes == shapes[0]).all():
                    raise ValueError(
                        f"multi-host batch shape mismatch across "
                        f"processes: {shapes.tolist()} — every host must "
                        f"feed identical local batch shapes")
                self._agreed_shape = x.shape
            elif x.shape != self._agreed_shape:
                raise ValueError(
                    f"multi-host batch shape changed mid-run: "
                    f"{x.shape} != agreed {self._agreed_shape} — use "
                    f"fixed-size batches on every host (SensorBatches "
                    f"pad_tail=True, or pad_tail=False which drops ragged "
                    f"tails) and equal step counts")
        put = lambda a: put_global(a, self._data_sharding)  # noqa: E731
        return put(x), put(y), put(mask)

    def step(self, x, y, mask):
        if self.state is None:
            self.init(x)
        xd, yd, md = self.put_batch(x, y, mask)
        self.state, metrics = self._step(self.state, xd, yd, md)
        return metrics

    def fit(self, batches, epochs: int = 1,
            prefetch_depth: Optional[int] = None) -> dict:
        """Epochs × steps with host↔device overlap.

        Two things keep the chips fed (SURVEY §7 hard part (b) — host decode
        must hide under the device step):
        - a `DevicePrefetcher` stages the next batch's sharded `device_put`
          on a background thread while the current step executes, and
        - per-step losses stay on device (no blocking `float()` per step);
          the sync happens once per epoch.
        """
        import numpy as np

        from ..data.prefetch import DevicePrefetcher

        history = {"loss": [], "records": [], "seconds": []}
        import time as _t

        def to_device(b):
            y = b.y if b.y is not None else b.x
            return self.put_batch(b.x, y, b.mask), b

        epoch_iter = batches.epochs(epochs) if hasattr(batches, "epochs") \
            else (iter(batches) for _ in range(epochs))
        import itertools

        for it in epoch_iter:
            t0 = _t.perf_counter()
            it = iter(it)
            if self.state is None:
                # init on the main thread (param sharding + jit build must
                # not ride the prefetch worker); peek the first batch for
                # shapes and chain it back
                first = next(it, None)
                if first is None:
                    history["loss"].append(float("nan"))
                    history["records"].append(0)
                    history["seconds"].append(_t.perf_counter() - t0)
                    continue
                self.init(first.x)
                it = itertools.chain([first], it)
            losses, records = [], 0
            with DevicePrefetcher(it, to_device=to_device,
                                  depth=prefetch_depth) as pf:
                for (xd, yd, md), b in pf:
                    self.state, m = self._step(self.state, xd, yd, md)
                    losses.append(m["loss"])  # device scalar: no step sync
                    records += b.n_valid
            losses = [float(v) for v in jax.device_get(losses)]
            history["loss"].append(float(np.mean(losses)) if losses
                                   else float("nan"))
            history["records"].append(records)
            history["seconds"].append(_t.perf_counter() - t0)
        return history


def make_sharded_eval_step(model, mesh: Mesh, params_specs=None):
    """jit eval with batch sharded over 'data' (scale-out scoring)."""
    def ev(params, x):
        return model.apply({"params": params}, x)

    return jax.jit(ev, in_shardings=(None, batch_sharding(mesh)),
                   out_shardings=batch_sharding(mesh))
