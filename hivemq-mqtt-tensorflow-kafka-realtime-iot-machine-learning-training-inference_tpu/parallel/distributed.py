"""Multi-host initialization and partition→host assignment.

The reference's distributed backbone is Kafka over the datacenter network
plus Kubernetes as control plane — no NCCL/MPI (SURVEY §2.7).  The TPU
rebuild splits the two planes explicitly:

- DCN side: each host runs its own stream consumers for an assigned subset
  of topic partitions (`assign_partitions`), exactly the consumer-group
  model the reference used between pods;
- ICI side: `jax.distributed.initialize` joins the hosts into one JAX
  process group, and the mesh's collectives (gradient all-reduce etc.)
  compile over ICI within a pod slice, DCN across slices — XLA picks the
  transport per axis, we just lay shardings out so the heavy traffic stays
  on the 'data' axis inside the slice.
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Join the multi-host process group. No-op single-host (the common
    test/dev case), env-driven on TPU pods where the runtime injects
    topology (jax.distributed reads it natively).

    Env fallbacks (what `deploy/model-training-multihost.yaml` sets per
    indexed-Job pod): JAX_COORDINATOR, JAX_NUM_PROCESSES, JAX_PROCESS_ID.
    """
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if num_processes in (None, 1) and not coordinator and \
            "JAX_COORDINATOR" not in os.environ:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator or os.environ.get("JAX_COORDINATOR"),
        num_processes=num_processes,
        process_id=process_id)
    return True


def assign_partitions(n_partitions: int, n_hosts: int, host_id: int) -> List[int]:
    """Static partition→host assignment (round-robin), the multi-host analogue
    of the reference's Kafka consumer-group balancing — but deterministic, so
    offset checkpoints stay host-stable across restarts."""
    if not 0 <= host_id < n_hosts:
        raise ValueError(f"host_id {host_id} out of range 0..{n_hosts - 1}")
    return [p for p in range(n_partitions) if p % n_hosts == host_id]


def consumer_specs(topic: str, partitions: List[int], offset: int = 0) -> List[str]:
    """Subscription specs for this host's partitions (reference spec format)."""
    return [f"{topic}:{p}:{offset}" for p in partitions]
