"""Expert parallelism: MoE expert weights sharded over an 'expert' mesh axis.

Tokens shard over the batch (data × expert product), expert FFN weights
shard over 'expert', and two `lax.all_to_all`s inside the MoE layer
(`models/moe.py`) exchange token slots expert-major and back over ICI —
the GShard dispatch pattern, compiled by XLA.

Absent from the reference (SURVEY §2.7: EP "Absent — N/A"); provided here
because a fleet-scale SensorFormer is the natural place experts pay off and
the mesh/axis design must reserve the axis from day one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import shard_map

from ..train.loop import TrainState

EXPERT_LEAVES = ("w1", "b1", "w2", "b2")


def expert_param_specs(params, ep_axis: str = "expert"):
    """Spec tree: MoE expert weights shard their leading [E] dim over the
    expert axis; router and everything else replicate."""
    def spec(path, leaf):
        names = [p.key if hasattr(p, "key") else str(p) for p in path]
        if "moe" in names and names[-1] in EXPERT_LEAVES:
            return P(ep_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def shard_expert_params(params, mesh: Mesh, ep_axis: str = "expert"):
    specs = expert_param_specs(params, ep_axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def make_ep_train_step(model, tx, mesh: Mesh, data_axis: str = "data",
                       ep_axis: str = "expert", aux_weight: float = 0.01):
    """Build (init_fn, step_fn, put_x) for expert(+data)-parallel training
    of a MoESensorFormer on the next-step objective.

    Mesh is (data_axis, ep_axis). Batch rows shard over the *product* of
    both axes (every device works a token slice); expert weights shard over
    ep_axis; the model's internal all_to_alls ride the ep axis.
    """
    ep_model = model.clone(ep_axis=ep_axis)
    x_spec = P((data_axis, ep_axis))

    def local_loss(params, x_local):
        pred, aux = ep_model.apply({"params": params}, x_local)
        se = jnp.sum(jnp.square(pred[:, :-1] - x_local[:, 1:]))
        cnt = jnp.float32(pred[:, :-1].size)
        se_tot = jax.lax.psum(se, (data_axis, ep_axis))
        cnt_tot = jax.lax.psum(cnt, (data_axis, ep_axis))
        n_shards = jax.lax.psum(1, (data_axis, ep_axis))
        aux_mean = jax.lax.psum(aux, (data_axis, ep_axis)) / n_shards
        mse = se_tot / cnt_tot
        return mse + aux_weight * aux_mean, mse

    def init(rng, sample_x):
        dense = model.clone(ep_axis=None)
        raw = dense.init(rng, jnp.asarray(sample_x))["params"]
        params = shard_expert_params(raw, mesh, ep_axis)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           opt_state=tx.init(params),
                           apply_fn=model.apply, tx=tx)
        return state

    def build_loss(params):
        specs = expert_param_specs(params, ep_axis)
        return shard_map(
            local_loss, mesh=mesh,
            in_specs=(specs, x_spec), out_specs=(P(), P()),
            check_vma=False)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, x):
        loss_fn = build_loss(state.params)
        (loss, mse), grads = jax.value_and_grad(
            lambda p: loss_fn(p, x), has_aux=True)(state.params)
        updates, opt_state = state.tx.update(grads, state.opt_state,
                                             state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state), {"loss": loss, "mse": mse}

    def put_x(x):
        return jax.device_put(x, NamedSharding(mesh, x_spec))

    return init, step, put_x
