"""Device mesh construction.

The reference's parallelism is systems-level — Kafka partitions × consumer
groups, scalable predict Deployments (SURVEY §2.7) — with single-process
training.  The TPU rebuild makes tensor-level parallelism first-class: one
`jax.sharding.Mesh` whose `data` axis carries the Kafka-partition →
device-shard assignment (gradient all-reduce rides ICI) and whose `model`
axis is the tensor-parallel hook for wider models.

`auto_mesh` gives a sane default on any device count; tests run it on the
8-virtual-CPU-device trick (conftest), the driver dry-runs it at arbitrary N.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(f, *, mesh: Mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None):
    """`jax.shard_map` across JAX versions.

    Newer JAX exposes `jax.shard_map(..., check_vma=...)`; 0.4.x has it
    at `jax.experimental.shard_map.shard_map(..., check_rep=...)` (same
    replication-checking knob under its old name).  Every sharded train
    step in this package routes through here so a JAX upgrade is a
    one-line change, not a five-module sweep."""
    kw = {}
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    if check_vma is not None:
        kw["check_rep"] = check_vma
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def make_mesh(shape: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = 1
    for s in shape:
        n *= s
    if n != len(devices):
        raise ValueError(f"mesh shape {tuple(shape)} needs {n} devices, "
                         f"have {len(devices)}")
    import numpy as np

    return Mesh(np.asarray(devices).reshape(shape), axis_names)


def auto_mesh(n_devices: Optional[int] = None, model_parallel: int = 1) -> Mesh:
    """('data', 'model') mesh over the first n devices; model axis optional."""
    devices = jax.devices()
    n = len(devices) if n_devices is None else n_devices
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    return make_mesh((n // model_parallel, model_parallel), ("data", "model"),
                     devices[:n])


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over 'data'; replicate the rest."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
