"""One host's role in a multi-host training rehearsal.

Run as `python -m iotml.parallel.multihost_worker <coordinator> <nprocs>
<pid> <servers> <topic> <n_partitions> [steps]` — what each pod of
`deploy/model-training-multihost.yaml` does, scaled down to a 2-process
CPU rehearsal (SURVEY §2.7: `jax.distributed` over DCN for the process
group; per-host stream consumers for the data plane):

1. join the process group via `parallel.distributed.initialize`;
2. consume ONLY this host's partition share (`assign_partitions`) from
   the Kafka wire server over TCP — the reference's consumer-group model
   with a deterministic assignment;
3. drive a `ShardedTrainer` whose mesh spans every process's devices —
   each host contributes its local rows, `put_global` assembles the
   global batch, and the compiled gradient all-reduce crosses processes;
4. assert the loss DECREASES and print a `MULTIHOST ... ok` line the
   spawner greps.

The spawner (tests/test_multihost.py, or dryrun_multichip — on by
default, IOTML_DRYRUN_MULTIHOST=0 opts out) must set JAX_PLATFORMS=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=<local devices> in the
child environment BEFORE this module imports jax.
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 6:
        print("usage: multihost_worker <coordinator> <nprocs> <pid> "
              "<servers> <topic> <n_partitions> [steps]")
        return 1
    coordinator, nprocs, pid, servers, topic, n_parts = argv[:6]
    nprocs, pid, n_parts = int(nprocs), int(pid), int(n_parts)
    steps = int(argv[6]) if len(argv) > 6 else 6

    import jax

    jax.config.update("jax_platforms", "cpu")

    from iotml.parallel.distributed import (assign_partitions, consumer_specs,
                                            initialize)

    assert initialize(coordinator, nprocs, pid), "initialize() was a no-op"
    assert jax.process_count() == nprocs, jax.process_count()

    import numpy as np

    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.parallel.data_parallel import ShardedTrainer
    from iotml.parallel.mesh import make_mesh
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.kafka_wire import KafkaWireBroker

    # the DCN data plane: this host consumes only its partition share
    parts = assign_partitions(n_parts, nprocs, pid)
    client = KafkaWireBroker(servers)
    consumer = StreamConsumer(client, consumer_specs(topic, parts),
                              group=f"multihost-{pid}")
    # pad_tail=False DROPS the ragged tail, so every batch is exactly
    # batch_size rows — the fixed local shape the multi-host put_batch
    # contract requires on every host
    batches = list(SensorBatches(consumer, batch_size=32, only_normal=True,
                                 pad_tail=False))
    assert batches, f"host {pid}: no data in partitions {parts}"

    # the ICI/collective plane: one mesh over every process's devices
    mesh = make_mesh((jax.device_count(),), ("data",),
                     devices=jax.devices())
    trainer = ShardedTrainer(CAR_AUTOENCODER, mesh)

    import time

    losses = []
    t0 = None
    rows = 0
    for i in range(steps):
        b = batches[i % len(batches)]
        if i == 1:
            # step 0 compiles: the timed window (per-leg records/sec,
            # ISSUE 15) covers warm steps only
            t0 = time.perf_counter()
            rows = 0
        m = trainer.step(b.x, b.x, b.mask)
        # the loss is replicated but not fully addressable from one
        # process: read the local replica
        losses.append(float(np.asarray(m["loss"].addressable_data(0))))
        rows += b.n_valid
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    dt = (time.perf_counter() - t0) if t0 is not None else 0.0
    rate = rows / dt if dt > 0 else 0.0
    print(f"MULTIHOST pid={pid}/{nprocs} devices={jax.device_count()} "
          f"partitions={parts} loss {losses[0]:.6f}->{losses[-1]:.6f} "
          f"rate={rate:.1f} rows={rows} ok", flush=True)
    return 0


def spawn_rehearsal(steps: int = 6, timeout: float = 420.0,
                    n_partitions: int = 4, n_procs: int = 2):
    """Spawn the n-process rehearsal and return (procs, outs).

    Shared by tests/test_multihost.py and __graft_entry__'s
    IOTML_DRYRUN_MULTIHOST leg so the two cannot drift: seeds a broker,
    serves it over the Kafka wire, scrubs the child env (no TPU-tunnel
    sitecustomize, no inherited pod topology), spawns the workers, and
    ALWAYS kills stragglers — a worker that dies early must not leave its
    peers pinned in the coordinator barrier."""
    import os
    import socket
    import subprocess

    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireServer

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    broker = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=40, failure_rate=0.02))
    gen.publish(broker, "SENSOR", n_ticks=60, partitions=n_partitions)

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()

    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "PYTHONPATH": repo})
    # no inherited pod topology; and the TPU-tunnel sitecustomize registers
    # its PJRT backend at interpreter start, which counts as XLA init and
    # breaks jax.distributed.initialize()
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_", "JAX_COORDINATOR",
                         "JAX_NUM_PROCESSES", "JAX_PROCESS_ID")):
            env.pop(k)

    with KafkaWireServer(broker) as srv:
        procs = [subprocess.Popen(
            [sys.executable, "-m", "iotml.parallel.multihost_worker",
             coord, str(n_procs), str(pid), f"127.0.0.1:{srv.port}",
             "SENSOR", str(n_partitions), str(steps)],
            env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
            for pid in range(n_procs)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    return procs, outs


if __name__ == "__main__":
    raise SystemExit(main())
