"""Pipeline parallelism: transformer blocks staged over a 'pipe' mesh axis.

The reference's only pipeline is systems-level — the train-pod → GCS →
predict-deployment handoff (SURVEY §2.7, reference `AUTOENCODER.../run.sh`).
The TPU rebuild makes in-model pipeline parallelism a first-class axis so
deep SensorFormer stacks can span chips whose HBM one stage's activations
would exhaust.

Design (GPipe-style, XLA-native):
- The layer stack is stored *stacked*: every block's params get a leading
  [num_layers] axis, sharded `P('pipe')`, so each device materializes only
  its own layers — this is the memory win.
- The schedule is a single `lax.scan` over M + S - 1 ticks inside
  `shard_map`.  Each tick every stage applies its blocks to its resident
  microbatch, then a `lax.ppermute` ring-shifts activations to the next
  stage over ICI.  Stage 0 injects microbatch t at tick t; the last stage
  banks its result.
- Backward is not hand-written: `jax.grad` transposes the scan and the
  ppermute (reverse ring) automatically, yielding the usual 1F1B-equivalent
  dataflow with microbatch gradient accumulation for free.
- Embed / final-norm / head are tiny; they run replicated on every stage and
  their cotangents are psum'd by the shard_map transpose, avoiding the
  heterogeneous first/last-stage params that make hand-rolled pipelines
  brittle.

Bubble fraction is (S-1)/(M+S-1) — pick n_microbatches >= 4*pipe for >80%
utilization; at demo scale the point is the compiled schedule, not the
bubble.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import shard_map

from ..train.loop import TrainState


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def pipeline_schedule(stage_fn: Callable, stage_params, mbs,
                      axis: str = "pipe"):
    """Run `stage_fn(stage_params, x)` as a pipeline over mesh axis `axis`.

    Call *inside* shard_map. `stage_params` is this device's local stage
    slice; `mbs` is [M, ...microbatch shape...], identical on every stage.
    Returns [M, ...] outputs, replicated across the axis (one psum).
    """
    n = jax.lax.psum(1, axis)  # static under shard_map
    idx = jax.lax.axis_index(axis)
    M = mbs.shape[0]
    ticks = M + n - 1

    def tick(carry, t):
        buf, outs = carry
        inj = jax.lax.dynamic_index_in_dim(
            mbs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        cur = jnp.where(idx == 0, inj, buf)
        out = stage_fn(stage_params, cur)
        w = t - (n - 1)  # microbatch the last stage finished this tick
        banked = jax.lax.dynamic_update_index_in_dim(
            outs, out, jnp.clip(w, 0, M - 1), axis=0)
        valid = (idx == n - 1) & (w >= 0)
        outs = jnp.where(valid, banked, outs)
        buf = jax.lax.ppermute(out, axis, _ring_perm(n))
        return (buf, outs), None

    carry0 = (jnp.zeros_like(mbs[0]), jnp.zeros_like(mbs))
    (_, outs), _ = jax.lax.scan(tick, carry0, jnp.arange(ticks))
    # only the last stage holds real outputs; psum replicates them ring-wide
    return jax.lax.psum(jnp.where(idx == n - 1, outs, 0.0), axis)


def pipeline_apply(stage_fn: Callable, mesh: Mesh, axis: str = "pipe"):
    """shard_map wrapper: (stacked_params, mbs) -> [M, ...] outputs.

    stacked_params leaves have leading dim = total layers, sharded over
    `axis`; every other mesh axis sees them replicated.  mbs is replicated.
    """
    def body(stacked_local, mbs):
        return pipeline_schedule(stage_fn, stacked_local, mbs, axis)

    return shard_map(body, mesh=mesh, in_specs=(P(axis), P()),
                         out_specs=P(), check_vma=False)


# ---------------------------------------------------------------------------
# SensorFormer pipeline-parallel training
# ---------------------------------------------------------------------------

def stack_blocks(params: dict, num_layers: int):
    """Split SensorFormer params into (static, blocks) where blocks leaves
    carry a leading [num_layers] stacking axis (shardable over 'pipe')."""
    static = {k: v for k, v in params.items() if not k.startswith("block")}
    blocks = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[params[f"block{i}"] for i in range(num_layers)])
    return static, blocks


def unstack_blocks(static: dict, blocks, num_layers: int) -> dict:
    params = dict(static)
    for i in range(num_layers):
        params[f"block{i}"] = jax.tree.map(lambda a, i=i: a[i], blocks)
    return params


def make_pp_train_step(model, tx, mesh: Mesh, n_microbatches: int,
                       data_axis: str = "data", pipe_axis: str = "pipe"):
    """Build (init_fn, step_fn, put_x) for pipeline(+data)-parallel training
    of a SensorFormer on the next-step objective.

    Mesh is (data_axis, pipe_axis): batch rows shard over data, the layer
    stack shards over pipe.  `model.num_layers` must divide by the pipe size
    and the per-data-shard batch by n_microbatches.

    state.params = {'static': embed/pos/ln_f/head (replicated),
                    'blocks': stacked [L, ...] leaves (sharded P(pipe))}.
    """
    import flax.linen as nn

    from ..models.transformer import Block

    n_pipe = mesh.shape[pipe_axis]
    L = model.num_layers
    if L % n_pipe:
        raise ValueError(f"num_layers={L} not divisible by pipe={n_pipe}")
    if model.attn_mode == "ring":
        # ring attention needs a 'seq' axis; each pipeline stage sees the
        # full sequence, so there is nothing to ring over
        raise ValueError("attn_mode='ring' cannot compose with pipeline "
                         "parallelism; use 'dense' or 'flash' (full T per "
                         "stage) or train via make_sp_train_step")
    per_stage = L // n_pipe
    block = Block(model.d_model, model.num_heads, attn_mode=model.attn_mode)
    embed = nn.Dense(model.d_model, name="embed")
    pos = nn.Embed(model.max_len, model.d_model, name="pos")
    ln_f = nn.LayerNorm(name="ln_f")
    head = nn.Dense(model.features, name="head")

    def stage_fn(blocks_local, h):
        # blocks_local leaves: [per_stage, ...] — this stage's layer slice
        for j in range(per_stage):
            p = jax.tree.map(lambda a, j=j: a[j], blocks_local)
            h = block.apply({"params": p}, h)
        return h

    def local_loss(static, blocks_local, x_local):
        Bl, T, F = x_local.shape
        h = embed.apply({"params": static["embed"]}, x_local)
        h = h + pos.apply({"params": static["pos"]}, jnp.arange(T))
        mbs = h.reshape(n_microbatches, Bl // n_microbatches, T, model.d_model)
        outs = pipeline_schedule(stage_fn, blocks_local, mbs, pipe_axis)
        h = outs.reshape(Bl, T, model.d_model)
        pred = head.apply({"params": static["head"]},
                          ln_f.apply({"params": static["ln_f"]}, h))
        # next-step MSE; count the loss only on the last pipe stage so the
        # replicated head/embed work on other stages contributes no gradient
        se = jnp.sum(jnp.square(pred[:, :-1] - x_local[:, 1:]))
        idx = jax.lax.axis_index(pipe_axis)
        n = jax.lax.psum(1, pipe_axis)
        se = jnp.where(idx == n - 1, se, 0.0)
        cnt = jnp.where(idx == n - 1, jnp.float32(pred[:, :-1].size), 0.0)
        se_tot = jax.lax.psum(se, (data_axis, pipe_axis))
        cnt_tot = jax.lax.psum(cnt, (data_axis, pipe_axis))
        return se_tot / cnt_tot

    x_spec = P(data_axis)
    loss_fn = shard_map(
        local_loss, mesh=mesh,
        in_specs=(P(), P(pipe_axis), x_spec), out_specs=P(),
        check_vma=False)

    blocks_sharding = NamedSharding(mesh, P(pipe_axis))
    rep = NamedSharding(mesh, P())

    def shard_pp_params(params):
        return {
            "static": jax.device_put(params["static"], rep),
            "blocks": jax.tree.map(
                lambda a: jax.device_put(a, blocks_sharding),
                params["blocks"]),
        }

    def init(rng, sample_x):
        dense = model.clone(attn_mode="dense")
        raw = dense.init(rng, jnp.asarray(sample_x))["params"]
        static, blocks = stack_blocks(raw, L)
        params = shard_pp_params({"static": static, "blocks": blocks})
        opt_state = tx.init(params)  # moments inherit the params' shardings
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state, apply_fn=model.apply, tx=tx)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, x):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p["static"], p["blocks"], x))(state.params)
        updates, opt_state = state.tx.update(grads, state.opt_state,
                                             state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state), {"loss": loss}

    def put_x(x):
        return jax.device_put(x, NamedSharding(mesh, x_spec))

    return init, step, put_x
