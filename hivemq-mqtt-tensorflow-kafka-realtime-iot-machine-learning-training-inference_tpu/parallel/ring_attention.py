"""Ring attention: sequence-parallel attention over a mesh axis.

Long-context design (first-class per the framework charter, SURVEY §2.7/§5):
when a per-car history is too long for one chip's HBM — or when the fleet
batch × sequence product wants more FLOPs than one chip has — the sequence
dimension shards over a mesh axis.  Each device holds a local Q/K/V block
[B, T/n, H, D]; K/V blocks rotate around the ring via `jax.lax.ppermute`
(ICI neighbor exchange, bandwidth-optimal), and every device folds each
arriving block into its online-softmax accumulator (`ops.attention
.blockwise_update` — the same math the flash kernel runs within a chip).
After n-1 hops every query has attended every key with O(T/n) memory and
fully overlapped compute/communication (XLA pipelines the permute against
the einsums).

Causality under rotation: device i starts with KV block i; after s hops it
holds block (i - s) mod n, so global key positions are derived from the hop
counter — no gather, no gaps.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map

from ..ops.attention import blockwise_update, finalize_blockwise


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Per-device body (runs under shard_map). q,k,v: local [B, Tl, H, D]."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    scale = 1.0 / math.sqrt(D)

    qpos = my * Tl + jnp.arange(Tl)  # global positions of local queries

    # mark the accumulators as device-varying over the seq axis so the scan
    # carry type matches its output (shard_map vma typing, jax>=0.8);
    # pre-vma JAX has no pcast and needs no marking
    _pcast = getattr(jax.lax, "pcast", None)
    if _pcast is not None:
        vary = lambda x: _pcast(x, (axis_name,), to="varying")  # noqa: E731
    else:
        vary = lambda x: x  # noqa: E731
    o0 = vary(jnp.zeros((B, Tl, H, D), jnp.float32))
    m0 = vary(jnp.full((B, H, Tl), -1e30, jnp.float32))
    l0 = vary(jnp.zeros((B, H, Tl), jnp.float32))

    # jax.checkpoint on the hop body: autodiff would otherwise save every
    # hop's [B,H,Tl,Tl] probability block — O(T²/n) per device — exactly the
    # memory wall ring attention exists to avoid.  Rematerializing keeps the
    # backward at O(T/n), the flash-attention recompute strategy across chips.
    @jax.checkpoint
    def hop_update(o, m, l, k_blk, v_blk, s):
        src = (my - s) % n  # which global block this hop's KV is
        kpos = src * Tl + jnp.arange(Tl)
        mask = (qpos[:, None] >= kpos[None, :]) if causal else None
        return blockwise_update(o, m, l, q.astype(jnp.float32),
                                k_blk.astype(jnp.float32),
                                v_blk.astype(jnp.float32), scale, mask)

    # hop 0 consumes the resident KV block; the scan then does exactly the
    # n-1 rotations needed (a rotate-last loop would ppermute a full K+V
    # shard per layer that nothing reads).
    o, m, l = hop_update(o0, m0, l0, k, v, 0)

    def hop(carry, s):
        o, m, l, k_blk, v_blk = carry
        # rotate KV to the right neighbor (receive from the left)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        o, m, l = hop_update(o, m, l, k_blk, v_blk, s)
        return (o, m, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = jax.lax.scan(hop, (o, m, l, k, v),
                                      jnp.arange(1, n))
    return finalize_blockwise(o, l).astype(q.dtype)


def make_ring_attention(mesh: Mesh, seq_axis: str = "seq",
                        causal: bool = True):
    """Build a sequence-sharded attention fn over `mesh`.

    Returns f(q, k, v) on [B, T, H, D] arrays whose T dim is sharded over
    `seq_axis` (other dims replicated or batch-sharded elsewhere).  Usable
    directly or inside a larger shard_mapped/pjit'd train step.
    """
    body = functools.partial(_ring_attention_local, axis_name=seq_axis,
                             causal=causal)
    spec = P(None, seq_axis, None, None)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = True):
    """shard_map-body form: call inside an existing shard_map/pjit context
    where q/k/v are already the local sequence shards."""
    return _ring_attention_local(q, k, v, axis_name, causal)
