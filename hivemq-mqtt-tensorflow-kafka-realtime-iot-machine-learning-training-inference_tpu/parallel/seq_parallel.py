"""Sequence-parallel training: shard the time axis, train on full context.

Composes with data parallelism over a ('data', 'seq') mesh: batch rows shard
over 'data', the sequence dimension shards over 'seq', attention runs as
ring attention (KV rotating over ICI), and the next-step objective's
cross-shard coupling — position t's target x[t+1] lives on the next shard
for the shard-final step — is a single `ppermute` neighbor exchange.
Gradients of all collectives are handled by their transpose rules, so the
whole step is `jax.grad` of one shard_mapped loss.

This is the long-context training path the reference never had (its LSTM
trains at look_back=1, batch 1 — SURVEY §2.5); here a 100k-step per-car
history trains without any chip holding the full sequence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import shard_map

from ..train.loop import TrainState


def shift_in_next(x_local, axis_name: str):
    """For each local [B, Tl, F] shard, return the next-step targets
    [B, Tl, F]: rows 0..Tl-2 come from the local shard, row Tl-1 is the
    first row of the *next* shard (garbage on the final shard — mask it)."""
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i - 1) % n) for i in range(n)]
    head_of_next = jax.lax.ppermute(x_local[:, :1], axis_name, perm)
    return jnp.concatenate([x_local[:, 1:], head_of_next], axis=1)


def next_step_mask(Tl: int, axis_name: str):
    """[Tl] validity mask for next-step targets: all 1 except the global
    final timestep (which has no successor)."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    local_pos = jnp.arange(Tl)
    is_global_last = (my == n - 1) & (local_pos == Tl - 1)
    return jnp.where(is_global_last, 0.0, 1.0)


def make_sp_train_step(model, tx, mesh: Mesh, data_axis: str = "data",
                       seq_axis: str = "seq"):
    """Build (init_fn, step_fn) for sequence+data-parallel training of a
    SensorFormer-like model (attn_mode='ring', ring_axis=seq_axis).

    step_fn(state, x) with x: [B, T, F] sharded P(data, seq); returns
    (state, metrics) with replicated params/grads (psum over both axes).
    """

    x_spec = P(data_axis, seq_axis)

    def local_loss(params, x_local):
        B, Tl, F = x_local.shape
        my = jax.lax.axis_index(seq_axis)
        n = jax.lax.psum(1, seq_axis)  # static: mesh axis size
        max_len = getattr(model, "max_len", None)
        if max_len is not None and n * Tl > max_len:
            raise ValueError(
                f"global sequence {n * Tl} exceeds model.max_len={max_len}; "
                f"the position Embed gather would silently clamp under jit")
        positions = my * Tl + jnp.arange(Tl)
        pred = model.apply({"params": params}, x_local, positions=positions)
        target = shift_in_next(x_local, seq_axis)
        mask = next_step_mask(Tl, seq_axis)[None, :, None]
        se = jnp.sum(jnp.square(pred - target) * mask)
        se_tot = jax.lax.psum(se, (data_axis, seq_axis))
        # elements counted: valid local steps × local batch × features
        cnt_tot = jax.lax.psum(jnp.sum(mask) * B * F, (data_axis, seq_axis))
        return se_tot / cnt_tot

    loss_fn = shard_map(
        local_loss, mesh=mesh,
        in_specs=(P(), x_spec), out_specs=P(),
        check_vma=False)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, x):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, x))(state.params)
        updates, opt_state = state.tx.update(grads, state.opt_state,
                                             state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state), {"loss": loss}

    def init(rng, sample_x):
        # params don't depend on the attention mode; init with the dense
        # twin so tracing needn't run inside shard_map
        dense = model.clone(attn_mode="dense")
        state = TrainState.create(dense, rng, jnp.asarray(sample_x), tx=tx)
        # replicate params/opt state across the mesh
        rep = NamedSharding(mesh, P())
        return state.replace(
            params=jax.device_put(state.params, rep),
            opt_state=jax.device_put(state.opt_state, rep))

    def put_x(x):
        return jax.device_put(x, NamedSharding(mesh, x_spec))

    return init, step, put_x


def sp_next_step_loss_reference(model_dense, params, x):
    """Single-device oracle: same masked next-step loss, dense attention."""
    pred = model_dense.apply({"params": params}, x)
    se = jnp.sum(jnp.square(pred[:, :-1] - x[:, 1:]))
    cnt = pred[:, :-1].size
    return se / cnt
