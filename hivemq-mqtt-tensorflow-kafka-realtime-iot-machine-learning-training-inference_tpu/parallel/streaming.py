"""Partition-parallel columnar feeds into a sharded train step (ROADMAP 3).

The reference's scaling story is "Kafka partitions × consumer group →
chips" (PARITY §2.7); until now the repo only ever dry-ran it.  This
module spends the consume-side headroom for real: the columnar plane
decodes at ~8× one chip's train rate, so a data-parallel mesh is exactly
what consumes it.

Dataflow (ARCHITECTURE §24):

- `MeshFeeds` gives each local device its OWN host-side pipeline: a
  partition subset (static `assign_partitions` split, or an elastic
  consumer-group membership per device), one `SensorBatches` whose
  `poll_into` fills that feed's private `DecodeRing`, and a
  `DevicePrefetcher` staging thread so decode hides under the device
  step.  Feeds share ONE consumer group: committed offsets stay
  partition-keyed, so a checkpoint manifest stamping every feed's
  cursors is one atomic resume unit.
- `ShardedStreamTrainer` pairs feed *d* with data-axis device *d*:
  each step `jax.device_put`s every feed's rows directly onto its
  device and assembles the global batch with
  `jax.make_array_from_single_device_arrays` — no host concatenation,
  no resharding copy — then runs the jitted step whose gradient
  all-reduce XLA compiles over the mesh (ICI on real slices).
- Normalization rides the step, not the host: with
  ``device_normalize=True`` the feeds ship RAW float32 columns
  (`core.normalize.RAW_COLUMNS`) and the affine map folds into the
  jitted program (`data_parallel.make_device_normalized_step`) — the
  last per-element host work disappears from the hot loop.

The per-row pre-update loss stays sharded over 'data' in the step's
metrics (zero collective cost), which is what `iotml.online`'s per-chip
drift detectors read.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from ..data.dataset import SensorBatches
from ..stream.consumer import StreamConsumer
from ..stream.group import GroupConsumer, GroupCoordinator
from ..train.loop import TrainState, adam_cached
from .data_parallel import ShardedTrainer
from .distributed import assign_partitions


def data_axis_devices(mesh) -> list:
    """The mesh's devices in 'data'-axis order — feed *d* owns device
    *d*.  The streaming trainer is pure data parallelism by design
    (SURVEY §2.7: partitions → chips); a >1 model/seq/pipe axis would
    need every row block replicated across that axis, which defeats the
    shard-lands-on-its-device contract, so it is refused here."""
    if mesh.axis_names[0] != "data":
        raise ValueError(f"streaming mesh must lead with the 'data' axis, "
                         f"got {mesh.axis_names}")
    for name in mesh.axis_names[1:]:
        if mesh.shape[name] != 1:
            raise ValueError(
                f"streaming trainer is pure data-parallel; axis "
                f"{name!r} has size {mesh.shape[name]} (use a "
                f"('data',) or ('data', 'model'=1) mesh)")
    return list(mesh.devices.reshape(mesh.shape["data"], -1)[:, 0])


class MeshFeeds:
    """Per-device partition-parallel host pipelines over ONE group.

    Args:
      broker: Broker duck-type (in-process, wire client, ClusterClient).
      topic: the consumed stream.
      n_feeds: local data-axis size — one feed (consumer + batcher +
        decode ring) per device.
      group: the shared consumer group; commits are partition-keyed so
        all feeds' offsets live in one resume namespace.
      coordinator: None (default) = static deterministic split via
        `assign_partitions` (offset checkpoints stay device-stable
        across restarts — the multihost contract).  A
        `GroupCoordinator` (shared, in-process) or a zero-arg factory
        returning one (wire `RemoteGroupCoordinator` per member) makes
        each feed a group MEMBER instead: partition subsets stay
        disjoint and exhaustive under rebalance, and a dead feed's
        partitions move to survivors after the session timeout.
      batch_size/take_batches/only_normal/poll_chunk: per-feed
        `SensorBatches` knobs; `take_batches` bounds EACH feed's round.
      normalizer: host-side normalizer (ignored under device_normalize).
      device_normalize: ship raw float32 columns — the affine map runs
        on-device inside the jitted step (pass the real normalizer to
        `ShardedStreamTrainer(normalizer=...)`).
    """

    def __init__(self, broker, topic: str, n_feeds: int,
                 group: str = "cardata-mesh-train",
                 coordinator: Union[None, GroupCoordinator, Callable] = None,
                 batch_size: int = 100, take_batches: Optional[int] = None,
                 only_normal: bool = True, normalizer=None,
                 device_normalize: bool = False, poll_chunk: int = 8192):
        from ..core.normalize import RAW_COLUMNS

        if n_feeds < 1:
            raise ValueError(f"n_feeds must be >= 1, got {n_feeds}")
        self.broker = broker
        self.topic = topic
        self.group = group
        self.batch_size = batch_size
        self.device_normalize = device_normalize
        n_parts = broker.topic(topic).partitions
        self.consumers: List = []
        self.partitions: List[List[int]] = []
        for d in range(n_feeds):
            if coordinator is None:
                parts = assign_partitions(n_parts, n_feeds, d)
                consumer = StreamConsumer.from_committed(
                    broker, topic, parts, group=group)
            else:
                coord = coordinator() if callable(coordinator) \
                    else coordinator
                consumer = GroupConsumer(coord, [topic])
                parts = [p for _t, p in consumer.assignment]
            self.consumers.append(consumer)
            self.partitions.append(list(parts))
        if coordinator is not None:
            # members join sequentially and each join rebalances: one
            # heartbeat round lets every member adopt the CONVERGED
            # assignment before anyone consumes
            for consumer in self.consumers:
                consumer._ensure_membership()
            self.partitions = [[p for _t, p in c.assignment]
                               for c in self.consumers]
        batch_kw = {}
        if device_normalize:
            batch_kw["normalizer"] = RAW_COLUMNS
        elif normalizer is not None:
            batch_kw["normalizer"] = normalizer
        self.batchers = [
            SensorBatches(c, batch_size=batch_size, take=take_batches,
                          only_normal=only_normal, poll_chunk=poll_chunk,
                          **batch_kw)
            for c in self.consumers]

    def __len__(self) -> int:
        return len(self.consumers)

    def set_take(self, take_batches: Optional[int]) -> None:
        """Re-bound every feed's next round (None = drain to log end)."""
        for b in self.batchers:
            b.take = take_batches

    def rounds(self):
        """Yield per-step rows ``[Batch | None per feed]`` until every
        feed's bounded iteration ends.  Each feed decodes on its OWN
        staging thread (`DevicePrefetcher` with a host-side pass-
        through), so the D host pipelines overlap each other and the
        device step; all JAX dispatch stays on the consuming thread
        (the prefetcher's documented discipline)."""
        from ..data.prefetch import DevicePrefetcher

        pfs = [DevicePrefetcher(iter(b), to_device=lambda batch: batch,
                                loop="train")
               for b in self.batchers]
        its = [iter(pf) for pf in pfs]
        try:
            while True:
                row = [next(it, None) for it in its]
                if all(b is None for b in row):
                    return
                yield row
        finally:
            for pf in pfs:
                pf.close()

    # ------------------------------------------- consumer-facade surface
    def positions(self) -> List[tuple]:
        """Every feed's cursors, one flat list — what a checkpoint
        manifest stamps: ALL devices' partitions as one atomic unit."""
        out: List[tuple] = []
        for c in self.consumers:
            out.extend(tuple(p) for p in c.positions())
        return sorted(out)

    def available(self) -> int:
        return sum(self.broker.end_offset(t, p) - off
                   for t, p, off in self.positions())

    def commit(self) -> None:
        for c in self.consumers:
            c.commit()

    def seek(self, topic: str, partition: int, offset: int) -> None:
        """Route a cursor move to the feed that owns the partition —
        by LIVE ownership, not the construction-time snapshot (group
        mode reassigns under rebalance).  Group-elastic feeds have no
        absolute seek (the group's committed offset is the cursor, the
        GroupConsumer contract), so they refuse loudly instead of
        silently resuming elsewhere."""
        for c in self.consumers:
            owned = {p for _t, p in c.assignment} \
                if hasattr(c, "assignment") \
                else {p for _t, p, _ in c.positions()}
            if partition in owned:
                seek = getattr(c, "seek", None)
                if seek is None:
                    raise NotImplementedError(
                        "group-elastic feeds seek via committed offsets "
                        "(commit before rebuilding), not absolute seeks")
                seek(topic, partition, offset)
                return
        raise KeyError(f"partition {partition} not owned by any feed")

    def take_event_time(self) -> dict:
        """Merged event-time ranges across feeds (watermark publish)."""
        merged: dict = {}
        for c in self.consumers:
            take = getattr(c, "take_event_time", None)
            if take is None:
                continue
            for key, (lo, hi) in take().items():
                if key in merged:
                    mlo, mhi = merged[key]
                    merged[key] = (min(mlo, lo), max(mhi, hi))
                else:
                    merged[key] = (lo, hi)
        return merged

    def take_traces(self) -> list:
        out: list = []
        for b in self.batchers:
            out.extend(b.take_traces())
        return out

    def records_seen(self) -> int:
        return sum(b.records_seen for b in self.batchers)

    def assignments(self) -> List[List[tuple]]:
        """Per-feed (topic, partition) ownership right now — group mode
        reads the live assignment (it moves under rebalance)."""
        out = []
        for c in self.consumers:
            if hasattr(c, "assignment"):
                out.append(sorted(c.assignment))
            else:
                out.append(sorted({(t, p)
                                   for t, p, _ in c.positions()}))
        return out

    def close(self) -> None:
        for c in self.consumers:
            close = getattr(c, "close", None)
            if close is not None:
                try:
                    close()
                except (ConnectionError, RuntimeError, OSError):
                    pass


class ShardedStreamTrainer:
    """The streaming face of `ShardedTrainer`: per-device feeds in,
    mesh-sharded optimizer steps out.

    Exposes the `train.loop.Trainer` warm-start surface
    (``_ensure_state`` + ``state``) so `mlops.restore_trainer` and the
    `AsyncCheckpointer` treat it like any trainer: a restore lands in a
    HOST state that the mesh adopts (shards) on the first step, and
    ``state`` is always fully addressable to `jax.device_get` — the
    checkpoint snapshot gathers the sharded params host-side for one
    atomic manifest.
    """

    def __init__(self, model, mesh, feeds: MeshFeeds,
                 learning_rate: float = 1e-3, tx=None, rng=None,
                 normalizer=None, supervised: bool = False):
        if normalizer is None and feeds.device_normalize:
            raise ValueError(
                "feeds ship raw columns (device_normalize=True) but no "
                "device-side normalizer was given — the step would train "
                "on unnormalized data")
        self.model = model
        self.mesh = mesh
        self.feeds = feeds
        self.learning_rate = learning_rate
        self._tx_key = ("adam", learning_rate) if tx is None else None
        self.tx = tx or adam_cached(learning_rate)
        self._st = ShardedTrainer(
            model, mesh, rng=rng, tx=self.tx, supervised=supervised,
            normalizer=normalizer if feeds.device_normalize else None,
            row_loss=True)
        self._devices = data_axis_devices(mesh)
        if len(self._devices) != len(feeds):
            raise ValueError(
                f"mesh data axis {len(self._devices)} != feeds "
                f"{len(feeds)} — one feed per data-axis device")
        self._host_state: Optional[TrainState] = None
        self._zero_shard: Optional[np.ndarray] = None
        self.last_shard_losses: Optional[np.ndarray] = None
        self.records_trained = 0

    # ----------------------------------------------- Trainer-shaped state
    @property
    def state(self) -> Optional[TrainState]:
        return self._st.state if self._st.state is not None \
            else self._host_state

    @state.setter
    def state(self, st: TrainState) -> None:
        # restore path: adopt a HOST state; the mesh (re)shards it on
        # the next step
        self._host_state = st
        self._st.state = None

    def _ensure_state(self, sample_x) -> None:
        if self.state is None:
            self._host_state = TrainState.create(
                self.model, self._st.rng, sample_x, tx=self.tx,
                tx_key=self._tx_key)

    # --------------------------------------------------------- assembly
    def _global_put(self, shards: List[np.ndarray]):
        """Per-device `device_put` + metadata-only global assembly: feed
        *d*'s rows land ONLY on device *d* (the zero-copy landing the
        tentpole names), then the mesh sees one logical array."""
        import jax

        arrays = [jax.device_put(s, d)
                  for s, d in zip(shards, self._devices)]
        shape = (sum(s.shape[0] for s in shards),) + shards[0].shape[1:]
        return jax.make_array_from_single_device_arrays(
            shape, self._st.data_sharding, arrays)

    def _assemble(self, row: Sequence):
        """Per-feed batches → (x_global, mask_global, n_valid).  A feed
        with no batch this step (its partitions ran dry first, or own
        fewer records) contributes a zero shard with a zero mask — the
        masked loss ignores it, shapes stay static, no recompiles."""
        template = next(b for b in row if b is not None)
        if self._zero_shard is None or \
                self._zero_shard.shape != template.x.shape:
            self._zero_shard = np.zeros_like(template.x)
        xs, masks, n_valid = [], [], 0
        zero_mask = np.zeros((template.x.shape[0],), np.float32)
        for b in row:
            if b is None:
                xs.append(self._zero_shard)
                masks.append(zero_mask)
            else:
                xs.append(np.ascontiguousarray(b.x, np.float32))
                masks.append(b.mask)
                n_valid += b.n_valid
        return self._global_put(xs), self._global_put(masks), n_valid

    # ---------------------------------------------------------- training
    def fit_round(self) -> dict:
        """One bounded pass over the feeds (their `take` budget): step
        per assembled global batch, losses held on device until the
        round closes (one sync), per-chip row losses published on
        `last_shard_losses`.  History mirrors `Trainer.fit_compiled`'s
        shape so `ContinuousTrainer.train_round` consumes it as-is."""
        import jax

        from ..obs import metrics as obs_metrics

        t0 = time.perf_counter()
        losses: list = []
        records = 0
        dev_s = 0.0
        last_row_loss = None
        last_counts = None
        for row in self.feeds.rounds():
            xg, mg, n_valid = self._assemble(row)
            if self._st.state is None:
                sample = next(b for b in row if b is not None).x
                self._ensure_state(sample)
                self._st.init(sample, from_state=self._host_state)
                self._host_state = None
            t_step = time.perf_counter()
            self._st.state, m = self._st._step(
                self._st.state, xg, xg, mg)
            dev_s += time.perf_counter() - t_step
            losses.append(m["loss"])  # device scalar: no per-step sync
            last_row_loss = m["row_loss"]
            last_counts = [0 if b is None else b.n_valid for b in row]
            records += n_valid
        if not losses:
            return {"loss": [], "accuracy": [], "records": [],
                    "seconds": []}
        # the PR 12 profiling contract: device_compute spans THROUGH the
        # sync (dispatch is async — per-step timers would read ~0).
        # Losses sync once per round, so the round's device leg is the
        # accumulated dispatch time plus the closing device_get wait,
        # observed as ONE sample.
        t_sync = time.perf_counter()
        losses = [float(v) for v in jax.device_get(losses)]
        dev_s += time.perf_counter() - t_sync
        obs_metrics.step_seconds.observe(dev_s, loop="train",
                                         phase="device_compute")
        if last_row_loss is not None:
            self.last_shard_losses = shard_mean_losses(
                last_row_loss, last_counts)
        self.records_trained += records
        obs_metrics.records_trained.inc(records)
        return {"loss": [float(np.mean(losses))],
                "accuracy": [float("nan")],
                "records": [records],
                "seconds": [time.perf_counter() - t0],
                "steps": len(losses), "step_loss": losses}

    def fit_compiled(self, _batches=None, epochs: int = 1) -> dict:
        """Trainer-API shim: the feeds ARE the batch source.  Mesh
        rounds are single-pass by design (a committed stream cursor
        cannot re-read its slice without a seek)."""
        if epochs != 1:
            raise ValueError("mesh streaming rounds are single-epoch "
                             "(the cursor is the slice)")
        return self.fit_round()


def shard_mean_losses(row_loss, valid_counts: Sequence[int]) -> np.ndarray:
    """Per-chip mean pre-update loss out of the sharded row-loss vector.

    ``row_loss`` is the step's [B] metric sharded over 'data' (each
    shard already lives on its chip); ``valid_counts`` are the host-side
    valid-row counts per feed (padding rows carry mask 0, so shard sums
    need only dividing by the true counts).  Shards are ordered by their
    global row index, which is the feed/device order by construction."""
    pieces = sorted(row_loss.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    if len(pieces) != len(valid_counts):
        # a >1 model axis replicates row blocks; streaming refuses that
        # mesh shape upstream, so this is a defensive invariant
        raise ValueError(f"{len(pieces)} row-loss shards != "
                         f"{len(valid_counts)} feeds")
    return np.asarray([float(np.asarray(p.data).sum()) / max(c, 1)
                       for p, c in zip(pieces, valid_counts)])


# --------------------------------------------------------------- benching
def leg_record(leg: str, devices: int, records: int, seconds: float,
               loss_first: Optional[float], loss_last: Optional[float],
               **extra) -> dict:
    """One scaling-curve leg in the SHARED schema: `bench_multichip`
    (bench.py) and the driver's MULTICHIP_r* harness
    (__graft_entry__.dryrun_multichip) both emit exactly this, so
    curves are comparable across rounds and sources."""
    rec = {"leg": leg, "devices": int(devices), "records": int(records),
           "seconds": round(float(seconds), 4),
           "records_per_sec": round(records / seconds, 1)
           if seconds > 0 else 0.0,
           "loss_first": None if loss_first is None
           else round(float(loss_first), 6),
           "loss_last": None if loss_last is None
           else round(float(loss_last), 6)}
    rec.update(extra)
    return rec


def bench_leg(n_devices: int, records: int = 40_000,
              warmup_records: int = 8_000, batch_size: int = 100,
              partitions: int = 8, store_dir: Optional[str] = None) -> dict:
    """One measured point of the 1→N scaling curve: a durable columnar
    broker seeded with ``warmup + records`` rows, partition-parallel
    feeds over the first ``n_devices`` local devices, device-side
    normalization ON, one warm (compile) round, then a timed drain of
    the remaining stream through the sharded step.

    Runs in-process over `jax.devices()[:n]` — the caller owns the
    device count (bench.py spawns one child per leg with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``; tests call
    it directly under the suite's 8-virtual-device mesh)."""
    import shutil
    import tempfile

    import jax

    from ..core.normalize import CAR_NORMALIZER
    from ..gen.simulator import FleetGenerator, FleetScenario
    from ..models.autoencoder import CAR_AUTOENCODER
    from ..store.log import StorePolicy
    from ..stream.broker import Broker
    from .mesh import make_mesh

    if n_devices > len(jax.devices()):
        raise ValueError(f"need {n_devices} devices, have "
                         f"{len(jax.devices())}")
    tmp = None
    if store_dir is None:
        tmp = store_dir = tempfile.mkdtemp(prefix="iotml_multichip_")
    broker = None
    feeds = None
    try:
        broker = Broker(store_dir=store_dir,
                        store_policy=StorePolicy(fsync="never"))
        num_cars = 100
        gen = FleetGenerator(FleetScenario(num_cars=num_cars,
                                           failure_rate=0.01))
        total = warmup_records + records
        gen.publish(broker, "SENSOR_DATA_S_AVRO",
                    n_ticks=max(total // num_cars, 1),
                    partitions=partitions)
        mesh = make_mesh((n_devices,), ("data",),
                         devices=jax.devices()[:n_devices])
        feeds = MeshFeeds(broker, "SENSOR_DATA_S_AVRO", n_devices,
                          group=f"multichip-bench-{n_devices}",
                          batch_size=batch_size, only_normal=True,
                          device_normalize=True)
        trainer = ShardedStreamTrainer(CAR_AUTOENCODER, mesh, feeds,
                                       normalizer=CAR_NORMALIZER)
        # warm round: bounded per-feed take → compile + cache warm
        warm_take = max(warmup_records // (n_devices * batch_size), 1)
        feeds.set_take(warm_take)
        warm = trainer.fit_round()
        # timed leg: drain the rest of the stream through the mesh
        feeds.set_take(None)
        t0 = time.perf_counter()
        hist = trainer.fit_round()
        seconds = time.perf_counter() - t0
        trained = hist["records"][-1] if hist["records"] else 0
        step_losses = (warm.get("step_loss") or []) + \
            (hist.get("step_loss") or [])
        return leg_record(
            "streaming dp", n_devices, trained, seconds,
            step_losses[0] if step_losses else None,
            step_losses[-1] if step_losses else None,
            per_device_batch=batch_size, partitions=partitions,
            steps=hist.get("steps", 0), device_normalize=True)
    finally:
        # close on EVERY exit: a raised round must not leak broker
        # threads / open segments into the calling process (tests run
        # this in-process)
        if feeds is not None:
            feeds.close()
        if broker is not None:
            broker.close()
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
