"""iotml.replication — quorum ISR durability and elastic reassignment.

The reference provisions every topic at replication factor 3 on a
3-broker cluster (PAPER.md L3, ``01_installConfluentPlatform.sh``
RF-3 topics); until this package the rebuild ran exactly one fenced
follower per shard — ``acks=1`` semantics, where acked data is one
crash away from loss.  This package generalises the PR 4/6
epoch-fencing machinery into Kafka-shape replicated durability:

- ``ReplicationState`` (`isr.py`): the leader-side in-sync-replica
  tracker.  Followers stamp a replica id into their FETCH/RAW_FETCH
  requests; the leader observes each fetch position, admits a follower
  into the ISR when it reaches the log end, evicts it after the
  staleness window, and advances a per-partition **quorum high-water
  mark** at min(ISR positions).  ``acks=all`` produces commit only
  below that mark, consumer fetches are bounded by it (no reads of the
  un-replicated tail), and the mark persists across remount through a
  store-owned checkpoint (`store/hwm.py`).
- ``ReplicaSet`` (`manager.py`): a leader plus N followers as one
  managed unit — construction, ISR formation, ISR-restricted leader
  election at epoch+1, live follower add/retire.
- ``ShardReassignment`` (`reassign.py`): the online reassignment state
  machine behind ``python -m iotml.cluster add-broker/drain-broker`` —
  a new replica bootstraps from the segment log over zero-copy
  RAW_FETCH, catches up, joins the ISR, leadership moves through the
  existing Topology cells, and the old replica retires, with zero
  consumer disruption.
- Live drills (`drill.py`, ``python -m iotml.replication drill``):
  double-fault (leader + one follower killed mid-epoch under
  sustained acks=all load; zero acked-record loss) and
  reassign-under-load (catch-up SLO, zero consumer disruption).

Lint R15 confines ISR-set and quorum-HWM mutation to this package
(the wire server's ``observe_fetch`` ingress excepted), mirroring the
R9/R11/R12 one-writer disciplines.
"""

from .isr import ReplicationState
from .manager import ReplicaSet
from .reassign import ShardReassignment

__all__ = ["ReplicationState", "ReplicaSet", "ShardReassignment"]
