"""CLI: live replication drills (exit status = invariant verdict).

    python -m iotml.replication drill [--name double-fault|reassign]
                                      [--seed 7] [--records 1500]
    python -m iotml.replication list
"""

from __future__ import annotations

import argparse
import json
import sys


def cmd_drill(args) -> int:
    from .drill import DRILLS

    names = list(DRILLS) if args.name == "all" else [args.name]
    ok = True
    for name in names:
        report = DRILLS[name](seed=args.seed, records=args.records)
        print(json.dumps(report.to_dict(), indent=2, default=str))
        for line in report.lines():
            print(line, file=sys.stderr)
        ok = ok and report.ok
    return 0 if ok else 1


def cmd_list(_args) -> int:
    from .drill import DRILLS

    for name, fn in sorted(DRILLS.items()):
        doc = (fn.__doc__ or "").strip().splitlines()[0]
        print(f"{name:14s} {doc}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.replication",
        description="quorum ISR durability + elastic reassignment")
    sub = ap.add_subparsers(dest="cmd", required=True)

    drill = sub.add_parser("drill", help="run a live drill")
    drill.add_argument("--name", default="all",
                       choices=("all", "double-fault", "reassign"))
    drill.add_argument("--seed", type=int, default=7)
    drill.add_argument("--records", type=int, default=1500)
    drill.set_defaults(fn=cmd_drill)

    lst = sub.add_parser("list", help="list drills")
    lst.set_defaults(fn=cmd_list)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
