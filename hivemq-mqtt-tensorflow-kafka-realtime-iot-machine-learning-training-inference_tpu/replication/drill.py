"""Live replication drills — quorum durability and elasticity under
real threads (``python -m iotml.replication drill``; exit = verdict).

Two drills, the live counterparts of the deterministic ``double-fault``
chaos scenario:

- ``double-fault``: a leader + two ISR followers serve sustained
  acks=all load from real producer/consumer threads; one follower is
  killed abruptly (the ISR must evict it and the quorum re-form), then
  the LEADER is killed with no drain while a Supervisor TCP-probes it —
  the on_death hook performs the ISR-RESTRICTED promotion at epoch+1
  and publishes the Topology cell, a new follower heals the set, and
  the stream finishes.  Invariants: ZERO acked-record loss
  (byte-identical at identical offsets), the new leader provably in
  the ISR at the kill, exact-once consumption; SLO: time-to-promote.

- ``reassign``: a 3-broker quorum cluster under sustained acks=all
  produce + committed consume runs ``add_broker`` (a new node
  bootstraps shard 1's log over zero-copy RAW_FETCH, joins the ISR,
  takes leadership, the old replica retires) and then ``drain_broker``
  (shard 2's leadership moves to an existing ISR follower) — LIVE,
  with the load never pausing.  Invariants: zero lost / zero
  double-consumed records by identity, the catch-up actually rode the
  raw mirror; SLOs: catch-up time, total move time, and the consumer's
  longest stall (zero disruption means reconnect-sized, not
  outage-sized).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

# lint-ok: R7 drill harness — the live peer of chaos.runner (reuses its
# Invariant machinery against a real platform), not a hot path
from ..chaos.runner import Invariant, _record_commits
from ..chaos.scenarios import CARS_PER_TICK
from ..supervise.drill import DrillReport
from ..supervise.registry import register_thread
from ..supervise.supervisor import Supervisor
from ..supervise.topology import Topology

IN_TOPIC = "sensor-data"
GROUP = "repl-drill"


class _Load:
    """Sustained acks=all produce + committed consume on own threads,
    with redelivery on every ConnectionError-family signal and the
    consumer's stall clock running — the background traffic both
    drills must never disrupt."""

    def __init__(self, producer, consumer, parts: int,
                 topic: str = IN_TOPIC, tick_sleep_s: float = 0.01):
        self.producer = producer
        self.consumer = consumer
        self.parts = parts
        self.topic = topic
        self.tick_sleep_s = tick_sleep_s
        self.acked: Dict[Tuple[int, int], bytes] = {}
        #: first-seen value per (partition, offset): delivery is
        #: at-least-once across failovers (a commit lost to a dying
        #: leader re-delivers its batch), so EFFECTS are counted by
        #: record identity and raw re-deliveries separately
        self.consumed: Dict[Tuple[int, int], bytes] = {}
        self.redelivered = 0
        self.rewinds = 0
        self.refused = 0          # NotEnoughReplicas windows observed
        self.produce_errors: List[str] = []  # exhausted redeliveries
        self.max_stall_s = 0.0
        self._stop = threading.Event()
        self._stop_produce = threading.Event()
        self._threads: List[threading.Thread] = []
        self._tick = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ bodies
    def _produce_loop(self) -> None:
        while not self._stop_produce.is_set():
            tick = self._tick
            self._tick += 1
            for p in range(self.parts):
                values = [f"t{tick}r{i}p{p}".encode()
                          for i in range(CARS_PER_TICK // self.parts)]
                last_err: Optional[Exception] = None
                for _attempt in range(40):
                    if self._stop_produce.is_set():
                        return
                    try:
                        last = self.producer.produce_many(
                            self.topic, [(None, v, 0) for v in values],
                            partition=p, timeout_ms=8000)
                    except ConnectionError as e:
                        # failover/reassignment in flight (incl.
                        # NotEnoughReplicas + ProduceTimedOut):
                        # redeliver — acks=all means only the ACK
                        # defines existence
                        self.refused += 1
                        last_err = e
                        time.sleep(0.1)
                        continue
                    with self._lock:
                        for i, v in enumerate(values):
                            self.acked[(p, last - len(values) + 1 + i)] \
                                = v
                    break
                else:
                    # NEVER drop a batch silently: a weakened load
                    # would let the delivery invariants pass vacuously
                    # — surface the failure as its own invariant and
                    # stop producing (the drill fails loudly)
                    self.produce_errors.append(
                        f"partition {p}: undeliverable after 40 "
                        f"redelivery attempts: {last_err}")
                    self._stop_produce.set()
                    return
            time.sleep(self.tick_sleep_s)

    def _consume_loop(self) -> None:
        last_ok = time.monotonic()
        while not self._stop.is_set():
            try:
                batch = self.consumer.poll(4096)
                if batch:
                    with self._lock:
                        for m in batch:
                            key = (m.partition, m.offset)
                            if key in self.consumed:
                                self.redelivered += 1
                            else:
                                self.consumed[key] = m.value
                    # commit INSIDE the failover guard: a leader dying
                    # between poll and commit is the drill's point —
                    # the rewind re-delivers this batch (at-least-once)
                    # and identity dedup above keeps effects exact-once
                    self.consumer.commit()
            except ConnectionError:
                self.consumer.rewind_to_committed()
                self.rewinds += 1
                time.sleep(0.02)
                continue
            now = time.monotonic()
            self.max_stall_s = max(self.max_stall_s, now - last_ok)
            last_ok = now
            if not batch:
                time.sleep(0.002)

    # --------------------------------------------------------- lifecycle
    def start(self) -> "_Load":
        for name, body in (("producer", self._produce_loop),
                           ("consumer", self._consume_loop)):
            t = register_thread(threading.Thread(
                target=body, daemon=True,
                name=f"iotml-repl-drill-{name}"))
            t.start()
            self._threads.append(t)
        return self

    def stop_producer(self) -> None:
        """Quiesce the write side (the consumer keeps draining — final
        drains need a stable log end, not a dead consumer)."""
        self._stop_produce.set()
        self._threads[0].join(timeout=15)

    def stop(self) -> None:
        self._stop_produce.set()
        self._stop.set()
        for t in self._threads:
            t.join(timeout=15)

    def drain_to_end(self, end_offsets: Dict[int, int],
                     timeout_s: float = 30.0) -> None:
        """Keep the consumer thread running until it has covered every
        offset below `end_offsets` (post-load final drain)."""
        want = {(p, o) for p, end in end_offsets.items()
                for o in range(end)}
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                have = set(self.consumed)
            if want <= have:
                return
            time.sleep(0.05)

    # --------------------------------------------------------- verdicts
    def delivery_invariants(self, end_offsets: Dict[int, int]
                            ) -> List[Invariant]:
        with self._lock:
            acked = dict(self.acked)
            seen = set(self.consumed)
        expected = {(p, o) for p, end in end_offsets.items()
                    for o in range(end)}
        missing = expected - seen
        return [
            Invariant(
                "zero_lost",
                not missing,
                f"all {len(expected)} log records consumed exactly "
                f"once by identity ({self.redelivered} at-least-once "
                f"re-deliveries absorbed)" if not missing else
                f"{len(missing)} records NEVER consumed "
                f"(e.g. {sorted(missing)[:3]})"),
            Invariant(
                "acked_all_covered",
                all(k in expected for k in acked),
                f"{len(acked)} acks all inside the final log"),
            Invariant(
                "producer_never_gave_up",
                not self.produce_errors,
                "every scheduled batch was eventually acked "
                f"({self.refused} redelivery windows ridden out)"
                if not self.produce_errors else
                "; ".join(self.produce_errors)),
        ]


def _end_offsets(broker_like, topic: str, parts: int) -> Dict[int, int]:
    return {p: broker_like.end_offset(topic, p) for p in range(parts)}


# ------------------------------------------------------- double-fault
def drill_double_fault(seed: int = 7, records: int = 1500,
                       slo_promote_s: float = 10.0) -> DrillReport:
    """Leader + one follower killed mid-epoch under live acks=all load;
    supervised ISR-restricted promotion, elastic heal, zero acked loss."""
    from ..stream.broker import Broker
    from ..stream.consumer import StreamConsumer
    from ..stream.kafka_wire import KafkaWireBroker, KafkaWireServer
    from .manager import ReplicaSet

    parts = 2
    leader = Broker()
    leader.create_topic(IN_TOPIC, partitions=parts)
    commit_log: List[tuple] = []
    _record_commits(leader, commit_log, "leader")
    lsrv = KafkaWireServer(leader, epoch=0).start()
    rs = ReplicaSet(leader_broker=leader, leader_server=lsrv,
                    n_followers=2, min_isr=2, max_lag_s=0.4,
                    topics=[IN_TOPIC], groups=(GROUP,))
    topo = Topology(f"127.0.0.1:{lsrv.port}", epoch=0,
                    fallback=[f"127.0.0.1:{rep.port}"
                              for rep in rs.followers.values()])
    rs.start(sync="thread")
    assert rs.await_isr(3, IN_TOPIC, 0, timeout_s=15), \
        "ISR never formed"

    producer = KafkaWireBroker(topo.leader, client_id="drill-producer",
                               topology=topo)
    consumer_client = KafkaWireBroker(topo.leader,
                                      client_id="drill-consumer",
                                      topology=topo)
    consumer = StreamConsumer(
        consumer_client, [f"{IN_TOPIC}:{p}:0" for p in range(parts)],
        group=GROUP)
    load = _Load(producer, consumer, parts).start()

    state: dict = {}
    promoted = threading.Event()

    def failover(_unit):
        state["isr_at_kill"] = sorted(rs.state.isr_follower_ids())
        state["acked_at_kill"] = dict(load.acked)
        rid, addr = rs.promote(topo.epoch + 1)  # ISR-restricted
        state["promoted_rid"] = rid
        topo.publish(addr, topo.epoch + 1)
        state["t_promoted"] = time.monotonic()
        promoted.set()

    def leader_probe():
        import socket

        s = socket.create_connection(("127.0.0.1", lsrv.port),
                                     timeout=0.25)
        s.close()
        return True

    sup = Supervisor(poll_interval_s=0.05, name="repl-drill-supervisor")
    sup.add_probed("leader-broker", leader_probe, on_death=failover,
                   probe_failures=2)
    sup.start()

    killed_follower: Optional[int] = None
    healed_rid: Optional[int] = None
    t_kill = None
    try:
        # phase 1: a third of the stream under the full-width quorum
        target = max(records // 3, CARS_PER_TICK)
        deadline = time.monotonic() + 30
        while len(load.acked) < target and time.monotonic() < deadline:
            time.sleep(0.02)
        # fault 1: one follower dies abruptly; the ISR must evict it
        killed_follower = sorted(rs.followers)[0]
        rs.kill_follower(killed_follower)
        state["t_follower_kill"] = time.monotonic()
        target = max(2 * records // 3, 2 * CARS_PER_TICK)
        deadline = time.monotonic() + 30
        while len(load.acked) < target and time.monotonic() < deadline:
            time.sleep(0.02)
        evicted = killed_follower not in rs.state.isr_follower_ids()
        # fault 2: the leader dies mid-epoch, NO drain
        t_kill = time.monotonic()
        lsrv.kill()
        assert promoted.wait(timeout=30), "supervisor never promoted"
        # elastic heal: re-form the 2-wide quorum so acks=all resumes
        if killed_follower is not None:
            rs.retire_follower(killed_follower)
        healed_rid = rs.add_follower(sync="thread")
        deadline = time.monotonic() + 30
        while healed_rid not in rs.state.isr_follower_ids() and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        # phase 3: finish the stream on the promoted quorum
        target = records
        deadline = time.monotonic() + 30
        while len(load.acked) < target and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        load.stop_producer()
        sup.stop()
        ends = _end_offsets(rs.leader, IN_TOPIC, parts)
        load.drain_to_end(ends)
        load.stop()
        for c in (producer, consumer_client):
            try:
                c.close()
            except OSError:
                pass
        rs.stop()

    # zero acked loss, byte-identical, for everything acked BEFORE the
    # leader death (later acks are trivially on the promoted log)
    lost = []
    for (p, off), value in sorted(state.get("acked_at_kill",
                                            {}).items()):
        got = {m.offset: m.value
               for m in rs.leader.fetch_tail(IN_TOPIC, p, off, 1)}
        if got.get(off) != value:
            lost.append((p, off))
    promote_s = state.get("t_promoted", float("inf")) - \
        (t_kill or float("inf"))
    invariants = load.delivery_invariants(ends) + [
        Invariant(
            "zero_acked_loss",
            not lost and bool(state.get("acked_at_kill")),
            f"all {len(state.get('acked_at_kill', {}))} pre-kill acks "
            f"byte-identical on the promoted log" if not lost else
            f"{len(lost)} ACKED RECORDS LOST (e.g. {lost[:3]})"),
        Invariant(
            "new_leader_in_isr",
            state.get("promoted_rid") in state.get("isr_at_kill", ()),
            f"promoted replica {state.get('promoted_rid')} was in the "
            f"ISR {state.get('isr_at_kill')} at the kill"),
        Invariant(
            "follower_evicted",
            evicted,
            f"dead follower {killed_follower} left the ISR within the "
            f"staleness window" if evicted else
            f"dead follower {killed_follower} still in the ISR"),
        Invariant(
            "quorum_healed",
            healed_rid is not None and
            healed_rid in rs.state.isr_follower_ids(),
            f"replica {healed_rid} bootstrapped and re-joined the ISR "
            f"(raw-mirrored "
            f"{getattr(rs.followers.get(healed_rid), 'raw_mirrored', 0)}"
            f" records)"),
        Invariant(
            "promote_slo",
            promote_s <= slo_promote_s,
            f"time-to-promote {promote_s:.2f}s <= {slo_promote_s}s"),
    ]
    return DrillReport(
        drill="double-fault", seed=seed, records=records,
        published=len(load.acked), scored=len(load.consumed),
        restarts={}, slos={"time_to_promote_s": promote_s,
                           "consumer_max_stall_s": load.max_stall_s},
        invariants=invariants, injected={})


# ----------------------------------------------------------- reassign
def drill_reassign(seed: int = 7, records: int = 1500,
                   slo_catch_up_s: float = 30.0,
                   slo_stall_s: float = 8.0) -> DrillReport:
    """add-broker → reassign → drain-broker under sustained load with
    zero consumer disruption and zero-copy RAW_FETCH catch-up."""
    from ..cluster import ClusterController
    from ..stream.consumer import StreamConsumer

    parts = 6
    ctl = ClusterController(brokers=3, replication_factor=3, min_isr=2,
                            replica_sync="thread", max_lag_s=0.4)
    ctl.start()
    load = None
    client = consumer_client = None
    reports: List[dict] = []
    try:
        ctl.create_topic(IN_TOPIC, partitions=parts)
        for i in range(3):
            assert ctl.replica_sets[i].await_isr(
                3, IN_TOPIC, i, timeout_s=15), f"shard {i} ISR"
        client = ctl.client(client_id="reassign-producer")
        consumer_client = ctl.client(client_id="reassign-consumer")
        consumer = StreamConsumer(
            consumer_client,
            [f"{IN_TOPIC}:{p}:0" for p in range(parts)], group=GROUP)
        load = _Load(client, consumer, parts).start()
        # let load establish, then move shard 1 onto a NEW node while
        # producing and consuming never pause
        target = max(records // 3, CARS_PER_TICK)
        deadline = time.monotonic() + 30
        while len(load.acked) < target and time.monotonic() < deadline:
            time.sleep(0.02)
        reports.append(ctl.add_broker(shard=1,
                                      catch_up_timeout_s=slo_catch_up_s))
        target = max(2 * records // 3, 2 * CARS_PER_TICK)
        deadline = time.monotonic() + 30
        while len(load.acked) < target and time.monotonic() < deadline:
            time.sleep(0.02)
        # then drain shard 2's leader onto an existing ISR follower
        reports.append(ctl.drain_broker(shard=2))
        target = records
        deadline = time.monotonic() + 30
        while len(load.acked) < target and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        if load is not None:
            load.stop_producer()
            ends = {p: ctl.serving[ctl.pmap.shard_for(IN_TOPIC, p)]
                    .end_offset(IN_TOPIC, p) for p in range(parts)}
            load.drain_to_end(ends)
            load.stop()
        for c in (client, consumer_client):
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass
        ctl.stop()

    add, drain = reports[0], reports[1]
    catch_up = add.get("catch_up_s") or float("inf")
    invariants = load.delivery_invariants(ends) + [
        Invariant(
            "reassign_completed",
            add.get("state") == "retired" and
            drain.get("state") == "retired",
            f"add-broker -> {add.get('state')} (epoch "
            f"{add.get('epoch')}), drain-broker -> "
            f"{drain.get('state')} (epoch {drain.get('epoch')})"),
        Invariant(
            "catch_up_via_raw_fetch",
            add.get("raw_mirrored", 0) > 0,
            f"new replica raw-mirrored {add.get('raw_mirrored')} of "
            f"{add.get('records_mirrored')} records over zero-copy "
            f"RAW_FETCH"),
        Invariant(
            "catch_up_slo",
            catch_up <= slo_catch_up_s,
            f"bootstrap->ISR {catch_up:.2f}s <= {slo_catch_up_s}s"),
        Invariant(
            "consumer_disruption_slo",
            load.max_stall_s <= slo_stall_s,
            f"longest consumer stall {load.max_stall_s:.2f}s <= "
            f"{slo_stall_s}s across both moves (reconnect-sized, not "
            f"outage-sized)"),
    ]
    return DrillReport(
        drill="reassign", seed=seed, records=records,
        published=len(load.acked), scored=len(load.consumed),
        restarts={},
        slos={"catch_up_s": catch_up,
              "move_s": add.get("move_s"),
              "consumer_max_stall_s": load.max_stall_s},
        invariants=invariants, injected={})


DRILLS = {
    "double-fault": drill_double_fault,
    "reassign": drill_reassign,
}
