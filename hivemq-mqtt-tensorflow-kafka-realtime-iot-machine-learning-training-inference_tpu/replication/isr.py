"""Leader-side ISR tracking and the quorum high-water mark.

Kafka's durability contract (Kreps et al., *Kafka: a Distributed
Messaging System for Log Processing*; PAPERS.md) is primary-backup
quorum commit in the viewstamped-replication shape: the leader tracks
which replicas are *in sync* — caught up to the log end within a
staleness window — and a record is **committed** once every ISR member
holds it, i.e. once it sits below ``min(ISR fetch positions)``: the
quorum high-water mark.  ``acks=all`` producers are acked at that
point and consumers may not read past it, so an acked record survives
the death of ANY ``|ISR| - 1`` replicas, and a consumer can never
observe a record a failover would un-write.

How positions flow in this rebuild: followers (``FollowerReplica``)
stamp a replica id into their FETCH / RAW_FETCH requests (classic
Kafka carries the same field); the wire server forwards each
``(replica, topic, partition, fetch offset)`` observation here.  A
fetch at offset *O* proves the follower has durably applied every
record below *O* — its sync loop appends a batch before advancing the
cursor — which is exactly Kafka's own HWM-advance rule.

Membership rules (ARCHITECTURE §23):

- a follower starts OUT of the ISR (Kafka's add-replica semantics) and
  is **admitted** the first time its fetch position reaches the leader
  log end;
- it is **evicted** when it has not reached the log end for
  ``max_lag_s`` (``replica.lag.time.max.ms`` semantics — time-based,
  so a slow-but-moving follower under a produce burst is not flapped
  out by a count threshold);
- it is **re-admitted** by the same catch-up rule, and the quorum HWM
  is monotone through all of it (evictions can only advance it,
  admissions require the log end so they never regress it).

The leader itself is always an ISR member; ``isr_size`` therefore
counts ``1 + in-sync followers``, and an unreplicated topic behaves as
Kafka RF-1: ISR = {leader}, quorum HWM = log end, ``acks=all`` ==
``acks=1``.

Lint R15: the mutating entry points here (``register_follower`` /
``unregister_follower`` / ``evict_stale``) may be called only from
this package, and the wire-ingress pair — ``observe_fetch`` and
``wait_replicated`` — additionally from ``stream/kafka_wire.py``,
where the protocol lands.  The ISR set and the quorum HWM have one
owner, like the store's bytes (R9) and the registry's manifests (R11).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..obs import metrics as obs_metrics

#: fallback bound on one acks=all quorum wait when the request carries
#: no timeout (the classic PRODUCE timeout field is the normal source)
DEFAULT_ACK_TIMEOUT_S = 10.0


class _FollowerPos:
    """One follower's view of one partition, as the leader observes it."""

    __slots__ = ("position", "in_sync", "last_fetch", "last_caught_up")

    def __init__(self, now: float):
        self.position = -1          # -1 = never fetched this partition
        self.in_sync = False        # admitted only at first catch-up
        self.last_fetch = now
        self.last_caught_up = now   # grace anchor for eviction


class _PartState:
    __slots__ = ("hwm", "followers")

    def __init__(self, hwm: int):
        self.hwm = hwm
        self.followers: Dict[int, _FollowerPos] = {}


class ReplicationState:
    """Per-leader ISR + quorum-HWM tracker, attached as
    ``broker.replication`` (the wire server and ``Broker.fetch`` both
    consult it through that attribute).

    Args:
      broker: the leader broker whose log ends anchor catch-up checks.
      follower_ids: the configured replica set (ints; per-leader scope).
      topics: replicated topic names, or None = every topic this leader
        serves.  ``acks=all`` against a topic outside the set answers
        NOT_ENOUGH_REPLICAS — "no ISR configured" is an explicit error,
        never a silent leader-only ack on a broker that opted into
        quorum.
      min_isr: Kafka's ``min.insync.replicas`` — acks=all is refused
        (nothing appended) while ``isr_size < min_isr``.
      max_lag_s: the staleness window: a follower that has not reached
        the log end for this long leaves the ISR.
      hwm_file: optional ``store.hwm.HwmFile`` — quorum HWMs persist
        through it (throttled, off the tracking lock) and re-anchor
        the fetch ceiling at remount.
    """

    def __init__(self, broker, follower_ids=(), topics=None,
                 min_isr: int = 2, max_lag_s: float = 0.5,
                 hwm_file=None, initial_hwms=None):
        if min_isr < 1:
            raise ValueError(f"min_isr must be >= 1, got {min_isr}")
        self._broker = broker
        self._cond = threading.Condition()
        self._ids: Set[int] = set(int(i) for i in follower_ids)
        self._topics = None if topics is None else set(topics)
        self.min_isr = int(min_isr)
        self.max_lag_s = float(max_lag_s)
        self._parts: Dict[Tuple[str, int], _PartState] = {}
        self._hwm_file = hwm_file
        # anchor precedence at first touch: an explicit carry-over
        # (promotion hands the OLD quorum's marks to the new leader's
        # state — the mirrored-but-never-committed tail must stay
        # unreadable until the NEW quorum covers it), else the durable
        # checkpoint, else the current log end
        self._persisted: Dict[Tuple[str, int], int] = \
            hwm_file.load() if hwm_file is not None else {}
        if initial_hwms:
            self._persisted.update(initial_hwms)
        self._hwm_dirty = False
        self._last_persist = 0.0
        self._persist_lock = threading.Lock()
        self._last_evict_scan = 0.0

    # ------------------------------------------------------------ scope
    def covers(self, topic: str) -> bool:
        """Whether acks=all may target this topic (an ISR is
        configured for it)."""
        return bool(self._ids) and (
            self._topics is None or topic in self._topics)

    @property
    def follower_ids(self) -> Tuple[int, ...]:
        with self._cond:
            return tuple(sorted(self._ids))

    @property
    def target_replicas(self) -> int:
        """Replication factor this leader is configured for (leader
        included) — the ISR width ``under_replicated`` measures against."""
        with self._cond:
            return 1 + len(self._ids)

    # -------------------------------------------------------- membership
    def register_follower(self, replica_id: int) -> None:
        """Register a replica id (reassignment bootstrap: the new
        replica starts OUT of the ISR and is admitted when it catches
        up, Kafka's add-replica shape)."""
        with self._cond:
            self._ids.add(int(replica_id))

    def unregister_follower(self, replica_id: int) -> None:
        """Retire a replica id everywhere (drain / old-replica
        retirement).  Dropping a laggard can only ADVANCE the quorum
        HWM, so waiting producers are re-checked."""
        rid = int(replica_id)
        with self._cond:
            self._ids.discard(rid)
            for key, ps in self._parts.items():
                ps.followers.pop(rid, None)
                self._advance_hwm_locked(key, ps, self._end(*key))
            self._cond.notify_all()
        self._refresh_gauges()
        self.maybe_persist()

    # ------------------------------------------------------ observations
    def _end(self, topic: str, partition: int) -> int:
        # broker lock is taken INSIDE (never hold our cond around it —
        # Broker.fetch consults fetch_ceiling after releasing its lock,
        # so the order broker-lock -> repl-lock never happens and this
        # repl-call -> broker-lock direction... also never happens: end
        # reads occur outside the cond (see call sites)
        try:
            return self._broker.end_offset(topic, partition)
        except (KeyError, ConnectionError, IndexError):
            return 0

    def _part(self, key: Tuple[str, int]) -> _PartState:
        """Caller holds the cond.  Lazily anchor a partition: its
        initial quorum HWM is the persisted checkpoint when one exists
        (remount: the un-replicated recovered tail stays unreadable
        until followers re-mirror it), else the CURRENT log end
        (attaching replication to a live log must not un-commit its
        pre-replication history)."""
        ps = self._parts.get(key)
        if ps is None:
            anchor = self._persisted.get(key)
            # end read outside the cond by callers that can; here the
            # broker call under our cond is acceptable only because no
            # broker path calls back into us while holding its lock
            # (fetch clamps after release) — the lockcheck pins this.
            end = self._end(*key)
            ps = _PartState(end if anchor is None else min(anchor, end))
            self._parts[key] = ps
        return ps

    def observe_fetch(self, replica_id: int, topic: str, partition: int,
                      position: int) -> None:
        """Record a follower's fetch position (the wire server's
        ingress; the ONLY R15-sanctioned call site outside this
        package).  A fetch at ``position`` proves the follower holds
        every record below it; reaching the log end admits it to the
        ISR and advances the quorum HWM."""
        rid = int(replica_id)
        try:
            # never track a partition the leader does not serve: a
            # garbage part state (end 0, instant admission) would
            # poison the every-partition ISR intersection elections use
            if not 0 <= int(partition) < \
                    self._broker.topic(topic).partitions:
                return
        except KeyError:
            return
        now = time.monotonic()
        end = self._end(topic, partition)
        key = (topic, partition)
        changed = False
        with self._cond:
            if rid not in self._ids:
                return  # unregistered observer: never counts toward quorum
            ps = self._part(key)
            f = ps.followers.get(rid)
            if f is None:
                f = ps.followers[rid] = _FollowerPos(now)
            f.position = max(f.position, int(position))
            f.last_fetch = now
            if f.position >= end:
                f.last_caught_up = now
                if not f.in_sync:
                    f.in_sync = True      # ISR admission (re-admission)
                    changed = True
            self._advance_hwm_locked(key, ps, end)
            self._cond.notify_all()
        if changed:
            self._refresh_gauges()
        self.evict_stale(now=now)
        self.maybe_persist()

    def _advance_hwm_locked(self, key, ps: _PartState, end: int) -> None:
        """Caller holds the cond.  Quorum HWM = min over ISR positions
        (the leader's position is its log end), MONOTONE: a late joiner
        or an eviction can never regress what consumers already read."""
        floor = end
        for f in ps.followers.values():
            if f.in_sync:
                floor = min(floor, max(f.position, 0))
        if floor > ps.hwm:
            ps.hwm = floor
            self._hwm_dirty = True
        obs_metrics.quorum_hwm_lag.set(max(end - ps.hwm, 0),
                                       topic=key[0], partition=key[1])

    def evict_stale(self, now: Optional[float] = None) -> List[int]:
        """Drop followers that have not reached the log end within
        ``max_lag_s`` from the ISR (time-based, Kafka's
        replica.lag.time.max.ms rule).  Throttled to a quarter of the
        window so hot paths can call it freely; returns the replica ids
        evicted by THIS scan."""
        now = time.monotonic() if now is None else now
        with self._cond:
            if now - self._last_evict_scan < self.max_lag_s / 4:
                return []
            self._last_evict_scan = now
            keys = list(self._parts)
        evicted: List[int] = []
        for key in keys:
            end = self._end(*key)
            with self._cond:
                ps = self._parts[key]
                for rid, f in ps.followers.items():
                    if f.in_sync and f.position < end and \
                            now - f.last_caught_up > self.max_lag_s:
                        f.in_sync = False
                        evicted.append(rid)
                self._advance_hwm_locked(key, ps, end)
                if evicted:
                    # an eviction can only ADVANCE the quorum: wake
                    # acks=all waiters so they re-check (or fail fast
                    # on min_isr)
                    self._cond.notify_all()
        if evicted:
            self._refresh_gauges()
        return evicted

    # ----------------------------------------------------------- queries
    def isr_size(self, topic: str, partition: int) -> int:
        """In-sync replica count, leader included.  Partitions no
        follower ever fetched report the registered width optimistically
        only as 1 (the leader) — admission is earned, not assumed."""
        with self._cond:
            ps = self._parts.get((topic, partition))
            n = 0 if ps is None else \
                sum(1 for f in ps.followers.values() if f.in_sync)
        return 1 + n

    def isr_follower_ids(self, topic: Optional[str] = None) -> Set[int]:
        """Replica ids in sync for EVERY tracked partition (of `topic`,
        or of everything) — the leader-election candidate set: a
        follower missing one partition's tail cannot serve that
        partition at identical offsets."""
        with self._cond:
            keys = [k for k in self._parts
                    if topic is None or k[0] == topic]
            if not keys:
                # nothing tracked = no follower ever fetched: nobody
                # has PROVEN sync, so nobody may be promoted (election
                # is evidence-based, never optimistic)
                return set()
            out: Optional[Set[int]] = None
            for k in keys:
                ins = {rid for rid, f in self._parts[k].followers.items()
                       if f.in_sync}
                out = ins if out is None else (out & ins)
            return out or set()

    def quorum_hwm(self, topic: str, partition: int) -> int:
        with self._cond:
            ps = self._parts.get((topic, partition))
            if ps is not None:
                return ps.hwm
        # untracked: anchor now (the read barrier must exist before the
        # first follower fetch, or early consumers read the tail)
        with self._cond:
            return self._part((topic, partition)).hwm

    def fetch_ceiling(self, topic: str, partition: int) -> Optional[int]:
        """The first offset consumers may NOT read (the quorum HWM),
        or None when this topic is not under replication (unbounded —
        the pre-replication behavior)."""
        if not self.covers(topic):
            return None
        return self.quorum_hwm(topic, partition)

    def hwm_snapshot(self) -> Dict[Tuple[str, int], int]:
        """Current quorum HWMs per tracked partition — what a promotion
        carries into the new leader's state (read-only; R15 untouched)."""
        with self._cond:
            return {k: ps.hwm for k, ps in self._parts.items()}

    def positions(self, topic: str, partition: int) -> Dict[int, int]:
        """Follower fetch positions (diagnostics / election tiebreaks)."""
        with self._cond:
            ps = self._parts.get((topic, partition))
            if ps is None:
                return {}
            return {rid: f.position for rid, f in ps.followers.items()}

    # ------------------------------------------------------- quorum wait
    def wait_replicated(self, topic: str, partition: int,
                        next_offset: int,
                        timeout_s: float = DEFAULT_ACK_TIMEOUT_S) -> bool:
        """Block until the quorum HWM reaches ``next_offset`` (the
        acks=all ack point for a batch ending at ``next_offset - 1``)
        or the timeout lapses.  The wait loop runs the eviction scan,
        so a dead follower stalls an ack for at most ``max_lag_s``
        before the quorum re-forms without it."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while True:
            with self._cond:
                ps = self._part((topic, partition))
                if ps.hwm >= next_offset:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, self.max_lag_s / 4
                                    if self.max_lag_s > 0 else 0.05))
            self.evict_stale()
        self.maybe_persist()
        return True

    def await_isr(self, size: int, topic: str, partition: int = 0,
                  timeout_s: float = 10.0) -> bool:
        """Block until ``isr_size(topic, partition) >= size`` — drill/
        test convenience for ISR formation."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.isr_size(topic, partition) >= size:
                return True
            time.sleep(0.01)
        return False

    # ------------------------------------------------------- persistence
    def maybe_persist(self, min_interval_s: float = 0.05) -> None:
        """Throttled HWM checkpoint write, OFF the tracking lock (file
        I/O must never sit on the quorum wait path)."""
        if self._hwm_file is None:
            return
        now = time.monotonic()
        with self._persist_lock:
            if not self._hwm_dirty or \
                    now - self._last_persist < min_interval_s:
                return
            with self._cond:
                snap = {k: ps.hwm for k, ps in self._parts.items()}
                self._hwm_dirty = False
            self._last_persist = now
        try:
            self._hwm_file.store(snap)
        except OSError:
            with self._cond:
                self._hwm_dirty = True  # retry on the next advance

    def flush(self) -> None:
        """Unthrottled checkpoint (shutdown path)."""
        if self._hwm_file is None:
            return
        with self._cond:
            snap = {k: ps.hwm for k, ps in self._parts.items()}
            self._hwm_dirty = False
        try:
            self._hwm_file.store(snap)
        except OSError:
            pass

    # ------------------------------------------------------------ gauges
    def _refresh_gauges(self) -> None:
        with self._cond:
            rows = [(k, sum(1 for f in ps.followers.values() if f.in_sync))
                    for k, ps in self._parts.items()]
            target = 1 + len(self._ids)
        under = 0
        for (t, p), in_sync in rows:
            size = 1 + in_sync
            obs_metrics.isr_size.set(size, topic=t, partition=p)
            if size < target:
                under += 1
        obs_metrics.under_replicated.set(under)
