"""ReplicaSet — one leader plus N followers as a managed quorum unit.

The PR 4/6 machinery gave the stream plane exactly one fenced follower
per leader; this manager generalises it to the reference's RF-3 shape:
a leader broker behind its wire server, ``n_followers`` pull replicas
(each a ``FollowerReplica`` stamping its replica id into FETCH /
RAW_FETCH so the leader's ``ReplicationState`` tracks it), quorum
durability (acks=all at the quorum high-water mark, consumer reads
bounded by it), and **ISR-restricted leader election**: a failover may
only promote a follower that is in sync for every partition — at
epoch+1, through the same Topology cell publish the whole failover
stack already consumes.

Elasticity primitives (used by the cluster reassignment state
machine): ``add_follower`` joins a brand-new replica live (it
bootstraps from the segment log over zero-copy RAW_FETCH, starting
OUT of the ISR and earning admission at catch-up — Kafka's
add-replica shape) and ``retire_follower`` removes one (leaving the
ISR first, so the quorum re-forms without it before it stops
answering).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..stream.broker import Broker
from ..stream.kafka_wire import KafkaWireServer
from ..stream.replica import FollowerReplica
from .isr import ReplicationState

#: process-wide replica-id allocator: ids only need to be unique per
#: leader, but globally-unique ids make drill logs unambiguous
_NEXT_RID = itertools.count(1)


def next_replica_id() -> int:
    return next(_NEXT_RID)


class ReplicaSet:
    """Build (or adopt) a leader and run N ISR-tracked followers.

    Args:
      leader_broker / leader_server: adopt an existing pair (the
        cluster controller's shards); both None builds a fresh
        in-memory leader + wire server.
      n_followers: replicas to build at construction (RF - 1).
      min_isr: acks=all refusal threshold (leader included).
      max_lag_s: ISR staleness window.
      topics / groups / partition_filter / store config: forwarded to
        each follower (shard followers mirror only their shard).
      topology: the shard's live (leader, epoch) cell — followers'
        leader connections re-resolve through it, so reassignment only
        has to publish the cell.
      follower_local_factory: () -> Broker for each follower's local
        log (ShardBroker in a cluster); None = plain in-memory Broker.
      hwm_file: store-owned HWM checkpoint for the leader (durable
        remount re-anchors the read barrier from it).
    """

    def __init__(self, leader_broker: Optional[Broker] = None,
                 leader_server: Optional[KafkaWireServer] = None,
                 n_followers: int = 2, min_isr: int = 2,
                 max_lag_s: float = 0.5, host: str = "127.0.0.1",
                 topics: Optional[List[str]] = None,
                 groups: Tuple[str, ...] = (),
                 partition_filter=None, topology=None,
                 follower_local_factory=None, hwm_file=None,
                 leader_addr: Optional[str] = None,
                 follower_port_fn=None,
                 poll_interval_s: float = 0.01):
        own_leader = leader_broker is None
        self.leader = Broker() if own_leader else leader_broker
        if leader_server is None:
            self.server = KafkaWireServer(self.leader, host=host)
            if own_leader:
                self.server.start()
        else:
            self.server = leader_server
        self._host = host
        self._topics = topics
        self._groups = tuple(groups)
        self._owns = partition_filter
        self._local_factory = follower_local_factory
        #: idle cadence of each follower's sync loop — it bounds the
        #: acks=all ack latency floor (a produce is acked when the
        #: followers' NEXT fetch passes it), so the quorum default is
        #: tighter than FollowerReplica's standalone 0.05
        self._poll_interval_s = float(poll_interval_s)
        #: j-th follower's listen port (deployments pin port ranges);
        #: None = ephemeral
        self._port_fn = follower_port_fn
        self._built = 0
        self._leader_addr = leader_addr or \
            f"{host}:{self.server.port}"
        # followers ALWAYS follow a topology cell, so survivors of a
        # promotion re-resolve the new leader instead of reconnect-
        # looping against the dead one's address forever.  An external
        # cell (the cluster's PartitionMap) is caller-published; a
        # standalone set owns a private cell and publishes it itself
        # at promote().
        from ..supervise.topology import Topology

        self._own_topology = topology is None
        self._topology = topology if topology is not None \
            else Topology(self._leader_addr)
        self.state = ReplicationState(
            self.leader, follower_ids=(), topics=topics,
            min_isr=min_isr, max_lag_s=max_lag_s, hwm_file=hwm_file)
        self.leader.replication = self.state
        #: replica id -> live follower (insertion-ordered)
        self.followers: Dict[int, FollowerReplica] = {}
        #: promoted ex-followers (now serving leaders) still owned by
        #: this set for shutdown purposes
        self._promoted: List[FollowerReplica] = []
        self._lock = threading.Lock()
        for _ in range(int(n_followers)):
            self._build_follower()

    # ---------------------------------------------------------- builders
    def _build_follower(self, store_dir: Optional[str] = None,
                        local: Optional[Broker] = None
                        ) -> Tuple[int, FollowerReplica]:
        rid = next_replica_id()
        if local is None and store_dir is None and \
                self._local_factory is not None:
            local = self._local_factory()
        port = self._port_fn(self._built) if self._port_fn else 0
        self._built += 1
        rep = FollowerReplica(
            self._leader_addr, topics=self._topics, groups=self._groups,
            host=self._host, port=port, partition_filter=self._owns,
            local=local, store_dir=store_dir, replica_id=rid,
            topology=self._topology,
            poll_interval_s=self._poll_interval_s)
        with self._lock:
            self.followers[rid] = rep
        self.state.register_follower(rid)
        return rid, rep

    # --------------------------------------------------------- lifecycle
    def start(self, sync: str = "thread") -> "ReplicaSet":
        """Start every follower (``sync="thread"`` runs their background
        sync loops; ``"manual"`` serves only — step with sync_once)."""
        for rep in list(self.followers.values()):
            if sync == "thread":
                rep.start()
            else:
                rep.server.start()
        return self

    def stop(self) -> None:
        for rep in list(self.followers.values()) + self._promoted:
            try:
                rep.stop()
            except (OSError, RuntimeError):
                pass
        self.state.flush()

    def __enter__(self) -> "ReplicaSet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ----------------------------------------------------------- driving
    def sync_once(self) -> int:
        """Step every live, unpromoted follower one replication round
        (deterministic runners)."""
        copied = 0
        for rep in list(self.followers.values()):
            if not rep.promoted:
                copied += rep.sync_once()
        return copied

    def await_isr(self, size: Optional[int] = None, topic: str = "",
                  partition: int = 0, timeout_s: float = 10.0) -> bool:
        """Block until the ISR reaches `size` (default: full width).
        With background sync threads the followers admit themselves;
        manual mode callers interleave sync_once()."""
        want = size if size is not None else 1 + len(self.followers)
        if not topic:
            names = self._topics or self.leader.topics()
            topic = names[0] if names else ""
        return self.state.await_isr(want, topic, partition, timeout_s)

    # ---------------------------------------------------------- election
    def elect(self, exclude: Tuple[int, ...] = ()) -> int:
        """Pick the failover target: an ISR member (in sync for EVERY
        partition), highest fetch position first (tiebreak: lowest id,
        deterministic).  Raises RuntimeError when no ISR member is
        available — promoting an out-of-sync follower would serve a log
        with acked records missing, the exact loss acks=all exists to
        rule out."""
        isr = self.state.isr_follower_ids() - set(exclude)
        live = [rid for rid in isr if rid in self.followers
                and not self.followers[rid].promoted]
        if not live:
            raise RuntimeError(
                "no in-sync replica available to elect: refusing to "
                "promote an out-of-sync follower (acked records would "
                "be lost)")
        # every ISR member is caught up by definition; positions break
        # the tie toward the longest log anyway (paranoia over trust)
        def score(rid: int) -> tuple:
            total = 0
            for t in (self._topics or self.leader.topics()):
                try:
                    parts = self.leader.topic(t).partitions
                except KeyError:
                    continue
                for p in range(parts):
                    total += max(self.state.positions(t, p)
                                 .get(rid, 0), 0)
            return (total, -rid)

        return max(live, key=score)

    def promote(self, epoch: int,
                rid: Optional[int] = None) -> Tuple[int, str]:
        """ISR-restricted promotion at `epoch`: elect (or take `rid`,
        verifying ISR membership), convert that follower into the
        serving leader, install a fresh ReplicationState on it for the
        REMAINING followers (they re-point through the topology cell),
        and return ``(rid, serving_address)`` for the cell publish."""
        if rid is None:
            rid = self.elect()
        elif rid not in self.state.isr_follower_ids():
            raise RuntimeError(
                f"replica {rid} is not in the ISR: refusing the "
                f"promotion (leader election is ISR-restricted)")
        rep = self.followers[rid]
        addr = rep.promote(epoch)
        with self._lock:
            self.followers.pop(rid, None)
            self._promoted.append(rep)
            remaining = tuple(self.followers)
        # the promoted log now LEADS: quorum tracking moves onto it,
        # CARRYING the old quorum's HWMs — the tail this follower
        # mirrored beyond the committed mark exists on one copy only
        # until the NEW quorum covers it, so it must stay unreadable
        # (the read-barrier invariant survives the failover).  Durable
        # promoted logs get their OWN checkpoint file (the old leader's
        # lives in a retired store dir).
        from ..store.hwm import hwm_file_for

        store = getattr(rep.local, "store", None)
        self.state = ReplicationState(
            rep.local, follower_ids=remaining, topics=self._topics,
            min_isr=self.state.min_isr, max_lag_s=self.state.max_lag_s,
            hwm_file=hwm_file_for(getattr(store, "dir", None)),
            initial_hwms=self.state.hwm_snapshot())
        rep.local.replication = self.state
        self.leader = rep.local
        self.server = rep.server
        self._leader_addr = addr
        if self._own_topology:
            # standalone set: publish the new term ourselves so the
            # remaining followers' connections re-resolve here (a
            # cluster cell is published by the controller instead)
            self._topology.publish(addr, epoch)
        return rid, addr

    # --------------------------------------------------------- elasticity
    def add_follower(self, store_dir: Optional[str] = None,
                     local: Optional[Broker] = None,
                     sync: str = "thread") -> int:
        """Join a brand-new replica live: it bootstraps the whole log
        over zero-copy RAW_FETCH mirroring, OUT of the ISR until its
        first catch-up (Kafka's add-replica semantics), then counts
        toward quorum.  Returns its replica id."""
        rid, rep = self._build_follower(store_dir=store_dir, local=local)
        if sync == "thread":
            rep.start()
        else:
            rep.server.start()
        return rid

    def retire_follower(self, rid: int, timeout_s: float = 10.0) -> None:
        """Remove a replica: it leaves the ISR FIRST (the quorum
        re-forms without it while it still answers), then stops."""
        self.state.unregister_follower(rid)
        with self._lock:
            rep = self.followers.pop(rid, None)
        if rep is not None:
            rep.stop()

    def kill_follower(self, rid: int) -> None:
        """Abrupt follower death (drills): the server dies mid-service,
        the ISR only learns through the staleness window — exactly a
        crashed replica process."""
        rep = self.followers.get(rid)
        if rep is None:
            return
        rep._stop.set()
        try:
            rep.server.kill()
        except OSError:
            pass

    # ------------------------------------------------------------- state
    def caught_up(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(rep.promoted or self._follower_lag(rep) == 0
                   for rep in list(self.followers.values())):
                return True
            time.sleep(0.02)
        return False

    def _follower_lag(self, rep: FollowerReplica) -> int:
        try:
            return sum(rep.lag().values())
        except (OSError, RuntimeError, KeyError):
            return 1  # unknown counts as behind

    def describe(self) -> dict:
        """Operator-facing snapshot (the admin `status` verb)."""
        topics = self._topics or self.leader.topics()
        isr: Dict[str, int] = {}
        for t in topics:
            try:
                parts = self.leader.topic(t).partitions
            except KeyError:
                continue
            for p in range(parts):
                isr[f"{t}:{p}"] = self.state.isr_size(t, p)
        return {
            "leader": self._leader_addr,
            "followers": sorted(self.followers),
            "isr_follower_ids": sorted(self.state.isr_follower_ids()),
            "isr_size": isr,
            "min_isr": self.state.min_isr,
        }
