"""Online shard reassignment — the elastic half of ISSUE 14.

The cluster's partition→shard policy is a pure function (``p % N``),
so the reassignment unit is the SHARD: moving capacity means moving a
shard's leadership (and its data) onto a different node, not renaming
partitions.  That is exactly what ``add-broker`` / ``drain-broker``
do, as a five-state machine an operator can watch:

    BOOTSTRAPPING   a new replica mirrors the shard's segment log over
                    zero-copy RAW_FETCH (batches append verbatim —
                    catch-up runs at the data plane's raw rate, not
                    per-record Python), OUT of the ISR
    CATCHING_UP     the mirror is live and lag is shrinking; the
                    replica earns ISR admission at its first catch-up
    IN_SYNC         the replica is an ISR member: it now bounds the
                    quorum HWM, so everything acked from here on is on
                    the new node too
    MOVED           leadership moved: the target was promoted at
                    epoch+1 and the shard's Topology cell republished —
                    clients re-resolve on their next reconnect/fence,
                    consumers keep their cursors (offsets are identical
                    by the mirror contract); remaining followers
                    re-point through the same cell
    RETIRED         the old replica retired: the previous leader's
                    server is dead (it would answer FENCED anyway —
                    its epoch is stale) and its broker closed

No step disrupts consumers: reads keep flowing from the old leader
until MOVED, and from the new one after — the only client-visible
event is one reconnect, which every consumer already treats as a
failover (rewind-to-committed redelivery, exact-once by offsets).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

#: state-machine vocabulary (ARCHITECTURE §23 diagram)
BOOTSTRAPPING = "bootstrapping"
CATCHING_UP = "catching_up"
IN_SYNC = "in_sync"
MOVED = "moved"
RETIRED = "retired"
FAILED = "failed"


@dataclasses.dataclass
class ShardReassignment:
    """One shard's move, with the numbers the drill SLOs bind on."""

    shard: int
    target_rid: Optional[int] = None
    state: str = BOOTSTRAPPING
    started_mono: float = dataclasses.field(
        default_factory=time.monotonic)
    catch_up_s: Optional[float] = None       # bootstrap -> ISR admission
    move_s: Optional[float] = None           # bootstrap -> cell publish
    records_mirrored: int = 0
    raw_mirrored: int = 0                    # via the zero-copy leg
    old_leader: str = ""
    new_leader: str = ""
    epoch: Optional[int] = None
    error: str = ""

    def advance(self, state: str) -> None:
        self.state = state
        now = time.monotonic()
        if state == IN_SYNC and self.catch_up_s is None:
            self.catch_up_s = now - self.started_mono
        if state == MOVED and self.move_s is None:
            self.move_s = now - self.started_mono

    def fail(self, error: str) -> None:
        self.state = FAILED
        self.error = error

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("started_mono", None)
        return d
