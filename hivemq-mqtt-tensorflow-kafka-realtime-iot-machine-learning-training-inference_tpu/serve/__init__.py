from .scorer import StreamScorer, format_prediction  # noqa: F401
