"""Per-car failure detection — the predictive-maintenance deliverable.

The reference exists to detect failing CARS, not merely anomalous rows
(reference README.md:7,19: "predictive maintenance … detect sensor
anomalies"), yet its pipeline stops at per-record reconstruction error.
Per-record detection is noise-limited: the car autoencoder's irreducible
error (unpredictable sensors: air temp, accelerometers, per-car tire
baselines) overlaps the failure modes' per-record signal, capping
per-record F1 near 0.6 (ARCHITECTURE.md; the e2e bench measures it live).
A car's failure, however, PERSISTS: every record it emits is drawn from
the shifted distribution, so averaging per-record errors over a car's
recent records shrinks the noise by ~1/√N while the failure signal stays
put — the per-car separation is near-total after a few dozen records.

`CarHealthDetector` maintains an exponential moving average of
reconstruction error per car key (the message key: MQTT topic → bridge →
KSQL pass-through), raises an ALERT when a car's EMA crosses the
threshold (after a minimum evidence count), and clears it with hysteresis
at 70% of the threshold.  Alert transitions are emitted as JSON records
onto a stream topic — the digital-twin feed a MongoDB sink consumes, car
id as the record key, same as the reference's twin pipeline shape.

Detection envelope (measured against the scenario generator's injected
modes; round-5 numbers, 120-car offline fleet, 10-epoch model):

- PARITY normalization, mean-MSE path: healthy per-car EMAs span
  ~0.17–0.35 (per-car quirks: tire baselines, firmware, unpredictable
  sensors) — threshold 0.38 sits just above.  High-magnitude faults
  (tire blowout: EMA ≈ 0.41+) alert cleanly; battery sag moves the
  18-feature mean by ~2% and is INVISIBLE — its whole signature
  (voltage sag + current spike) lives in the two fields parity
  normalization zeroes (the reference's TODO fields).
- FULL normalization (core/normalize.FULL_NORMALIZER): healthy mean-EMA
  band rises to ~0.22–0.42 (four more live features carry irreducible
  error) — the mean-MSE threshold for full-norm deployments sits near
  0.6 offline.
- Per-feature ERROR heads (feature_heads=True, full norm): battery sag
  is a z≈700–900 outlier on BATTERY_VOLTAGE's reconstruction error and
  tire blowout z≈400 on its tire's — the model predicts those features
  from their correlates (voltage from battery %, tires from their
  baseline), so a conditional residual is razor-sharp.  Healthy cars
  reach error-z≈13 on quirk features (per-car tire baselines
  reconstruct persistently badly — the "heavy healthy tails" that
  killed round 4's absolute per-feature thresholds).  feature_z=30
  sits in the ~30× gap; feature_floor=0.1 gates features whose fleet
  MAD is numerical dust.  The engine-vibration mode is INVISIBLE to
  the error head: vibration is inherently unpredictable (speed × a
  per-row random factor), its healthy error spread is as wide as the
  fault's excess (measured z≈2).
- Per-feature VALUE-DRIFT heads (same flag): per-car EMAs of the
  normalized feature VALUES against fleet median/MAD, two-sided,
  model-free.  The vibration fault is a 5.8-z value outlier vs healthy
  max 2.7 (drift_z=4.5 splits the gap); tire blowout 9.2.  Features
  whose fleet MAD is ~0 (control-unit firmware: categorical, a
  minority config is not a failure) are masked.  Both heads'
  statistics are CROSS-SECTIONAL and recomputed every update, so model
  hot-swaps — which shift every car together — cancel instead of
  page-storming (the drift head, having no model, is immune outright).
- TAIL GUARD (live-measured): under continuous 1-epoch/round training
  the error head's MAD scale under-covers structurally heavy-tailed
  features — battery % reconstructs persistently worse for cars at the
  charge-distribution edges (healthy error-z up to 235; 55 false
  alerts in a 200-car live session, every one on BATTERY_PERCENTAGE).
  Each head's alert bar therefore also clears tail_k× the fleet's own
  p90 excess per feature; with that guard the same live session
  detects 8/8 injected failing cars at 0 false alerts across the whole
  sweep of tested thresholds (feature_z 20–30, tail_k 3–6, measured on
  recorded head-state snapshots).
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

import numpy as np

from ..obs import metrics as obs_metrics


class CarHealthDetector:
    """EMA-per-key anomaly detector with hysteresis and alert records.

    Args:
      threshold: EMA level that raises an alert — default 0.38 sits just
        above the measured healthy-fleet EMA band (module docstring).
        "auto" calibrates from the fleet itself (median + k·(p75−median)
        over warmed-up cars, recomputed as the stream flows); it needs a
        STABLE model — under continuous hot-swapping the per-car EMA
        spread collapses to the swap cadence and the quantile margin
        under-estimates, so live deployments with fast retrain loops
        should pin the threshold to their measured healthy band instead.
      alpha: EMA weight per record (effective window ≈ 1/alpha records).
      min_records: evidence required before a car may alert (a single
        outlier row must not page an operator).
      clear_ratio: hysteresis — an alerted car clears below
        threshold×clear_ratio (flapping at the boundary is operator spam).
      auto_k / auto_floor: the auto calibration's margin multiplier and
        minimum threshold.
    """

    #: recompute the auto threshold every this many update() calls
    AUTO_EVERY = 50
    #: steady-state cadence for the feature-head fleet calibration: with
    #: alpha 0.05 the EMAs move ≤ ~18% of any shift within 4 updates and
    #: the excess floors absorb that; a model swap (the one event that
    #: shifts the whole fleet at once) triggers a hot window of
    #: per-update recalibration via notify_model_swap()
    RECAL_EVERY = 4

    def __init__(self, threshold=0.38, alpha: float = 0.05,
                 min_records: int = 20, clear_ratio: float = 0.7,
                 auto_k: float = 4.5, auto_floor: float = 0.3,
                 feature_heads: bool = False, feature_z: float = 30.0,
                 feature_floor: float = 0.1, feature_tail_k: float = 4.0,
                 drift_z: float = 4.5, drift_floor: float = 0.1,
                 drift_tail_k: float = 2.5,
                 feature_names: Optional[list] = None):
        self.auto = threshold == "auto"
        self.threshold = auto_floor if self.auto else float(threshold)
        self.auto_k = auto_k
        self.auto_floor = auto_floor
        #: auto mode must not alert before the first successful fleet
        #: calibration — the floor is a lower BOUND, not a threshold
        self._calibrated = not self.auto
        self._updates = 0
        self.alpha = alpha
        self.min_records = min_records
        self.clear_ratio = clear_ratio
        self.ema: Dict[bytes, float] = {}
        self.count: Dict[bytes, int] = {}
        self.alerted: Dict[bytes, float] = {}  # key → alert wall time
        self.alert_source: Dict[bytes, str] = {}  # key → what fired
        self.transitions: list = []  # (t, key, "ALERT"|"CLEAR", ema, src)
        #: per-FEATURE error heads (round 5): a low-magnitude fault that
        #: barely moves the 18-feature MEAN error (battery sag ≈ +2% MSE
        #: under parity normalization) is a huge outlier on ITS feature's
        #: error — per-car per-feature EMAs are scored as robust
        #: cross-sectional z against the fleet (median/MAD per feature).
        #: Cross-sectional is the property the round-4 per-feature
        #: variants lacked: a model hot-swap shifts every car's error
        #: together, so the fleet median/MAD track it and the z of a
        #: healthy car stays put, where absolute per-feature thresholds
        #: collapsed (measured and rejected, round 4).  Feeds on the
        #: per-row per-feature squared errors the scorer already computes.
        self.feature_heads = bool(feature_heads)
        self.feature_z = float(feature_z)
        #: absolute excess floor (normalized-units²): a feature whose MAD
        #: is tiny (well-reconstructed) would otherwise turn numerical
        #: dust into huge z scores
        self.feature_floor = float(feature_floor)
        #: TAIL GUARD (the live-measured failure mode of pure MAD-z): a
        #: feature can be heavy-tailed across healthy cars for structural
        #: reasons — live continuous models reconstruct battery %
        #: persistently worse for cars at the charge-distribution edges
        #: (z 30–235 on a MAD scale, 55 false alerts in a 200-car live
        #: session).  The alert bar therefore also clears tail_k× the
        #: fleet's own p90 excess per feature: where the healthy tail is
        #: wide the bar widens with it, where it is tight (voltage given
        #: battery: the fault signature) the MAD term still rules.  p90
        #: tolerates un-alerted failing cars in the calibration set
        #: (≤5% contamination cannot reach the 90th percentile).
        self.feature_tail_k = float(feature_tail_k)
        self.drift_tail_k = float(drift_tail_k)
        self.feature_names = feature_names
        #: value-DRIFT head: per-car EMAs of the normalized feature
        #: values themselves, scored two-sided against fleet median/MAD.
        #: Model-free — catches faults on features the model cannot
        #: predict (engine vibration), immune to hot-swaps by
        #: construction.  Fleet-constant/categorical features (MAD ≈ 0:
        #: firmware) are masked — a minority config is not a failure.
        self.drift_z = float(drift_z)
        self.drift_floor = float(drift_floor)
        self._recal_hot = 0
        self.fema: Dict[bytes, np.ndarray] = {}   # key → [F] error EMAs
        self.vema: Dict[bytes, np.ndarray] = {}   # key → [F] value EMAs
        self._fmed: Optional[np.ndarray] = None   # fleet median per feat
        self._fsig: Optional[np.ndarray] = None   # 1.4826·MAD + eps
        self._ftail: Optional[np.ndarray] = None  # p90 healthy excess
        self._vmed: Optional[np.ndarray] = None
        self._vsig: Optional[np.ndarray] = None
        self._vtail: Optional[np.ndarray] = None  # p90 |deviation|
        self._vlive: Optional[np.ndarray] = None  # non-categorical mask
        self._m_alerts = obs_metrics.default_registry.counter(
            "car_health_alerts_total", "per-car failure alerts raised")
        self._m_active = obs_metrics.default_registry.gauge(
            "car_health_alerts_active", "cars currently in ALERT state")

    # ------------------------------------------------------------ update
    def update(self, keys: np.ndarray, errs: np.ndarray,
               ferrs: Optional[np.ndarray] = None,
               fvals: Optional[np.ndarray] = None) -> list:
        """Fold one scored batch's (keys [n] bytes, per-row errors [n],
        optional per-feature errors [n, F] and normalized feature values
        [n, F]) into the per-car state; returns this call's alert
        transitions as [(t, key, state, ema, source)] — the same 5-tuples
        recorded in self.transitions, so publishing them downstream
        carries the transition's own timestamp and which signal fired.
        Vectorized per distinct car: a batch holds many rows of few cars,
        so the group-by does the heavy lifting in numpy and the Python
        loop runs per CAR, not per row."""
        if len(keys) == 0:
            return []
        self._updates += 1
        if self.auto and (not self._calibrated
                          or self._updates % self.AUTO_EVERY == 0):
            self._recalibrate_mse()
        if self.feature_heads and (
                self._fmed is None or self._recal_hot > 0
                or self._updates % self.RECAL_EVERY == 0):
            # the z scores are only cross-sectional if the fleet
            # median/scale are contemporaneous with the EMAs they
            # normalize — at the AUTO_EVERY cadence a model hot-swap
            # mid-window raised every car's error against a stale median
            # and page-stormed (pinned by
            # test_feature_heads_survive_fleetwide_error_shift).
            # Steady-state: every RECAL_EVERY updates (the floors absorb
            # the ≤4-update fold drift); post-swap: per-update for the
            # fold transient (notify_model_swap)
            self._recal_hot = max(0, self._recal_hot - 1)
            self._recalibrate_features()
        order = np.argsort(keys, kind="stable")
        sk, se = keys[order], errs[order]
        sf = ferrs[order] if ferrs is not None else None
        sv = fvals[order] if fvals is not None else None
        # keyless records carry no car identity: drop them before
        # grouping so they can't pollute the per-car state either
        nonempty = sk != b""
        if not nonempty.all():
            sk, se = sk[nonempty], se[nonempty]
            sf = sf[nonempty] if sf is not None else None
            sv = sv[nonempty] if sv is not None else None
            if len(sk) == 0:
                return []
        uniq, starts = np.unique(sk, return_index=True)
        counts = np.append(starts[1:], len(sk)) - starts
        bounds = np.append(starts, len(sk))
        ckeys = [bytes(u) for u in uniq]
        # segmented closed-form EMA folds + whole-batch head evaluation
        # (the per-car python loop was the detector's hot spot: ~8 numpy
        # calls per car per batch cost ~1/3 of the scorer's throughput)
        fe_mat = (self._fold_all(self.fema, ckeys, sf, starts, counts)
                  if self.feature_heads and sf is not None else None)
        ve_mat = (self._fold_all(self.vema, ckeys, sv, starts, counts)
                  if self.feature_heads and sv is not None else None)
        # head evidence is unusable through the post-swap fold transient
        # (suppressed below): skip computing it at all
        fire_src = ([None] * len(ckeys) if self._recal_hot > 0 else
                    self._head_sources_batch(fe_mat, ve_mat, len(ckeys)))
        out = []
        now = time.time()
        for ci, (lo, hi) in enumerate(zip(bounds[:-1], bounds[1:])):
            k = ckeys[ci]
            e = self.ema.get(k)
            # fold the car's rows in arrival order: EMA of the sequence
            # (a closed form exists but per-row exactness matters for
            # parity with a record-at-a-time consumer)
            for x in se[lo:hi]:
                e = float(x) if e is None else \
                    e + self.alpha * (float(x) - e)
            self.ema[k] = e
            self.count[k] = self.count.get(k, 0) + int(hi - lo)
            # head evidence is SUPPRESSED through the post-swap fold
            # transient (_recal_hot > 0): within one update the fleet
            # calibration is computed before the folds while the z is
            # evaluated after them, so a large model swap makes every
            # freshly-folded car an apparent outlier against the
            # pre-fold median — evidence that straddles a model
            # boundary must neither PAGE (new alerts, pinned by
            # test_swap_notification_recalibrates_through_the_fold_
            # transient) nor HOLD (clears — a transient fire must not
            # starve an alerted car's recovery through every hot
            # window).  The mse path keeps its own-car threshold either
            # way.
            hot = self._recal_hot > 0
            src_fire = fire_src[ci]
            if k not in self.alerted:
                src = None
                if self._calibrated and \
                        self.count[k] >= self.min_records and \
                        e > self.threshold:
                    src = "mse"
                elif src_fire is not None and \
                        self.count[k] >= self.min_records:
                    src = src_fire
                if src is not None:
                    self.alerted[k] = now
                    self.alert_source[k] = src
                    self.transitions.append((now, k, "ALERT", e, src))
                    out.append((now, k, "ALERT", e, src))
                    self._m_alerts.inc()
            else:
                # hysteresis applies to the path that FIRED; a head-alerted
                # car whose healthy mean EMA happens to sit above
                # threshold×clear_ratio must still clear once the heads go
                # quiet (requiring the mse hysteresis bar unconditionally
                # left such cars in ALERT forever), but never while its
                # mean error is above the alert threshold itself
                src0 = self.alert_source.get(k, "")
                if hot and src0 != "mse":
                    continue  # defer: head-sourced state frozen while hot
                mse_bar = (self.threshold * self.clear_ratio
                           if src0 == "mse" else self.threshold)
                # during a hot window head evidence can neither page nor
                # hold: treat it as quiet for mse-sourced clears
                quiet_heads = hot or self._head_source(
                    k, ratio=self.clear_ratio) is None
                if e < mse_bar and quiet_heads:
                    src = self.alert_source.pop(k, "")
                    del self.alerted[k]
                    self.transitions.append((now, k, "CLEAR", e, src))
                    out.append((now, k, "CLEAR", e, src))
        self._m_active.set(len(self.alerted))
        return out

    def _fold_all(self, store: Dict[bytes, np.ndarray], ckeys: list,
                  rows: np.ndarray, starts: np.ndarray,
                  counts: np.ndarray) -> np.ndarray:
        """Closed-form EMA fold of EVERY car's rows in one segmented
        pass — the exact same recurrence as the scalar per-row loop,
        vectorized over cars and features (fp association differs only).
        Per-row weight: alpha·(1−alpha)^(m−1−j) within a car's segment;
        a NEW car's first row seeds the EMA, so its weight is
        (1−alpha)^(m−1).  Returns the [C, F] post-fold matrix (also
        written back to the store)."""
        rows = rows.astype(np.float64)
        n = len(rows)
        pos = np.arange(n) - np.repeat(starts, counts)
        m = np.repeat(counts, counts)
        w = self.alpha * (1.0 - self.alpha) ** (m - 1 - pos)
        old = [store.get(k) for k in ckeys]
        is_new = np.array([o is None for o in old], bool)
        if is_new.any():
            w[starts[is_new]] = (1.0 - self.alpha) ** \
                (counts[is_new] - 1)
        wsum = np.add.reduceat(w[:, None] * rows, starts, axis=0)
        decay = (1.0 - self.alpha) ** counts
        out = np.empty((len(ckeys), rows.shape[1]))
        for i, k in enumerate(ckeys):
            fe = wsum[i] if is_new[i] else old[i] * decay[i] + wsum[i]
            out[i] = fe
            store[k] = fe
        return out

    def _error_bar(self) -> np.ndarray:
        """The error head's per-feature alert bar — THE single source of
        truth shared by the batched alert path and the scalar clear path
        (diverging copies would let cars alert under one bar and clear
        under another)."""
        return np.maximum(np.maximum(
            self.feature_z * self._fsig,
            self.feature_tail_k * self._ftail), self.feature_floor)

    def _drift_bar(self) -> np.ndarray:
        return np.maximum(np.maximum(
            self.drift_z * self._vsig,
            self.drift_tail_k * self._vtail), self.drift_floor)

    def _head_sources_batch(self, fe_mat, ve_mat, n_cars: int) -> list:
        """Whole-batch head evaluation: [C] list of firing-source strings
        (None = no head fires).  Same rule as _head_source at ratio 1,
        computed as two matrix comparisons instead of per-car calls."""
        src = [None] * n_cars
        if fe_mat is not None and self._fmed is not None:
            excess = fe_mat - self._fmed
            fire = excess > self._error_bar()
            for i in np.nonzero(fire.any(axis=1))[0]:
                z = np.where(fire[i], excess[i] / self._fsig, 0.0)
                j = int(np.argmax(z))
                src[i] = f"feature:{self._name_of(j)} z={z[j]:.1f}"
        if ve_mat is not None and self._vmed is not None:
            dev = np.abs(ve_mat - self._vmed)
            fire = (dev > self._drift_bar()) & self._vlive
            for i in np.nonzero(fire.any(axis=1))[0]:
                if src[i] is None:
                    z = np.where(fire[i], dev[i] / self._vsig, 0.0)
                    j = int(np.argmax(z))
                    src[i] = f"drift:{self._name_of(j)} z={z[j]:.1f}"
        return src

    def notify_model_swap(self) -> None:
        """Hot-swap notification (StreamScorer.set_params calls this):
        the swap shifts every car's reconstruction error together, so
        the fleet calibration recomputes EVERY update through the EMA
        fold transient (~2/alpha records per car) instead of at the
        steady-state cadence."""
        self._recal_hot = int(2.0 / max(self.alpha, 1e-3))

    def _name_of(self, j: int) -> str:
        return (self.feature_names[j] if self.feature_names is not None
                and j < len(self.feature_names) else str(j))

    def _head_source(self, k: bytes, ratio: float = 1.0):
        """The firing head's source string for car k, or None if no head
        fires at `ratio`× its threshold (ratio<1 = the hysteresis check).

        Error head: one-sided excess of the per-feature reconstruction
        error EMA over an alert bar of max(feature_z·MADsig,
        tail_k·p90-excess, floor).  Drift head: the two-sided analogue
        on the value EMAs, categorical features masked.  The tail term
        is the live robustness guard — see its constructor comment."""
        if not self.feature_heads:
            return None
        if self._fmed is not None:
            fe = self.fema.get(k)
            if fe is not None:
                excess = fe - self._fmed
                fire = excess > self._error_bar() * ratio
                if fire.any():
                    z = np.where(fire, excess / self._fsig, 0.0)
                    j = int(np.argmax(z))
                    return f"feature:{self._name_of(j)} z={z[j]:.1f}"
        if self._vmed is not None:
            ve = self.vema.get(k)
            if ve is not None:
                dev = np.abs(ve - self._vmed)
                fire = (dev > self._drift_bar() * ratio) & self._vlive
                if fire.any():
                    z = np.where(fire, dev / self._vsig, 0.0)
                    j = int(np.argmax(z))
                    return f"drift:{self._name_of(j)} z={z[j]:.1f}"
        return None

    def _recalibrate_mse(self) -> None:
        """Auto MSE threshold: robust fleet quantiles over warmed-up,
        un-alerted cars.  median + k·(p75−median) is
        contamination-tolerant (a few percent of failing cars sit in the
        upper tail and barely move either statistic) and tracks the
        model's error scale; alerted cars are excluded so a detected
        failure cannot inflate the bar for the next one."""
        emas = [e for k, e in self.ema.items()
                if self.count.get(k, 0) >= self.min_records
                and k not in self.alerted]
        if len(emas) >= 20:
            med = float(np.median(emas))
            p75 = float(np.percentile(emas, 75))
            self.threshold = max(self.auto_floor,
                                 med + self.auto_k * (p75 - med))
            self._calibrated = True

    def _recalibrate_features(self) -> None:
        """Per-feature fleet median and MAD over warmed-up, un-alerted
        cars — recomputed every update so the z scores stay
        CROSS-SECTIONAL: a model hot-swap moves every car's error
        together and contemporaneous median/MAD absorb it.  (The flip
        side, inherent to cross-sectional detection: a fault affecting
        the ENTIRE fleet at once shifts the median with it and no single
        car alerts — fleet-level drift belongs to the record-level AUC
        and the obs dashboards, not the per-car pager.)"""
        # ONE quantile call per head (it runs every update): med from
        # p50, robust sigma from the IQR (IQR/1.349 estimates the same
        # sigma as 1.4826·MAD for the distribution core and is
        # computable in the same partition pass), tail from p90 — the
        # one-sided error tail is p90−med exactly (clipping at 0
        # commutes with the quantile above the median).
        fes = [fe for k, fe in self.fema.items()
               if self.count.get(k, 0) >= self.min_records
               and k not in self.alerted]
        if len(fes) >= 20:
            q25, med, q75, q90 = np.percentile(
                np.stack(fes), [25, 50, 75, 90], axis=0)
            self._fmed = med
            self._fsig = (q75 - q25) / 1.349 + 1e-9
            self._ftail = np.maximum(q90 - med, 0.0)
        ves = [ve for k, ve in self.vema.items()
               if self.count.get(k, 0) >= self.min_records
               and k not in self.alerted]
        if len(ves) >= 20:
            stack = np.stack(ves)
            q25, med, q75 = np.percentile(stack, [25, 50, 75], axis=0)
            iqr = q75 - q25
            self._vmed = med
            self._vsig = iqr / 1.349 + 1e-9
            # two-sided tail needs the |deviation| quantile (one extra
            # partition pass)
            self._vtail = np.percentile(np.abs(stack - med), 90, axis=0)
            # fleet-constant features (firmware: categorical) are not
            # drift candidates — a minority config is not a failure
            self._vlive = iqr > 1e-6

    # ------------------------------------------------------------- sinks
    def publish_transitions(self, broker, topic: str,
                            transitions: Optional[list] = None) -> int:
        """Emit alert transitions as keyed JSON records (the digital-twin
        feed: key = car key, value = {car, state, ema, t}).  Pass the
        return value of update() to publish just that batch's
        transitions; the published `t` is the transition's recorded
        timestamp (identical to self.transitions), never re-stamped.
        One wire request for the whole batch (a per-transition produce
        paid a full round trip against a busy broker — 68 ms each
        measured in the scorer ceiling profile)."""
        trans = (list(transitions) if transitions is not None
                 else list(self.transitions))
        if not trans:
            return 0
        entries = [(k, json.dumps(
            {"car": k.decode(errors="replace"), "state": s,
             "ema": round(e, 6), "t": t, "source": src}).encode(), 0)
            for t, k, s, e, src in trans]
        pm = getattr(broker, "produce_many", None)
        if pm is not None:
            pm(topic, entries)
        else:
            for k, v, _ in entries:
                broker.produce(topic, v, key=k)
        return len(entries)

    def summary(self) -> dict:
        out = {
            "cars_seen": len(self.ema),
            "cars_alerted": sorted(k.decode(errors="replace")
                                   for k in self.alerted),
            "n_transitions": len(self.transitions),
            "threshold": round(self.threshold, 4),
        }
        if self.feature_heads:
            out["feature_heads"] = True
            out["feature_calibrated"] = self._fmed is not None
            out["alert_sources"] = {
                k.decode(errors="replace"): s
                for k, s in sorted(self.alert_source.items())}
        return out
