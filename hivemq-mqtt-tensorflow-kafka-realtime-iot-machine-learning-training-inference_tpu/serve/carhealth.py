"""Per-car failure detection — the predictive-maintenance deliverable.

The reference exists to detect failing CARS, not merely anomalous rows
(reference README.md:7,19: "predictive maintenance … detect sensor
anomalies"), yet its pipeline stops at per-record reconstruction error.
Per-record detection is noise-limited: the car autoencoder's irreducible
error (unpredictable sensors: air temp, accelerometers, per-car tire
baselines) overlaps the failure modes' per-record signal, capping
per-record F1 near 0.6 (ARCHITECTURE.md; the e2e bench measures it live).
A car's failure, however, PERSISTS: every record it emits is drawn from
the shifted distribution, so averaging per-record errors over a car's
recent records shrinks the noise by ~1/√N while the failure signal stays
put — the per-car separation is near-total after a few dozen records.

`CarHealthDetector` maintains an exponential moving average of
reconstruction error per car key (the message key: MQTT topic → bridge →
KSQL pass-through), raises an ALERT when a car's EMA crosses the
threshold (after a minimum evidence count), and clears it with hysteresis
at 70% of the threshold.  Alert transitions are emitted as JSON records
onto a stream topic — the digital-twin feed a MongoDB sink consumes, car
id as the record key, same as the reference's twin pipeline shape.

Detection envelope (measured against the scenario generator's injected
modes, reference-parity model): per-car EMAs of healthy cars span
~0.17–0.35 (per-car quirks: tire baselines, firmware, unpredictable
sensors), so the default threshold 0.38 sits just above that band —
high-magnitude persistent faults (tire blowout: EMA ≈ 0.41+) alert with
zero false positives; low-magnitude modes (battery sag ≈ +2% MSE) stay
inside the healthy band and are visible only in the fleet-level
per-record AUC, not separable per car by reconstruction MSE.  Per-car
baseline-relative variants (drift/z-score per feature) were measured and
rejected: their healthy-tail false-alert rate exceeds the recall they
add.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

import numpy as np

from ..obs import metrics as obs_metrics


class CarHealthDetector:
    """EMA-per-key anomaly detector with hysteresis and alert records.

    Args:
      threshold: EMA level that raises an alert — default 0.38 sits just
        above the measured healthy-fleet EMA band (module docstring).
        "auto" calibrates from the fleet itself (median + k·(p75−median)
        over warmed-up cars, recomputed as the stream flows); it needs a
        STABLE model — under continuous hot-swapping the per-car EMA
        spread collapses to the swap cadence and the quantile margin
        under-estimates, so live deployments with fast retrain loops
        should pin the threshold to their measured healthy band instead.
      alpha: EMA weight per record (effective window ≈ 1/alpha records).
      min_records: evidence required before a car may alert (a single
        outlier row must not page an operator).
      clear_ratio: hysteresis — an alerted car clears below
        threshold×clear_ratio (flapping at the boundary is operator spam).
      auto_k / auto_floor: the auto calibration's margin multiplier and
        minimum threshold.
    """

    #: recompute the auto threshold every this many update() calls
    AUTO_EVERY = 50

    def __init__(self, threshold=0.38, alpha: float = 0.05,
                 min_records: int = 20, clear_ratio: float = 0.7,
                 auto_k: float = 4.5, auto_floor: float = 0.3):
        self.auto = threshold == "auto"
        self.threshold = auto_floor if self.auto else float(threshold)
        self.auto_k = auto_k
        self.auto_floor = auto_floor
        #: auto mode must not alert before the first successful fleet
        #: calibration — the floor is a lower BOUND, not a threshold
        self._calibrated = not self.auto
        self._updates = 0
        self.alpha = alpha
        self.min_records = min_records
        self.clear_ratio = clear_ratio
        self.ema: Dict[bytes, float] = {}
        self.count: Dict[bytes, int] = {}
        self.alerted: Dict[bytes, float] = {}  # key → alert wall time
        self.transitions: list = []  # (t, key, "ALERT"|"CLEAR", ema)
        self._m_alerts = obs_metrics.default_registry.counter(
            "car_health_alerts_total", "per-car failure alerts raised")
        self._m_active = obs_metrics.default_registry.gauge(
            "car_health_alerts_active", "cars currently in ALERT state")

    # ------------------------------------------------------------ update
    def update(self, keys: np.ndarray, errs: np.ndarray) -> list:
        """Fold one scored batch's (keys [n] bytes, per-row errors [n])
        into the per-car state; returns this call's alert transitions as
        [(t, key, state, ema)] — the same 4-tuples recorded in
        self.transitions, so publishing them downstream carries the
        transition's own timestamp.  Vectorized per distinct car: a batch holds
        many rows of few cars, so the group-by does the heavy lifting in
        numpy and the Python loop runs per CAR, not per row."""
        if len(keys) == 0:
            return []
        self._updates += 1
        if self.auto and (not self._calibrated
                          or self._updates % self.AUTO_EVERY == 0):
            self._recalibrate()
        order = np.argsort(keys, kind="stable")
        sk, se = keys[order], errs[order]
        uniq, starts = np.unique(sk, return_index=True)
        bounds = np.append(starts, len(sk))
        out = []
        now = time.time()
        for u, lo, hi in zip(uniq, bounds[:-1], bounds[1:]):
            k = bytes(u)
            if not k:
                continue  # keyless records carry no car identity
            e = self.ema.get(k)
            # fold the car's rows in arrival order: EMA of the sequence
            # (a closed form exists but per-row exactness matters for
            # parity with a record-at-a-time consumer)
            for x in se[lo:hi]:
                e = float(x) if e is None else \
                    e + self.alpha * (float(x) - e)
            self.ema[k] = e
            self.count[k] = self.count.get(k, 0) + int(hi - lo)
            if k not in self.alerted:
                if self._calibrated and \
                        self.count[k] >= self.min_records and \
                        e > self.threshold:
                    self.alerted[k] = now
                    self.transitions.append((now, k, "ALERT", e))
                    out.append((now, k, "ALERT", e))
                    self._m_alerts.inc()
            elif e < self.threshold * self.clear_ratio:
                del self.alerted[k]
                self.transitions.append((now, k, "CLEAR", e))
                out.append((now, k, "CLEAR", e))
        self._m_active.set(len(self.alerted))
        return out

    def _recalibrate(self) -> None:
        """Auto threshold: robust fleet quantiles over warmed-up cars.

        median + k·(p75−median) is contamination-tolerant (a few percent
        of failing cars sit in the upper tail and barely move either
        statistic) and tracks the model's error scale; alerted cars are
        excluded so a detected failure cannot inflate the bar for the
        next one."""
        emas = [e for k, e in self.ema.items()
                if self.count.get(k, 0) >= self.min_records
                and k not in self.alerted]
        if len(emas) < 20:
            return  # too few calibrated cars: keep the floor/last value
        med = float(np.median(emas))
        p75 = float(np.percentile(emas, 75))
        self.threshold = max(self.auto_floor,
                             med + self.auto_k * (p75 - med))
        self._calibrated = True

    # ------------------------------------------------------------- sinks
    def publish_transitions(self, broker, topic: str,
                            transitions: Optional[list] = None) -> int:
        """Emit alert transitions as keyed JSON records (the digital-twin
        feed: key = car key, value = {car, state, ema, t}).  Pass the
        return value of update() to publish just that batch's
        transitions; the published `t` is the transition's recorded
        timestamp (identical to self.transitions), never re-stamped."""
        trans = (list(transitions) if transitions is not None
                 else list(self.transitions))
        n = 0
        for t, k, s, e in trans:
            broker.produce(topic, json.dumps(
                {"car": k.decode(errors="replace"), "state": s,
                 "ema": round(e, 6), "t": t}).encode(), key=k)
            n += 1
        return n

    def summary(self) -> dict:
        return {
            "cars_seen": len(self.ema),
            "cars_alerted": sorted(k.decode(errors="replace")
                                   for k in self.alerted),
            "n_transitions": len(self.transitions),
            "threshold": round(self.threshold, 4),
        }
