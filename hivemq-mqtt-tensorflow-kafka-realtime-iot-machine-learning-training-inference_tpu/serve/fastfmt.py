"""Byte-identical fast `np.array2string` for prediction rows.

The serve path's payload contract is `np.array2string(row)` — the exact
string the reference's OutputCallback produced (cardata-v3.py:247).
Profiling shows that call IS the serve bottleneck: ~90% of a drain's wall
clock goes to numpy's per-element Python formatting pipeline
(FloatingFormat.fillFormat + _formatArray), ~5× the cost of the
underlying dragon4 C calls.

`format_rows` reproduces numpy's output byte-for-byte for the common
case — 1-D finite float rows, default print options, positional
(non-exponential) formatting — by calling dragon4 once per element and
re-implementing the padding + line-wrap assembly
(numpy/_core/arrayprint.py: `FloatingFormat.fillFormat` positional
branch, `_formatArray`'s 1-D recurser with `_extendLine`).  Rows that
would take any other numpy path — non-finite values, exponential
trigger (|x|max ≥ 1e8, nonzero |x|min < 1e-4, or max/min > 1000), or
non-default printoptions — fall back to `np.array2string` itself, so
equality holds unconditionally (pinned by tests/test_fastfmt.py against
numpy on adversarial inputs).
"""

from __future__ import annotations

import warnings
from typing import List

import numpy as np

# the printoptions this fast path reproduces; anything else → fallback
_DEFAULTS = {
    "precision": 8, "suppress": False, "floatmode": "maxprec",
    "sign": "-", "linewidth": 75,
}

_LINEWIDTH = 75
_HANG = " "                       # 1-D next_line_prefix
_ELEM_W = _LINEWIDTH - 1          # minus max(len(sep.rstrip()), len(']'))

# the public format_float_positional wrapper spends ~3× the C call's cost
# in argument validation; go straight to dragon4 when the private symbol
# exists (same function numpy itself dispatches to), else use the wrapper
try:
    from numpy._core._multiarray_umath import \
        dragon4_positional as _dragon4  # type: ignore[attr-defined]
except ImportError:  # numpy layout changed: correctness over speed
    _dragon4 = np.format_float_positional


def _options_are_default() -> bool:
    opts = np.get_printoptions()
    return all(opts.get(k) == v for k, v in _DEFAULTS.items())


def _format_fast_row(row: np.ndarray) -> str:
    """One finite, non-exponential row → np.array2string(row) bytes."""
    fmt = _dragon4
    strs = [fmt(x, precision=8, unique=True, fractional=True, trim=".")
            for x in row]
    parts = [s.split(".") for s in strs]
    pad_left = max(len(p[0]) for p in parts)
    pad_right = max(len(p[1]) for p in parts)
    words = [
        " " * (pad_left - len(p[0])) + s + " " * (pad_right - len(p[1]))
        for s, p in zip(strs, parts)
    ]
    # numpy's 1-D assembly: hanging indent ' ', separator ' ' appended
    # after every element but the last, wrap when the next word would
    # cross elem_width, then strip the indent and wrap in brackets
    out = []
    line = _HANG
    last = len(words) - 1
    for i, w in enumerate(words):
        if len(line) + len(w) > _ELEM_W and len(line) > len(_HANG):
            out.append(line.rstrip())
            line = _HANG
        line += w
        if i != last:
            line += " "
    out.append(line)
    return "[" + "\n".join(out)[1:] + "]"


def _format_rows_native(rows: np.ndarray):
    """Whole-batch formatting in the C++ engine (fmt_engine.cc): one call
    formats every eligible row; ineligible rows come back flagged and are
    formatted through np.array2string here.  Returns None when the native
    engine is unavailable or the dtype is not float32/float64 — caller
    falls through to the per-row Python fast path."""
    import ctypes

    from ..stream import native

    lib = native.load()
    if lib is None or rows.dtype not in (np.float32, np.float64):
        return None
    fn = (lib.iotml_format_rows_f32 if rows.dtype == np.float32
          else lib.iotml_format_rows_f64)
    ptr_t = ctypes.c_float if rows.dtype == np.float32 else ctypes.c_double
    rows = np.ascontiguousarray(rows)
    n, f = rows.shape
    # worst-case padded word ~ (20 left + 20 right + 2) chars; cap adds
    # wrap newlines + brackets with slack, retried doubled on overflow
    cap = int(n * (f * 44 + f + 18) + 64)
    for _ in range(2):
        out = np.empty((cap,), np.uint8)
        offsets = np.zeros((n + 1,), np.int64)
        fallback = np.zeros((n,), np.uint8)
        total = fn(rows.ctypes.data_as(ctypes.POINTER(ptr_t)),
                   ctypes.c_int64(n), ctypes.c_int64(f),
                   out.ctypes.data_as(ctypes.c_char_p),
                   ctypes.c_int64(cap),
                   offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                   fallback.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if total >= 0:
            # slice before copying: cap over-allocates ~4× the formatted
            # bytes, and tobytes() on the full buffer would memcpy the
            # slack on every drain of the very hot path this exists for
            raw = out[:total].tobytes()
            return [
                raw[offsets[i]:offsets[i + 1]].decode()
                if not fallback[i] else np.array2string(rows[i])
                for i in range(n)
            ]
        cap *= 2
    return None


def format_rows(rows: np.ndarray) -> List[str]:
    """np.array2string for each row of [N, F], byte-identical, fast.

    Vectorized eligibility: a row takes the fast path iff every value is
    finite and the positional format applies (no exponential trigger).
    Everything else — and any session with non-default printoptions —
    formats through numpy itself.  The whole-batch C++ formatter carries
    the eligible rows when the native engine is present; the per-element
    dragon4 path below is the pure-Python fallback."""
    rows = np.asarray(rows)
    if rows.ndim != 2 or rows.dtype.kind != "f" or \
            not _options_are_default():
        return [np.array2string(r) for r in rows]

    native_out = _format_rows_native(rows)
    if native_out is not None:
        return native_out

    finite = np.isfinite(rows).all(axis=1)
    absd = np.abs(rows.astype(np.float64))
    nz = np.where(absd > 0, absd, np.nan)
    with np.errstate(invalid="ignore", divide="ignore", over="ignore"), \
            warnings.catch_warnings():
        # all-zero rows are legitimately all-NaN here; has_nz handles them
        warnings.simplefilter("ignore", RuntimeWarning)
        mx = np.nanmax(nz, axis=1)
        mn = np.nanmin(nz, axis=1)
        has_nz = ~np.isnan(mx)
        exp = has_nz & ((mx >= 1e8) | (mn < 1e-4) | (mx / mn > 1000.0))
    fast = finite & ~exp

    return [
        _format_fast_row(row) if ok else np.array2string(row)
        for row, ok in zip(rows, fast)
    ]
