"""Long-lived scorer with live model hot-swap off the artifact store.

The reference's predict Deployment downloads a fixed GCS model name at pod
start and scores until restarted (`cardata-v3.py:255-274`,
`run.sh:16-91` restarts it after each training job so new weights take
effect).  `LiveScorer` is that loop without the restart: it polls the
`{model_name}.latest` pointer a `train.live.ContinuousTrainer` flips after
every round, downloads the new immutable blob, and swaps params between
super-batches — predictions keep flowing, in order, across the swap
(`StreamScorer.set_params`).

Detection quality rides along: batches keep the stream's
`failure_occurred` labels, so the threshold verdicts written to the
predictions topic are scored live into a confusion matrix
(`StreamScorer.quality`) — the streaming notebook's offline protocol
(threshold → confusion matrix, cells 21-26) as a live metric.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Callable, Optional

from ..data.dataset import SensorBatches
from ..obs import metrics as obs_metrics
from ..stream.consumer import StreamConsumer
from ..stream.producer import OutputSequence
from ..train.artifacts import ArtifactStore
from .scorer import StreamScorer


class LiveScorer:
    """Continuous scoring with pointer-driven weight hot-swap."""

    def __init__(self, broker, topic: str, result_topic: str,
                 store: Optional[ArtifactStore],
                 model_name: str = "cardata-live.h5",
                 model=None, threshold: Optional[float] = 5.0,
                 group: str = "cardata-live-score", batch_size: int = 100,
                 out_partition: Optional[int] = 0,
                 carhealth_topic: Optional[str] = "car-health",
                 car_threshold=0.38, car_feature_heads: bool = False,
                 normalizer=None, registry=None):
        if model is None:
            from ..models.autoencoder import CAR_AUTOENCODER

            model = CAR_AUTOENCODER
        if store is None and registry is None:
            raise ValueError("need an ArtifactStore pointer or a "
                             "ModelRegistry (iotml.mlops) to follow")
        self.broker = broker
        self.store = store
        #: versioned-registry mode (iotml.mlops): follow the registry's
        #: ``serving`` channel instead of the `.latest` pointer file —
        #: promote/rollback flips land here between super-batches.  The
        #: swap protocol itself (channel read, checksum-verified h5
        #: load, set_params fan-out, swap metrics) lives in ONE place:
        #: a RegistryWatcher polled inline from this loop
        self.registry = registry
        self._watcher = None
        if registry is not None:
            from ..mlops.rollout import RegistryWatcher

            self._watcher = RegistryWatcher(registry, component="scorer")
        self._current_version: Optional[int] = None
        self.model_name = model_name
        self.model = model
        parts = range(broker.topic(topic).partitions)
        consumer = StreamConsumer.from_committed(broker, topic, parts,
                                                 group=group, eof=False)
        carhealth = None
        if carhealth_topic is not None:
            from ..core.schema import KSQL_CAR_SCHEMA
            from .carhealth import CarHealthDetector

            carhealth = CarHealthDetector(
                threshold=car_threshold,
                feature_heads=car_feature_heads,
                feature_names=[f.name
                               for f in KSQL_CAR_SCHEMA.sensor_fields])
            broker.create_topic(carhealth_topic)
        batch_kw = {} if normalizer is None else dict(normalizer=normalizer)
        batches = SensorBatches(consumer, batch_size=batch_size,
                                keep_labels=True,
                                keep_keys=carhealth is not None,
                                **batch_kw)
        out = OutputSequence(broker, result_topic, partition=out_partition)
        # under full normalization the verdict mean stays on the PARITY
        # feature subset — the threshold protocol's calibrated feature
        # set (see StreamScorer.verdict_mask)
        verdict_mask = None
        if normalizer is not None:
            from ..core.normalize import CAR_NORMALIZER

            if normalizer is not CAR_NORMALIZER:
                verdict_mask = CAR_NORMALIZER.mask.astype(bool)
        # params are loaded by wait_for_model(); scoring before that would
        # write garbage predictions from random init
        self.scorer = StreamScorer(model, None, batches, out,
                                   threshold=threshold,
                                   carhealth=carhealth,
                                   carhealth_topic=carhealth_topic,
                                   verdict_mask=verdict_mask)
        if self._watcher is not None:
            self._watcher.attach(self.scorer)
        self._current_artifact: Optional[str] = None
        self.model_updates = 0

    # ----------------------------------------------------------- weights
    def _load(self, artifact: str) -> None:
        from ..models.h5_import import autoencoder_params_from_h5

        with tempfile.TemporaryDirectory(prefix="iotml_swap_") as tmp:
            local = os.path.join(tmp, "model.h5")
            self.store.download(artifact, local)
            params = autoencoder_params_from_h5(local)
        # lint-ok: R13 legacy artifact-store pointer flow (pre-registry
        # deployments); registry-backed LiveScorers swap via _watcher
        self.scorer.set_params(params)
        self._current_artifact = artifact
        self.model_updates += 1
        obs_metrics.live_model_updates.inc()

    def maybe_swap(self) -> bool:
        """Poll the pointer (or the registry's serving channel); swap
        when it names a new version."""
        if self._watcher is not None:
            if not self._watcher.poll_once():
                return False
            self._current_version = self._watcher.current_version
            self._current_artifact = f"registry:v{self._current_version}"
            self.model_updates += 1
            obs_metrics.live_model_updates.inc()
            return True
        latest = self.store.get_text(f"{self.model_name}.latest")
        if latest is None or latest == self._current_artifact:
            return False
        self._load(latest)
        return True

    def wait_for_model(self, timeout_s: float = 60.0) -> str:
        """Block until the trainer publishes the first model (the predict
        pod's download-at-start, made explicit)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.maybe_swap():
                return self._current_artifact
            time.sleep(0.05)
        raise TimeoutError(f"no artifact at {self.model_name}.latest "
                           f"after {timeout_s}s")

    # ------------------------------------------------------------- serve
    def run(self, stop: Optional[Callable[[], bool]] = None,
            max_drains: Optional[int] = None,
            poll_interval_s: float = 0.02,
            on_drain: Optional[Callable[[dict], None]] = None) -> int:
        """Score until `stop()`; returns rows scored.  Calls `on_drain`
        with a stats snapshot after every non-empty drain (the live CLI
        prints these as JSON lines for the orchestrating process)."""
        if self.scorer.params is None:
            self.wait_for_model()
        scored0 = self.scorer.scored
        drains = 0
        last_emit = 0.0
        try:
            while (stop is None or not stop()) and \
                    (max_drains is None or drains < max_drains):
                self.maybe_swap()
                # bounded drain: under sustained overload an unbounded
                # drain would never return and this loop would stop
                # polling for new weights / the stop signal
                n = self.scorer.score_available(max_rows=50_000)
                if n == 0:
                    time.sleep(poll_interval_s)
                    continue
                drains += 1
                # stats are cumulative, so a consumer only needs them at
                # its own cadence: throttle to 10 Hz so tiny frequent
                # drains don't spend the core serializing stats lines
                if on_drain is not None and \
                        time.time() - last_emit >= 0.1:
                    last_emit = time.time()
                    on_drain(self.stats())
        finally:
            # final snapshot: the cumulative counters up to the stop point
            if on_drain is not None and drains:
                on_drain(self.stats())
        return self.scorer.scored - scored0

    def stats(self) -> dict:
        q = self.scorer.quality
        if q["tp"] + q["fp"]:
            obs_metrics.live_detection_precision.set(
                q["tp"] / (q["tp"] + q["fp"]))
        if q["tp"] + q["fn"]:
            obs_metrics.live_detection_recall.set(
                q["tp"] / (q["tp"] + q["fn"]))
        return {
            "t": time.time(),
            # False while a max_rows-truncated drain is suspended: the
            # consumer positions then run ahead of the flushed
            # predictions, so position-based joins (per-record latency)
            # must only trust complete-drain snapshots
            "drain_complete": self.scorer._resume is None,
            "scored": self.scorer.scored,
            "quality": dict(self.scorer.quality),
            "err_hist": {k: v.tolist()
                         for k, v in self.scorer.err_hist.items()},
            "model_updates": self.model_updates,
            "artifact": self._current_artifact,
            "positions": {f"{p}": off for _, p, off
                          in self.scorer.batches.consumer.positions()},
            "carhealth": (self.scorer.carhealth.summary()
                          if self.scorer.carhealth is not None else None),
        }
