"""Continuous stream scorer with ordered write-back.

The reference's inference side is a K8s Deployment that scores a fixed slice
(batch 100 × take 100), exits, and is restarted by Kubernetes forever — its
own README calls this out as "not an ideal architecture … Python batch style"
(python-scripts/README.md:24).  The TPU-native replacement is what that
README wishes for: one long-lived process with a jit-compiled scoring step,
polling the stream, writing predictions back through the ordered
OutputSequence, and committing offsets so a crash resumes where it stopped.

Output format parity: each prediction row is serialized with
`np.array2string` exactly like the reference callback (cardata-v3.py:247), so
downstream consumers of the predictions topic see identical payloads.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import numpy as np

from ..obs import metrics as obs_metrics
from ..data.dataset import SensorBatches
from ..stream.producer import OutputSequence
from ..train.loop import make_eval_step


def format_prediction(row: np.ndarray) -> str:
    """Reference-parity payload: np.array2string of the output vector."""
    return np.array2string(row)


class StreamScorer:
    """Score an input stream continuously; write ordered predictions back.

    Args:
      model/params: flax module + params (trained, h5-imported, or orbax).
      batches: SensorBatches over the input consumer (only_normal=False —
        the predict path scores everything, cardata-v3.py:264-268).
      out: OutputSequence onto the predictions topic.
      threshold: optional reconstruction-error threshold; when set, rows also
        get an anomaly verdict appended (the notebook's fixed-threshold
        protocol, threshold 5).
    """

    def __init__(self, model, params, batches: SensorBatches,
                 out: OutputSequence, threshold: Optional[float] = None):
        self.model = model
        self.params = params
        self.batches = batches
        self.out = out
        self.threshold = threshold
        self._eval = make_eval_step(model)
        self.scored = 0

    def score_available(self) -> int:
        """Drain whatever is currently in the stream; returns rows scored.

        The whole drain is ONE device dispatch: batches are stacked and
        scored as a single [S*B, F] eval instead of a dispatch per 100-row
        batch — per-dispatch link latency dominates a model this small, so
        a drain of 100 batches costs one round trip instead of 100."""
        n0 = self.scored
        base = self.scored  # batch.first_index restarts per drain; rebase globally
        bs = list(self.batches)
        if not bs:
            self.out.flush()
            self.batches.consumer.commit()
            return 0
        xs = np.stack([b.x for b in bs])   # [S, B, ...] (F, or T×F windowed)
        S, B = xs.shape[:2]
        row_shape = xs.shape[2:]
        # pad the batch count to a power-of-two bucket: drains vary in size
        # and jit would otherwise recompile the eval for every distinct S
        S_pad = 1 << max(0, (S - 1).bit_length())
        if S_pad != S:
            xs_in = np.concatenate(
                [xs, np.zeros((S_pad - S, B) + row_shape, xs.dtype)])
        else:
            xs_in = xs
        preds = jax.device_get(self._eval(
            self.params, xs_in.reshape((S_pad * B,) + row_shape)))
        preds = preds.reshape((S_pad, B) + preds.shape[1:])[:S]
        # per-row reconstruction error over every non-batch axis
        err_axes = tuple(range(2, preds.ndim))
        errs = np.mean(np.square(preds - xs), axis=err_axes)  # [S, B]
        for k, b in enumerate(bs):
            pred, err = preds[k], errs[k]
            for i in range(b.n_valid):
                idx = base + b.first_index + i
                msg = format_prediction(pred[i])
                if self.threshold is not None:
                    verdict = "anomaly" if err[i] > self.threshold else "normal"
                    msg = f"{msg}|{verdict}|{err[i]:.6f}"
                self.out.setitem(idx, msg)
            self.scored += b.n_valid
            obs_metrics.records_scored.inc(b.n_valid)
            if b.n_valid:
                obs_metrics.reconstruction_mse.set(float(np.mean(err[: b.n_valid])))
        self.out.flush()
        self.batches.consumer.commit()
        return self.scored - n0

    def run_forever(self, poll_interval_s: float = 0.2,
                    max_rounds: Optional[int] = None):
        """The long-lived loop the reference's restart-the-pod pattern
        approximates.  max_rounds bounds it for tests."""
        rounds = 0
        while max_rounds is None or rounds < max_rounds:
            n = self.score_available()
            rounds += 1
            if n == 0:
                time.sleep(poll_interval_s)
