"""Continuous stream scorer with ordered write-back.

The reference's inference side is a K8s Deployment that scores a fixed slice
(batch 100 × take 100), exits, and is restarted by Kubernetes forever — its
own README calls this out as "not an ideal architecture … Python batch style"
(python-scripts/README.md:24).  The TPU-native replacement is what that
README wishes for: one long-lived process with a jit-compiled scoring step,
polling the stream, writing predictions back through the ordered
OutputSequence, and committing offsets so a crash resumes where it stopped.

Output format parity: each prediction row is serialized with
`np.array2string` exactly like the reference callback (cardata-v3.py:247), so
downstream consumers of the predictions topic see identical payloads.
"""

from __future__ import annotations

import itertools
import time
from typing import Optional

import jax
import numpy as np

from ..chaos import faults as chaos
from ..obs import metrics as obs_metrics
from ..obs import tracing, watermark
from ..data.dataset import SensorBatches
from ..stream.producer import OutputSequence
from ..train.loop import make_eval_step
from ..utils.backoff import ExpBackoff
from .fastfmt import format_rows


def format_prediction(row: np.ndarray) -> str:
    """Reference-parity payload: np.array2string of the output vector."""
    return np.array2string(row)


#: log-spaced reconstruction-error bucket edges for live quality histograms
#: (64 buckets spanning 1e-4..1e2 + one overflow bucket)
ERR_BUCKETS = np.geomspace(1e-4, 1e2, 65)


def hist_auc(anom: np.ndarray, normal: np.ndarray) -> Optional[float]:
    """ROC AUC from per-label score histograms (midpoint tie handling).

    Buckets ascend in score; AUC = P(score_anom > score_normal) with ties
    counted half — the rank-sum estimator over binned errors."""
    a_tot, n_tot = int(anom.sum()), int(normal.sum())
    if not a_tot or not n_tot:
        return None
    n_below = np.concatenate([[0], np.cumsum(normal)[:-1]])
    wins = float(np.sum(anom * (n_below + normal / 2.0)))
    return wins / (a_tot * n_tot)


class StreamScorer:
    """Score an input stream continuously; write ordered predictions back.

    Args:
      model/params: flax module + params (trained, h5-imported, or orbax).
      batches: SensorBatches over the input consumer (only_normal=False —
        the predict path scores everything, cardata-v3.py:264-268).
      out: OutputSequence onto the predictions topic.
      threshold: optional reconstruction-error threshold; when set, rows also
        get an anomaly verdict appended (the notebook's fixed-threshold
        protocol, threshold 5).

    Delivery semantics: input is at-least-once (offsets commit once per
    drain, after every polled row is scored — a mid-drain commit would
    record offsets for rows still inside the batcher's poll/filter buffers
    and lose them on crash-resume).  The flip side is that predictions are
    flushed to the output topic per super-batch, so a crash mid-drain
    re-emits every super-batch of that drain on resume: the output topic is
    at-least-once too, with a duplicate window of up to one drain.
    Duplicates are benign here — each prediction row is keyed by its global
    index through OutputSequence.setitem, so idempotent downstream
    consumers (and the reference's, which tolerates pod-restart re-scoring,
    python-scripts/README.md:24) deduplicate on key.
    """

    #: Upper bound on batches stacked into one device dispatch.  A drain of
    #: an arbitrarily deep backlog (e.g. scoring a retained topic from offset
    #: 0) proceeds in fixed-size super-batches so host+device memory stays
    #: bounded, while a typical drain (≤ this many batches) keeps the
    #: single-dispatch win.  128 batches × 100 rows × 18 features is under
    #: 1 MB on device — the bound exists for pathological backlogs, and at
    #: 64 the reference-shaped 10k-row drain was paying TWO device round
    #: trips instead of one.
    max_super_batches = 128

    def __init__(self, model, params, batches: SensorBatches,
                 out: OutputSequence, threshold: Optional[float] = None,
                 carhealth=None, carhealth_topic: Optional[str] = None,
                 verdict_mask=None, feature_store=None):
        self.model = model
        self.params = params
        self.batches = batches
        self.out = out
        self.threshold = threshold
        #: optional twin.TwinFeatureStore: per-car HISTORICAL features
        #: (rolling-window aggregates from the digital twin) are
        #: concatenated onto each live row before scoring, so the model
        #: sees [F live + K twin] inputs — its input_dim must match.
        #: Requires batches built with keep_keys=True (the join key is
        #: the car's message key); rows without a key — or cars with no
        #: twin yet — join the zero vector, the cold-start null.
        #: Batched 2-D rows only: windowed/LSTM rows have no single
        #: per-row car identity to join on.
        self.feature_store = feature_store
        #: optional boolean [F] mask restricting the per-row error MEAN
        #: (verdicts, quality histograms, car mean-EMA) to a feature
        #: subset.  Full-normalization deployments pass the PARITY mask:
        #: the threshold protocol was calibrated on the reference's
        #: feature set, and the four extra full-norm features (inherently
        #: noisy) dilute the per-record verdict signal (measured: best f1
        #: 0.50 unmasked vs 0.60 masked at the same model) — while the
        #: per-feature detector heads still see all 18.
        self.verdict_mask = (np.asarray(verdict_mask, bool)
                             if verdict_mask is not None else None)
        #: optional per-car detector (serve.carhealth.CarHealthDetector):
        #: fed each scored batch's (keys, per-row errors) when the batch
        #: source keeps keys; alert transitions publish to
        #: `carhealth_topic` on the output broker (the digital-twin feed)
        self.carhealth = carhealth
        self.carhealth_topic = carhealth_topic
        self._eval = make_eval_step(model)
        self.scored = 0
        #: registry version of the loaded weights (None = not registry-
        #: managed); stamped by set_params(version=) / RegistryWatcher
        self.model_version: Optional[int] = None
        #: suspended (iterator, index_base) of a max_rows-truncated drain
        self._resume = None
        #: confusion counts of the threshold verdicts against stream labels
        #: (batches built with keep_labels=True): live detection quality —
        #: the notebook's offline protocol (threshold / confusion matrix,
        #: streaming notebook cells 21-26) running against the predictions
        #: actually being written.  Padding rows are excluded; rows without
        #: a label (empty string) count as negatives, matching the training
        #: filter's reading of the label field.
        self.quality = {"tp": 0, "fp": 0, "fn": 0, "tn": 0}
        #: per-label reconstruction-error histograms (log buckets): enough
        #: to recover threshold-free quality (AUC, any operating point)
        #: from a live run without retaining per-row errors
        self.err_hist = {"true": np.zeros(len(ERR_BUCKETS) + 1, np.int64),
                         "false": np.zeros(len(ERR_BUCKETS) + 1, np.int64)}

    def set_params(self, params, version: Optional[int] = None) -> None:
        """Hot-swap model weights; takes effect at the next super-batch.

        The handoff the reference performs by restarting its predict pod
        with a fresh GCS download (cardata-v3.py:255-261) — a long-lived
        scorer swaps in place instead.  The jit eval traces params as
        arguments, so same-shaped params reuse the compiled program, and
        the swap cannot drop or reorder output: the OutputSequence index
        stream is untouched.  ``version`` (a registry id) stamps the
        scorer's model identity for /healthz + the version gauge."""
        self.params = params
        if version is not None:
            self.model_version = version
            obs_metrics.model_version.set(version, component="scorer")
        if self.carhealth is not None and \
                hasattr(self.carhealth, "notify_model_swap"):
            # new weights shift every car's error together: the detector
            # recalibrates per-update through the fold transient
            self.carhealth.notify_model_swap()

    def score_available(self, max_rows: Optional[int] = None) -> int:
        """Drain whatever is currently in the stream; returns rows scored.

        Each super-batch is ONE device dispatch: up to max_super_batches
        batches are stacked and scored as a single [S*B, F] eval instead of
        a dispatch per 100-row batch — per-dispatch link latency dominates a
        model this small, so a typical drain costs one round trip instead of
        one per batch, and a deep backlog costs ceil(S/cap) round trips with
        bounded memory.

        `max_rows` bounds ONE call: when producers outpace the scorer, an
        unbounded drain never returns and the caller's control loop
        (hot-swap polling, stop flags) starves.  A bounded call that still
        had data keeps its iterator SUSPENDED — the batcher's buffered
        rows stay queued, the next call resumes exactly where it stopped,
        and offsets only commit once the drain truly reaches the stream
        end (committing at the truncation point would persist the cursor
        past polled-but-unscored rows and silently drop them)."""
        start = self.scored
        if self._resume is not None:
            # continue the truncated drain: same iterator, same index base
            it, it_base = self._resume
            self._resume = None
        else:
            # batch.first_index restarts per iterator; rebase globally
            it, it_base = iter(self.batches), self.scored
        while True:
            chaos.point("scorer.poll")  # injected stall/crash lands at a
            # super-batch boundary: exactly where a real broker death
            # surfaces, upstream of the commit (redelivery covers it)
            with obs_metrics.step_seconds.time(loop="score",
                                               phase="host_pipeline"):
                # the host leg: poll + columnar decode + batching (the
                # batcher's iterator does all three)
                bs = list(itertools.islice(it, self.max_super_batches))
            if not bs:
                break
            self._score_super_batch(bs, it_base)
            # flush per super-batch: indices are monotone so the ordered
            # flush is preserved and host memory stays bounded by one
            # super-batch of formatted predictions
            self.out.flush()
            if max_rows is not None and self.scored - start >= max_rows:
                self._resume = (it, it_base)
                break
        if self._resume is None:
            # offsets commit once per COMPLETED drain, AFTER every polled
            # row was scored: the consumer cursor runs ahead of the scored
            # rows inside the batcher's poll/filter buffers, so an earlier
            # commit would record offsets for rows not yet scored and lose
            # them on crash-resume.  A crash mid-drain therefore redoes
            # the drain from the previous commit (at-least-once), never
            # skips data; under sustained overload (every call truncated)
            # commits simply wait for the first completed drain.
            self.batches.consumer.commit()
            # completed drain: everything consumed has been SCORED, so
            # the accumulated event-time ranges become the ingest→score
            # watermark (ISSUE 13) — true e2e staleness on the columnar
            # paths where per-record spans cannot exist
            take = getattr(self.batches.consumer, "take_event_time", None)
            if take is not None:
                watermark.observe_taken(
                    "score", take(),
                    group=getattr(self.batches.consumer, "group", ""))
            if tracing.ENABLED:
                # completed drain: every decoded record has been scored,
                # so close each trace with its e2e (ingest → score) span.
                # A truncated drain keeps traces pending with its
                # suspended iterator — rows still inside the batcher's
                # buffers must not report a score they haven't had.
                for ctx in self.batches.take_traces():
                    ctx.close("score")
        return self.scored - start

    def _score_super_batch(self, bs, base: int) -> None:
        if self.feature_store is not None and bs[0].x.ndim == 2:
            # the feature-store join: twin features ride beside the live
            # row INTO the model, so reconstruction error covers both —
            # a car whose live reading contradicts its own history
            # scores anomalous even when the reading is fleet-normal
            xs = np.stack([
                np.concatenate(
                    [b.x, self.feature_store.matrix(b.keys, b.x.shape[0])],
                    axis=1).astype(b.x.dtype)
                for b in bs])               # [S, B, F + K]
        else:
            xs = np.stack([b.x for b in bs])  # [S, B, ...] (F, or T×F)
        S, B = xs.shape[:2]
        row_shape = xs.shape[2:]
        # pad the batch count to a power-of-two bucket: drains vary in size
        # and jit would otherwise recompile the eval for every distinct S
        S_pad = 1 << max(0, (S - 1).bit_length())
        if S_pad != S:
            xs_in = np.concatenate(
                [xs, np.zeros((S_pad - S, B) + row_shape, xs.dtype)])
        else:
            xs_in = xs
        with obs_metrics.step_seconds.time(loop="score",
                                           phase="device_compute"):
            preds = jax.device_get(self._eval(
                self.params, xs_in.reshape((S_pad * B,) + row_shape)))
        preds = preds.reshape((S_pad, B) + preds.shape[1:])[:S]
        # per-row reconstruction error over every non-batch axis
        err_axes = tuple(range(2, preds.ndim))
        sq = np.square(preds - xs)
        if self.verdict_mask is not None and sq.ndim == 3:
            mask = self.verdict_mask
            if mask.shape[0] < sq.shape[2]:
                # feature-store join widened the rows: the verdict mask
                # was calibrated on the LIVE features, so the joined
                # twin columns stay out of the verdict mean
                mask = np.concatenate(
                    [mask, np.zeros(sq.shape[2] - mask.shape[0], bool)])
            errs = sq[:, :, mask].mean(axis=2)  # [S, B]
        else:
            errs = np.mean(sq, axis=err_axes)  # [S, B]
        # per-FEATURE errors for the detector's feature heads (2-D rows
        # only: windowed rows have no single per-feature identity)
        want_ferrs = (self.carhealth is not None
                      and getattr(self.carhealth, "feature_heads", False)
                      and sq.ndim == 3)
        # one vectorized formatting pass over every valid row in the
        # super-batch (byte-identical to np.array2string per row — the
        # serve bottleneck, see fastfmt)
        flat = preds.reshape((S * B,) + preds.shape[2:])
        valid_rows = np.concatenate(
            [flat[k * B: k * B + b.n_valid] for k, b in enumerate(bs)])
        if valid_rows.ndim == 2:
            msgs = format_rows(valid_rows)
        else:  # windowed/LSTM rows are [T, F]: 2-D payloads, numpy formats
            msgs = [format_prediction(r) for r in valid_rows]
        mi = 0
        for k, b in enumerate(bs):
            err = errs[k]
            if self.threshold is not None and b.labels is not None \
                    and b.n_valid:
                flag = err[: b.n_valid] > self.threshold
                truth = b.labels[: b.n_valid] == "true"
                self.quality["tp"] += int(np.sum(flag & truth))
                self.quality["fp"] += int(np.sum(flag & ~truth))
                self.quality["fn"] += int(np.sum(~flag & truth))
                self.quality["tn"] += int(np.sum(~flag & ~truth))
                buckets = np.searchsorted(ERR_BUCKETS, err[: b.n_valid])
                for lab, sel in (("true", truth), ("false", ~truth)):
                    if np.any(sel):
                        self.err_hist[lab] += np.bincount(
                            buckets[sel], minlength=len(ERR_BUCKETS) + 1)
            if self.carhealth is not None and b.keys is not None \
                    and b.n_valid:
                # per-feature heads see the LIVE columns only: joined
                # twin features are model input, not car sensors
                n_live = b.x.shape[1] if b.x.ndim == 2 else None
                trans = self.carhealth.update(
                    b.keys[: b.n_valid], err[: b.n_valid],
                    ferrs=sq[k][: b.n_valid, : n_live]
                    if want_ferrs else None,
                    fvals=xs[k][: b.n_valid, : n_live]
                    if want_ferrs else None)
                if trans and self.carhealth_topic is not None:
                    self.carhealth.publish_transitions(
                        self.out.broker, self.carhealth_topic, trans)
            for i in range(b.n_valid):
                idx = base + b.first_index + i
                msg = msgs[mi]
                mi += 1
                if self.threshold is not None:
                    verdict = "anomaly" if err[i] > self.threshold else "normal"
                    msg = f"{msg}|{verdict}|{err[i]:.6f}"
                self.out.setitem(idx, msg)
            self.scored += b.n_valid
            obs_metrics.records_scored.inc(b.n_valid)
            if b.n_valid:
                obs_metrics.reconstruction_mse.set(float(np.mean(err[: b.n_valid])))

    def run_forever(self, poll_interval_s: float = 0.2,
                    max_rounds: Optional[int] = None):
        """The long-lived loop the reference's restart-the-pod pattern
        approximates.  max_rounds bounds it for tests.

        Failover: the wire client does NOT auto-retry non-idempotent
        produce/commit after a reconnect (kafka_wire._request) — a broker
        death mid-drain surfaces ConnectionError here, already
        reconnected to the next bootstrap server.  This loop is the
        opt-in redelivery point the contract requires: rewind the input
        to the committed offsets and re-drain.  Output duplicates are
        benign — predictions are keyed by global index (see class
        docstring), the same at-least-once window a crash-restart has."""
        rounds = 0
        # bounded exponential backoff with jitter for the rewind loop: a
        # leader that STAYS dead turned the fixed poll_interval_s retry
        # into a busy-spin of doomed reconnect+redrain attempts (chaos
        # blackout scenarios exercise exactly this); healthy idle polling
        # keeps the flat cadence
        base = max(poll_interval_s, 0.01)  # poll_interval_s=0 is a legal
        # busy-poll for tests; the FAILURE path still must not busy-spin
        backoff = ExpBackoff(base_s=base, cap_s=max(2.0, base))
        while max_rounds is None or rounds < max_rounds:
            try:
                n = self.score_available()
            except ConnectionError:
                self.batches.consumer.rewind_to_committed()
                obs_metrics.scorer_rewinds.inc()
                rounds += 1
                time.sleep(backoff.next_delay())
                continue
            backoff.reset()
            rounds += 1
            if n == 0:
                time.sleep(poll_interval_s)
