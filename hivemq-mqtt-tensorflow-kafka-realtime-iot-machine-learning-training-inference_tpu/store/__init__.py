"""iotml.store — durable segmented log storage for the stream broker.

The paper's pipeline trains directly from the distributed commit log —
"no data lake" — which only holds if the commit log actually retains
and re-serves history across process deaths.  This package is that
retention: an append-only segmented log per partition (CRC32C-framed
records, configurable fsync, size/age segment roll, byte+time
retention, sparse offset + timestamp indexes), crash recovery that
truncates torn tails, a compacted consumer-offsets file, key-based log
compaction for ``cleanup.policy=compact`` topics (`compact.py`: latest
record per key, tombstone grace windows, dirty-ratio triggering,
atomic segment swaps), and a replay API (`read_from` / `read_since`)
for training backfill.

Mounted by `stream.broker.Broker(store_dir=...)`; every knob rides the
`store.*` config section (`IOTML_STORE_DIR`, `IOTML_STORE_FSYNC`, ...).
Lint rule R9 keeps every file write under a store directory inside this
package (`segment.SegmentWriter` owns the bytes and the fsync ledger).
"""

from .compact import CompactionStats, StoreCompactor
from .hwm import HwmFile, hwm_file_for
from .log import SegmentedLog, StorePolicy
from .mount import StoreMount
from .offsets import OffsetsFile
from .remote import RemoteSegmentMeta, RemoteTier
from .segment import SegmentWriter, atomic_write, crc32c, fsync_dir
from .tiered import RemoteSegmentCache, TieredLog, TierPolicy, TierUploader

__all__ = ["SegmentedLog", "StorePolicy", "StoreMount", "OffsetsFile",
           "SegmentWriter", "atomic_write", "crc32c", "fsync_dir",
           "CompactionStats", "StoreCompactor", "HwmFile",
           "hwm_file_for", "RemoteTier", "RemoteSegmentMeta",
           "TieredLog", "TierPolicy", "TierUploader",
           "RemoteSegmentCache"]
