"""Key-based log compaction — Kafka's ``cleanup.policy=compact``.

A compacted topic is a changelog: the log's *meaning* is the latest
record per key, so any record shadowed by a later one with the same key
is dead weight the store may reclaim.  This module owns the ONE
keep/discard decision in the codebase (`latest_offsets` + `keep` — the
consumer-offsets file and the segment compactor both route through it)
and the segment-level rewrite machinery:

- Only SEALED segments are compacted; the active segment keeps
  appending untouched, so compaction never contends with produce.
- Surviving records are copied as their ORIGINAL frame bytes (offset,
  CRC and all) into ``<base>.log.cleaned``, then atomically swapped
  over the sealed segment with ``os.replace`` — a reader mid-scan keeps
  its open fd on the old inode, a reader arriving after sees only the
  new file, and a crash between swaps leaves every segment either
  fully-old or fully-new (each is independently valid: frames are
  self-describing, offsets are preserved).  Leftover ``.cleaned`` tmp
  files are swept at mount.
- Offsets are PRESERVED (Kafka's contract): compaction punches holes in
  the offset sequence, it never renumbers.  Consumer cursors, committed
  offsets and the replica's offset-identical mirroring all survive.
- A TOMBSTONE (null-value record, segment attrs bit 1) deletes its key:
  it survives compaction long enough for slow readers to observe the
  delete, then is dropped once older than ``grace_ms`` against the
  log's NEWEST record timestamp — record-time, not wall-clock, so the
  same log compacts to the same bytes anywhere (the determinism rule
  the chaos schedules already follow).
- Triggering is by DIRTY RATIO: bytes appended since the last clean
  pass over total sealed bytes, Kafka's ``min.cleanable.dirty.ratio``.

Unkeyed records are never compacted away — with no key there is no
"latest per key", and silently dropping them would turn a mis-keyed
producer into data loss.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
from typing import Dict, Iterable, Optional, Tuple

from ..chaos import faults as chaos
from ..obs import metrics as obs_metrics
from . import segment as seg
from .segment import SegmentWriter

compaction_runs = obs_metrics.default_registry.counter(
    "iotml_store_compaction_runs_total",
    "segment compaction passes completed")
compaction_reclaimed = obs_metrics.default_registry.counter(
    "iotml_store_compaction_reclaimed_bytes",
    "bytes reclaimed by key-based compaction (dirty -> clean)")
compaction_removed = obs_metrics.default_registry.counter(
    "iotml_store_compaction_records_removed_total",
    "records removed by compaction (shadowed values + expired tombstones)")
compaction_seconds = obs_metrics.default_registry.histogram(
    "iotml_store_compaction_seconds", "one full compaction pass over a log")
compaction_errors = obs_metrics.default_registry.counter(
    "iotml_store_compaction_errors_total",
    "background compaction passes that failed (thread survives, retries "
    "next interval)")

#: suffix of the rewrite tmp file; never a valid segment name (the
#: recovery listing matches ``*.log`` exactly) and swept at mount.
CLEANED_SUFFIX = ".cleaned"


# ------------------------------------------------------- the ONE decision
def latest_offsets(records: Iterable[tuple]) -> Dict[bytes, int]:
    """{key: offset of its newest record} over ``(offset, key, value,
    ts, headers)`` tuples in offset order.  Unkeyed records never enter
    the map (they are unconditionally kept)."""
    latest: Dict[bytes, int] = {}
    for off, key, _value, _ts, _headers in records:
        if key is not None:
            latest[key] = off
    return latest


def keep(record: tuple, latest: Dict[bytes, int], newest_ts: int,
         grace_ms: Optional[int]) -> bool:
    """The keep/discard rule — shared by the segment compactor and the
    consumer-offsets file so there is exactly one compaction semantics:

    - unkeyed records are kept;
    - a keyed record survives iff it IS its key's latest;
    - a tombstone (value None), even when latest, is dropped once its
      timestamp is more than ``grace_ms`` behind the log's newest
      record timestamp (``grace_ms=None`` keeps tombstones forever).
    """
    off, key, value, ts, _headers = record
    if key is None:
        return True
    if latest.get(key) != off:
        return False
    if value is None and grace_ms is not None and newest_ts - ts > grace_ms:
        return False
    return True


@dataclasses.dataclass
class CompactionStats:
    segments_rewritten: int = 0
    records_removed: int = 0
    bytes_reclaimed: int = 0

    def merged(self, other: "CompactionStats") -> "CompactionStats":
        return CompactionStats(
            self.segments_rewritten + other.segments_rewritten,
            self.records_removed + other.records_removed,
            self.bytes_reclaimed + other.bytes_reclaimed)


# ------------------------------------------------------ segment compactor
def _scan_frames(path: str):
    """(frame_bytes, (offset, key, value, ts, headers)) per valid frame.
    Raw bytes ride along so survivors are copied verbatim — same CRC,
    same byte form, which is what makes compacted reads byte-stable."""
    data = seg.read_file(path)
    for pos, end, off, key, value, ts, headers in seg.scan_records(data):
        yield data[pos:end], (off, key, value, ts, headers)


def compact_log(slog, grace_ms: Optional[int] = None,
                lock=None) -> CompactionStats:
    """One full compaction pass over a SegmentedLog's sealed segments.

    ``lock`` (the broker lock) is held only around each atomic swap and
    the segment-list update — the expensive part (scanning every
    segment, rewriting dirty ones to ``.cleaned`` tmps with fsync) runs
    WITHOUT it, so a multi-hundred-MB pass never stalls produce/fetch.
    That is safe against concurrent appends because the keep/discard
    decision is conservative in exactly one direction: a record is only
    removed when its newer shadow existed at scan time, and shadows
    never un-exist — appends during the pass can only make the kept set
    slightly stale (extra survivors), never lose a latest record.  A
    segment deleted by concurrent retention mid-pass is skipped (its
    rewrite tmp discarded).  ``lock=None`` (tests driving a bare log)
    degrades to lock-free single-threaded behavior.

    Crash safety: the swap of each segment is one atomic ``os.replace``.
    Dying before it leaves a stale ``.cleaned`` tmp (swept at mount);
    dying between segments leaves a prefix of segments compacted — every
    key's latest record is still present (compaction only removes
    records whose newer shadow exists elsewhere in the log), so a
    remount serves the same latest-per-key table.
    """
    t0 = time.perf_counter()
    stats = CompactionStats()
    lock = lock if lock is not None else contextlib.nullcontext()
    with lock:
        segments = list(slog._segments)
    sealed = segments[:-1]
    if not sealed:
        return stats
    # the offset map spans the WHOLE log (active segment included): a key
    # rewritten in the active segment makes its sealed copies dead.  A
    # torn in-flight frame at the active tail just stops that scan early
    # — conservative (fewer shadows known -> more records kept).
    latest: Dict[bytes, int] = {}
    newest_ts = -1
    for s in segments:
        try:
            frames = list(_scan_frames(s.path))
        except FileNotFoundError:
            continue  # retention deleted it mid-pass
        for _frame, rec in frames:
            off, key, _v, ts, _h = rec
            if key is not None:
                latest[key] = off
            if ts > newest_ts:
                newest_ts = ts
    # make the shadow map's active-tail evidence DURABLE before any
    # destructive swap: the scan above reads flushed-but-unfsynced
    # appends, and a shadow torn off by a power loss must not have
    # already erased its sealed (fsynced) victim — that would turn the
    # bounded-recent-loss fsync=interval contract into old-durable-data
    # loss.  One fsync per pass; under the lock so a concurrent roll
    # cannot swap the writer mid-sync.
    with lock:
        w = slog._writer
        if w is not None:
            w.sync()
    for i, s in enumerate(sealed):
        kept_frames = []
        removed = 0
        try:
            frames = list(_scan_frames(s.path))
        except FileNotFoundError:
            continue
        for frame, rec in frames:
            if keep(rec, latest, newest_ts, grace_ms):
                kept_frames.append(frame)
            else:
                removed += 1
        if not removed:
            continue
        tmp = s.path + CLEANED_SUFFIX
        if os.path.exists(tmp):
            os.remove(tmp)  # stale leftover of a killed pass
        w = SegmentWriter(tmp, fsync=slog.policy.fsync)
        for frame in kept_frames:
            w.write_blob(frame)
        w.close(sync=slog.policy.fsync != "never")
        # the chaos kill point: a scheduled error here simulates dying
        # between the durable rewrite and its publication — the .cleaned
        # tmp exists, the live segment is untouched
        chaos.point("store.compact_swap")
        with lock:
            if s not in slog._segments:
                os.remove(tmp)  # retention won the race; nothing to swap
                continue
            old_size = s.size
            if not kept_frames and i > 0:
                # fully-dead non-head segment: drop it outright (the same
                # shape mount-time recovery gives an empty sealed
                # segment).  The HEAD segment is kept even when empty so
                # base_offset — and with it every consumer's out-of-range
                # contract — is compaction-invariant.
                os.remove(tmp)
                os.remove(s.path)
                slog._remove_sidecars(s.base_offset)
                new = None
            else:
                os.replace(tmp, s.path)
                slog._remove_sidecars(s.base_offset)
                new = slog._scan_segment(s.base_offset, s.path)
                if not kept_frames:
                    # empty head segment: preserve the roll invariant so
                    # the next segment's records stay reachable
                    new.next_offset = s.next_offset
            # publish the swap into the live segment list IN the same
            # lock hold, so no reader ever pairs new file bytes with the
            # old segment's metadata
            segs = list(slog._segments)
            idx = segs.index(s)
            if new is None:
                segs.pop(idx)
            else:
                segs[idx] = new
            slog._segments = segs
            slog._total_bytes = sum(x.size for x in segs)
        stats.segments_rewritten += 1
        stats.records_removed += removed
        stats.bytes_reclaimed += old_size - (new.size if new else 0)
    with lock:
        if stats.segments_rewritten:
            slog._persist_sidecars()
            slog._update_size_gauge()
            compaction_reclaimed.inc(stats.bytes_reclaimed)
            compaction_removed.inc(stats.records_removed)
        slog._clean_through = sealed[-1].next_offset
    compaction_runs.inc()
    compaction_seconds.observe(time.perf_counter() - t0)
    return stats


def dirty_ratio(slog) -> float:
    """Sealed bytes appended since the last clean pass over total sealed
    bytes — 0.0 for a log with no sealed segments or nothing new."""
    sealed = slog._segments[:-1]
    if not sealed:
        return 0.0
    total = sum(s.size for s in sealed)
    if not total:
        return 0.0
    clean_through = getattr(slog, "_clean_through", slog.base_offset)
    dirty = sum(s.size for s in sealed if s.next_offset > clean_through)
    return dirty / total


def sweep_cleaned(dir: str) -> int:
    """Remove leftover ``.cleaned`` rewrite tmps (a compaction pass died
    before its swap).  Called by SegmentedLog recovery; returns count."""
    n = 0
    for name in os.listdir(dir):
        if name.endswith(CLEANED_SUFFIX):
            os.remove(os.path.join(dir, name))
            n += 1
    return n


# --------------------------------------------------- background compactor
class StoreCompactor:
    """Background dirty-ratio-driven compaction for one broker.

    Periodically calls ``broker.run_compaction()`` (which takes the
    broker lock per partition and applies the dirty-ratio gate).  Owned
    thread follows the R8 supervised-thread discipline; ``run_once`` is
    the deterministic entry tests and drills drive directly."""

    def __init__(self, broker, interval_s: float = 5.0):
        self.broker = broker
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_once(self) -> Dict[Tuple[str, int], CompactionStats]:
        return self.broker.run_compaction()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except (OSError, RuntimeError, ValueError):
                # a transient pass failure (ENOSPC while writing a
                # .cleaned tmp — disk pressure is exactly when
                # compaction matters — or a mid-pass remount) must not
                # kill the thread: count it, retry next interval
                compaction_errors.inc()

    def start(self) -> "StoreCompactor":
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self._loop, daemon=True, name="iotml-store-compactor"))
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
